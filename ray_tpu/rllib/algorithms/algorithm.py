"""Algorithm: the top-level train loop object.

Parity: `rllib/algorithms/algorithm.py` — `train()` returns a result dict,
`save()/restore()` checkpoint the component tree (reference Checkpointable
mixin), `evaluate()` runs greedy episodes, and the object is Tune-trainable
via `as_trainable()`.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Optional

import jax
import numpy as np

from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.rl_module import ModuleSpec, spec_from_env
from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup
from ray_tpu.rllib.env.envs import make_env


class Algorithm:
    learner_cls = None       # set by subclasses
    needs_epsilon = False    # DQN-style exploration

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self._multi_agent = bool(config.policies)
        if self._multi_agent:
            self._init_multi_agent()
            self.iteration = 0
            self._timesteps = 0
            return
        probe = make_env(config.env, **config.env_kwargs)
        self.module_spec = self._module_spec(probe)
        mesh = None
        if config.mesh_devices:
            devs = jax.devices()[:config.mesh_devices]
            mesh = jax.sharding.Mesh(np.array(devs), ("dp",))
        self.learner = self._build_learner(mesh)
        self.env_runner_group = EnvRunnerGroup(
            config.env, self.module_spec,
            num_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs_per_env_runner,
            seed=config.seed,
            epsilon=0.0 if self.needs_epsilon else None,
            env_kwargs=config.env_kwargs,
            env_to_module_connector=config.env_to_module_connector,
            module_to_env_connector=config.module_to_env_connector)
        self.env_runner_group.sync_weights(self.learner.get_weights())
        self.iteration = 0
        self._timesteps = 0

    def _init_multi_agent(self) -> None:
        """Per-policy learners + a policy-batched multi-agent runner
        (reference MultiRLModule + MultiAgentEnvRunner)."""
        import dataclasses as _dc

        from ray_tpu.rllib.env.multi_agent import (MultiAgentEnvRunner,
                                                   spec_for_agent)

        config = self.config
        if not hasattr(self, "_multi_agent_training_step"):
            raise NotImplementedError(
                f"multi-agent training is implemented for PPO; "
                f"{type(self).__name__} does not support "
                f"config.multi_agent() yet")
        env_factory = (config.env if callable(config.env)
                       else lambda: make_env(config.env,
                                             **config.env_kwargs))
        probe = env_factory()
        mapping_fn = config.policy_mapping_fn
        if mapping_fn is None:
            if len(config.policies) == 1:
                only = next(iter(config.policies))
                mapping_fn = lambda agent_id: only  # parameter sharing
            else:
                raise ValueError("policy_mapping_fn is required with "
                                 "more than one policy")
        self.policy_mapping_fn = mapping_fn
        self.module_specs = {}
        for pid, spec in config.policies.items():
            if spec is None:
                rep = next((a for a in probe.agents
                            if mapping_fn(a) == pid), None)
                if rep is None:
                    raise ValueError(
                        f"policy {pid!r} has spec=None but no agent maps "
                        f"to it (agents: {probe.agents}) — give it a "
                        f"ModuleSpec or fix policy_mapping_fn")
                spec = spec_for_agent(probe, rep,
                                      hiddens=tuple(config.hiddens))
            else:
                spec = _dc.replace(spec, hiddens=tuple(config.hiddens))
            self.module_specs[pid] = spec
        self.learners = {pid: self._build_learner_for(spec)
                         for pid, spec in self.module_specs.items()}
        self.ma_runner = MultiAgentEnvRunner(
            env_factory, self.module_specs, mapping_fn, seed=config.seed)
        self.ma_runner.set_weights({p: l.get_weights()
                                    for p, l in self.learners.items()})

    def _build_learner_for(self, spec):
        """Multi-agent hook: a learner for ONE policy's module spec
        (honoring config.learners(mesh_devices=...) like single-agent)."""
        mesh = None
        if self.config.mesh_devices:
            devs = jax.devices()[:self.config.mesh_devices]
            mesh = jax.sharding.Mesh(np.array(devs), ("dp",))
        saved, self.module_spec = getattr(self, "module_spec", None), spec
        try:
            return self._build_learner(mesh)
        finally:
            self.module_spec = saved

    # hooks -----------------------------------------------------------------
    def _module_spec(self, env) -> ModuleSpec:
        spec = spec_from_env(env)
        return ModuleSpec(**{**spec.__dict__, "hiddens": tuple(self.config.hiddens)})

    def _build_learner(self, mesh):
        raise NotImplementedError

    def training_step(self) -> dict:
        raise NotImplementedError

    # public API ------------------------------------------------------------
    def train(self) -> dict:
        t0 = time.time()
        metrics = self.training_step()
        self.iteration += 1
        result = {"training_iteration": self.iteration,
                  "num_env_steps_sampled_lifetime": self._timesteps,
                  "time_this_iter_s": time.time() - t0, **metrics}
        if (self.config.evaluation_interval
                and self.iteration % self.config.evaluation_interval == 0):
            result["evaluation"] = self.evaluate()
        return result

    def evaluate(self) -> dict:
        if self._multi_agent:
            self.ma_runner.set_weights({p: l.get_weights()
                                        for p, l in self.learners.items()})
            return self.ma_runner.evaluate(
                self.config.evaluation_num_episodes)
        self.env_runner_group.sync_weights(self.learner.get_weights())
        return self.env_runner_group.evaluate(self.config.evaluation_num_episodes)

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        state = {"iteration": self.iteration, "timesteps": self._timesteps}
        if self._multi_agent:
            state["learners"] = {p: l.get_state()
                                 for p, l in self.learners.items()}
        else:
            state["learner"] = self.learner.get_state()
        with open(path, "wb") as f:
            pickle.dump(state, f)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        if self._multi_agent:
            for p, st in state["learners"].items():
                self.learners[p].set_state(st)
            self.ma_runner.set_weights({p: l.get_weights()
                                        for p, l in self.learners.items()})
        else:
            self.learner.set_state(state["learner"])
            self.env_runner_group.sync_weights(self.learner.get_weights())
        self.iteration = state["iteration"]
        self._timesteps = state["timesteps"]

    def stop(self) -> None:
        if not self._multi_agent:
            self.env_runner_group.stop()

    def get_policy_weights(self, policy_id: Optional[str] = None):
        if self._multi_agent:
            if policy_id is not None:
                return self.learners[policy_id].get_weights()
            return {p: l.get_weights() for p, l in self.learners.items()}
        return self.learner.get_weights()

    # ----------------------------------------------------- off-policy helper
    def _off_policy_step(self, epsilon: float = 0.0) -> dict:
        """Shared DQN/SAC iteration: sample → replay.add → N updates.
        Bootstraps through time-limit truncation by storing the pre-reset
        successor obs and masking targets with `terminateds` only."""
        c = self.config
        self.env_runner_group.sync_weights(self.learner.get_weights())
        fragments = self.env_runner_group.sample(c.rollout_fragment_length,
                                                 epsilon=epsilon)
        ep_metrics = [f.pop("_metrics") for f in fragments]
        for f in fragments:
            T, N = f["rewards"].shape
            self.replay.add_batch(f["obs"], f["actions"], f["rewards"],
                                  f["terminateds"].astype(np.float32),
                                  f["next_obs_seq"])
            self._timesteps += T * N
        metrics = {}
        if self.replay.size >= c.num_steps_sampled_before_learning_starts:
            for _ in range(c.num_updates_per_iteration):
                metrics = self.learner.update(
                    self.replay.sample(c.train_batch_size))
        return {**metrics, **self._episode_metrics(ep_metrics)}

    @staticmethod
    def _episode_metrics(ep_metrics) -> dict:
        eps = [m for m in ep_metrics if m["episodes"]]
        if not eps:
            return {}
        return {"episode_return_mean": float(np.mean(
            [m["episode_return_mean"] for m in eps]))}

    @classmethod
    def as_trainable(cls, base_config: AlgorithmConfig):
        """Adapter so `tune.Tuner(PPO.as_trainable(cfg), param_space=...)`
        sweeps RLlib configs (reference: Algorithms are Tune Trainables).
        The returned function follows this framework's trainable protocol:
        one `config` arg, reporting via `ray_tpu.train.session.report` (which
        raises StopIteration when the scheduler stops the trial)."""

        def _train_fn(config: dict):
            from ray_tpu.train import session

            algo = cls(base_config.copy().update_from_dict(config))
            try:
                while True:
                    session.report(algo.train())
            except StopIteration:
                pass
            finally:
                algo.stop()

        return _train_fn
