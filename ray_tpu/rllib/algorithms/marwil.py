"""MARWIL: monotonic advantage re-weighted imitation learning.

Parity: `rllib/algorithms/marwil/` (Wang et al., NeurIPS 2018 — the
reference's recommended offline algorithm) — behavior cloning whose
per-sample loss is weighted by exp(beta * advantage), so better-than-
average logged behavior is imitated harder and the learned policy can
EXCEED the data-collection policy. beta=0 reduces exactly to BC.

Offline input reuses BC's pipeline (`obs`, `actions`, plus `rewards` +
episode boundaries via `dones` for the return computation); advantages
come from a jointly trained value baseline on the logged returns, with
the reference's running-average advantage normalization (`moving average
of squared advantages`, marwil_torch_learner.py) folded into the jitted
update as a batch-local estimate.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.bc import BC, BCConfig
from ray_tpu.rllib.core.learner import JaxLearner


def discounted_returns(rewards: np.ndarray, dones: np.ndarray,
                       gamma: float) -> np.ndarray:
    """Per-step discounted return-to-go within episodes (offline target
    for the value baseline)."""
    out = np.zeros_like(rewards, dtype=np.float32)
    acc = 0.0
    for t in range(len(rewards) - 1, -1, -1):
        if dones[t]:
            acc = 0.0
        acc = rewards[t] + gamma * acc
        out[t] = acc
    return out


class MARWILLearner(JaxLearner):
    def __init__(self, spec, cfg: "MARWILConfig", mesh=None):
        self.cfg = cfg
        super().__init__(spec, lr=cfg.lr, grad_clip=cfg.grad_clip,
                         seed=cfg.seed, mesh=mesh)

    def loss(self, params, batch, rng) -> Tuple[jnp.ndarray, dict]:
        c = self.cfg
        dist = self.module.dist(params, batch["obs"])
        logp = dist.log_prob(batch["actions"])
        v = self.module.value(params, batch["obs"])
        adv = batch["returns"] - v
        vf_loss = (adv ** 2).mean()
        if c.beta > 0.0:
            # exp(beta * normalized advantage), gradient-stopped: the
            # weight ranks samples, it must not be a policy gradient path
            sg_adv = jax.lax.stop_gradient(adv)
            # the normalizer must be gradient-stopped too, or w leaks a
            # path into the value tower through the policy loss
            norm = jnp.sqrt((sg_adv ** 2).mean()) + 1e-8
            w = jnp.exp(c.beta * jnp.clip(sg_adv / norm, -5.0, 5.0))
        else:
            w = jnp.ones_like(logp)  # beta=0: exact BC
        pg = -(w * logp).mean()
        total = pg + c.vf_coeff * vf_loss
        return total, {"marwil_loss": pg, "vf_loss": vf_loss,
                       "mean_weight": w.mean()}


class MARWIL(BC):
    """BC's offline pipeline (loading/scaling/minibatching inherited via
    its hooks) + logged discounted returns as an extra column."""

    offline_columns = ("obs", "actions", "rewards", "dones")

    def _post_load(self, cols: dict) -> None:
        self._extras["returns"] = discounted_returns(
            np.asarray(cols["rewards"], np.float32),
            np.asarray(cols["dones"], bool), self.config.gamma)

    def _make_learner(self, mesh):
        return MARWILLearner(self.module_spec, self.config, mesh=mesh)


class MARWILConfig(BCConfig):
    algo_class = MARWIL

    def __init__(self):
        super().__init__()
        self.beta = 1.0       # 0 = plain BC (reference default 1.0)
        self.vf_coeff = 1.0
