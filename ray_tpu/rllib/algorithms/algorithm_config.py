"""AlgorithmConfig: the fluent builder the reference uses everywhere.

Parity: `rllib/algorithms/algorithm_config.py` — `.environment()`,
`.env_runners()`, `.training()`, `.learners()`, `.evaluation()`, `.build()`.
"""

from __future__ import annotations

import copy
from typing import Any, Optional, Tuple


class AlgorithmConfig:
    algo_class = None  # set by subclasses (PPOConfig → PPO, ...)

    def __init__(self):
        # environment
        self.env: Any = "CartPole-v1"
        self.env_kwargs: dict = {}
        # env runners
        self.num_env_runners: int = 0
        self.num_envs_per_env_runner: int = 1
        self.rollout_fragment_length: int = 128
        self.env_to_module_connector = None   # factory -> ConnectorPipeline
        self.module_to_env_connector = None
        # training (shared knobs; algo subclasses add their own)
        self.lr: float = 3e-4
        self.gamma: float = 0.99
        self.grad_clip: Optional[float] = 0.5
        self.train_batch_size: int = 512
        self.hiddens: Tuple[int, ...] = (64, 64)
        self.seed: int = 0
        # learners: mesh_shape=(dp,) shards the update batch over devices
        self.mesh_devices: Optional[int] = None
        # evaluation
        self.evaluation_interval: int = 0
        self.evaluation_num_episodes: int = 5
        # multi-agent (reference config.multi_agent()): policy id ->
        # ModuleSpec (or None to derive from the env) + agent->policy map
        self.policies: Optional[dict] = None
        self.policy_mapping_fn: Optional[Any] = None

    # fluent setters — each returns self, mirroring the reference exactly
    def environment(self, env=None, *, env_config: Optional[dict] = None):
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_kwargs = env_config
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None,
                    env_to_module_connector=None,
                    module_to_env_connector=None):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        # connector FACTORIES (reference contract): each runner builds
        # its own stateful pipeline from these
        if env_to_module_connector is not None:
            self.env_to_module_connector = env_to_module_connector
        if module_to_env_connector is not None:
            self.module_to_env_connector = module_to_env_connector
        return self

    def training(self, **kwargs):
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise AttributeError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def multi_agent(self, *, policies: Optional[dict] = None,
                    policy_mapping_fn=None):
        """Enable multi-agent training: `policies` maps policy ids to
        ModuleSpecs (None values derive the spec from the env's per-agent
        spaces); `policy_mapping_fn(agent_id) -> policy_id` (default:
        one shared policy when a single policy is given, else identity
        prefix matching is the caller's job)."""
        if policies is not None:
            self.policies = policies
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def learners(self, *, mesh_devices: Optional[int] = None):
        """TPU-first replacement for the reference's num_learners: instead of
        N DDP learner actors, one learner whose update is sharded over an
        N-device mesh dp axis (XLA psum over ICI)."""
        if mesh_devices is not None:
            self.mesh_devices = mesh_devices
        return self

    def evaluation(self, *, evaluation_interval: Optional[int] = None,
                   evaluation_num_episodes: Optional[int] = None):
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_num_episodes is not None:
            self.evaluation_num_episodes = evaluation_num_episodes
        return self

    def debugging(self, *, seed: Optional[int] = None):
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build(self) -> "Algorithm":  # noqa: F821
        if self.algo_class is None:
            raise ValueError("use an algorithm-specific config (PPOConfig, ...)")
        return self.algo_class(self.copy())

    # Tune integration: dict-style access for param_space sweeps
    def update_from_dict(self, d: dict) -> "AlgorithmConfig":
        for k, v in d.items():
            if not hasattr(self, k):
                raise AttributeError(f"unknown config key {k!r}")
            setattr(self, k, v)
        return self
