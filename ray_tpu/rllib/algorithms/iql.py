"""IQL: implicit Q-learning for offline RL.

Parity: `rllib/algorithms/iql/` — offline RL WITHOUT querying Q on
out-of-distribution actions (the CQL failure mode is avoided rather than
penalized): a state-value net V is fit to expectile tau of Q (upper
expectile ~ max over DATASET actions), Q regresses to r + gamma*V(s'),
and the policy is extracted by advantage-weighted regression
exp(beta * (Q - V)) on logged actions. Rides the BC/MARWIL/CQL offline
seam; the V head is a small extra pytree owned by the learner.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.bc import BC, BCConfig
from ray_tpu.rllib.algorithms.cql import CQL
from ray_tpu.rllib.core.learner import JaxLearner


def _mlp_init(key, sizes):
    params = []
    for i, (m, n) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        params.append({"w": jax.random.normal(sub, (m, n))
                       * jnp.sqrt(2.0 / m), "b": jnp.zeros(n)})
    return params


def _mlp_apply(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x


class IQLLearner(JaxLearner):
    """Actor (squashed Gaussian) + twin Q from the shared module; V net
    owned here. Three optimized parts per update."""

    def __init__(self, spec, cfg: "IQLConfig", mesh=None):
        self.cfg = cfg
        super().__init__(spec, lr=cfg.lr, grad_clip=cfg.grad_clip,
                         seed=cfg.seed, mesh=mesh)
        self.target_params = jax.tree.map(jnp.asarray, self.params)
        key = jax.random.key(cfg.seed + 101)
        self.v_params = _mlp_init(
            key, [spec.obs_dim, *cfg.hiddens, 1])
        self.v_opt = optax.adam(cfg.lr)
        self.v_opt_state = self.v_opt.init(self.v_params)

        tau, gamma, beta = cfg.expectile_tau, cfg.gamma, cfg.awr_beta

        @jax.jit
        def _v_update(v_params, v_opt_state, target_q_params, obs, acts):
            q1, q2 = self.module.q_values(target_q_params, obs, acts)
            q = jax.lax.stop_gradient(jnp.minimum(q1, q2))

            def v_loss(vp):
                v = _mlp_apply(vp, obs)[:, 0]
                diff = q - v
                w = jnp.where(diff > 0, tau, 1 - tau)
                return (w * diff ** 2).mean(), v

            (loss, v), g = jax.value_and_grad(v_loss, has_aux=True)(v_params)
            upd, v_opt_state = self.v_opt.update(g, v_opt_state)
            return optax.apply_updates(v_params, upd), v_opt_state, loss

        self._v_update = _v_update
        self._beta = beta
        self._gamma = gamma

    def loss(self, params, batch, rng) -> Tuple[jnp.ndarray, dict]:
        c = self.cfg
        # critic: Q(s, a_data) -> r + gamma (1-d) V(s')   (no policy
        # actions anywhere — the IQL point)
        v_next = jax.lax.stop_gradient(
            _mlp_apply(batch["_v_params"], batch["next_obs"])[:, 0])
        y = batch["rewards"] + c.gamma * (1 - batch["dones"]) * v_next
        q1, q2 = self.module.q_values(params, batch["obs"],
                                      batch["actions"])
        critic_loss = ((q1 - y) ** 2).mean() + ((q2 - y) ** 2).mean()
        # actor: advantage-weighted regression on LOGGED actions
        v = jax.lax.stop_gradient(
            _mlp_apply(batch["_v_params"], batch["obs"])[:, 0])
        q1_t, q2_t = self.module.q_values(batch["_target"], batch["obs"],
                                          batch["actions"])
        adv = jax.lax.stop_gradient(jnp.minimum(q1_t, q2_t) - v)
        w = jnp.exp(jnp.clip(self._beta * adv, -5.0, 5.0))
        dist = self.module.dist(params, batch["obs"])
        logp = dist.log_prob(batch["actions"])
        actor_loss = -(w * logp).mean()
        total = critic_loss + actor_loss
        return total, {"critic_loss": critic_loss, "actor_loss": actor_loss,
                       "adv_mean": adv.mean(), "v_mean": v.mean()}

    def update(self, batch) -> dict:
        batch = dict(batch)
        obs = jnp.asarray(batch["obs"])
        acts = jnp.asarray(batch["actions"])
        self.v_params, self.v_opt_state, v_loss = self._v_update(
            self.v_params, self.v_opt_state, self.target_params, obs, acts)
        batch["_v_params"] = self.v_params
        batch["_target"] = self.target_params
        out = super().update(batch)
        tau = self.cfg.polyak_tau
        self.target_params = jax.tree.map(
            lambda t, p: (1 - tau) * t + tau * p,
            self.target_params, self.params)
        out["v_loss"] = float(v_loss)
        return out

    def get_state(self) -> dict:
        s = super().get_state()
        s["target_params"] = jax.tree.map(np.asarray, self.target_params)
        s["v_params"] = jax.tree.map(np.asarray, self.v_params)
        return s

    def set_state(self, state) -> None:
        super().set_state(state)
        self.target_params = jax.tree.map(jnp.asarray,
                                          state["target_params"])
        self.v_params = jax.tree.map(jnp.asarray, state["v_params"])


class IQL(CQL):
    """Same offline columns/spec as CQL (continuous, squashed actor +
    twin Q); only the learner differs."""

    def _make_learner(self, mesh):
        return IQLLearner(self.module_spec, self.config, mesh=mesh)


class IQLConfig(BCConfig):
    algo_class = IQL

    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.train_batch_size = 256
        self.num_updates_per_iteration = 32
        self.expectile_tau = 0.8
        self.awr_beta = 3.0
        self.polyak_tau = 0.005
