"""BC: behavior cloning from offline data.

Parity: `rllib/algorithms/bc/` (+ the offline-data pipeline in
`rllib/offline/`) — supervised imitation of logged actions. Offline input:
a `ray_tpu.data.Dataset` (columns `obs`, `actions`) or a dict of arrays;
the dataset path streams batches through the data library's executor.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import JaxLearner


class BCLearner(JaxLearner):
    def __init__(self, spec, cfg: "BCConfig", mesh=None):
        self.cfg = cfg
        super().__init__(spec, lr=cfg.lr, grad_clip=cfg.grad_clip,
                         seed=cfg.seed, mesh=mesh)

    def loss(self, params, batch, rng) -> Tuple[jnp.ndarray, dict]:
        dist = self.module.dist(params, batch["obs"])
        logp = dist.log_prob(batch["actions"])
        nll = -logp.mean()
        return nll, {"bc_nll": nll}


class BC(Algorithm):
    def _build_learner(self, mesh):
        c = self.config
        data = c.offline_data
        if data is None:
            raise ValueError("BCConfig.offline(offline_data=...) is required")
        if isinstance(data, dict):
            self._obs = np.asarray(data["obs"], np.float32)
            self._acts = np.asarray(data["actions"])
        else:  # ray_tpu.data.Dataset
            obs, acts = [], []
            for b in data.iter_batches(batch_size=4096):
                obs.append(np.asarray(b["obs"], np.float32))
                acts.append(np.asarray(b["actions"]))
            if not obs:
                raise ValueError("offline dataset is empty")
            self._obs = np.concatenate(obs)
            self._acts = np.concatenate(acts)
        if len(self._obs) == 0:
            raise ValueError("offline dataset is empty")
        if not self.module_spec.discrete:
            # logged actions are in ENV space; the module (and the env
            # runner, which multiplies by action_scale on the way out)
            # work in module space [-1, 1]
            self._acts = self._acts / self.module_spec.action_scale
        self._rng = np.random.default_rng(c.seed)
        return BCLearner(self.module_spec, c, mesh=mesh)

    def training_step(self) -> dict:
        c = self.config
        n = len(self._obs)
        bs = min(c.train_batch_size, n)
        metrics = {}
        for _ in range(c.num_updates_per_iteration):
            idx = self._rng.integers(0, n, size=bs)
            metrics = self.learner.update({"obs": self._obs[idx],
                                           "actions": self._acts[idx]})
        self._timesteps += c.num_updates_per_iteration * bs
        return metrics


class BCConfig(AlgorithmConfig):
    algo_class = BC

    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.train_batch_size = 256
        self.num_updates_per_iteration = 64
        self.offline_data = None

    def offline(self, *, offline_data=None):
        """Reference parity: `.offline_data(input_=...)`."""
        if offline_data is not None:
            self.offline_data = offline_data
        return self

    def __deepcopy__(self, memo):
        # build()/as_trainable deepcopy configs; cloning gigabytes of
        # offline arrays per trial would double peak RAM — share them
        import copy

        cls = self.__class__
        new = cls.__new__(cls)
        memo[id(self)] = new
        for k, v in self.__dict__.items():
            if k == "offline_data":
                new.offline_data = v
            else:
                setattr(new, k, copy.deepcopy(v, memo))
        return new
