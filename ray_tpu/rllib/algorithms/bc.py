"""BC: behavior cloning from offline data.

Parity: `rllib/algorithms/bc/` (+ the offline-data pipeline in
`rllib/offline/`) — supervised imitation of logged actions. Offline input:
a `ray_tpu.data.Dataset` (columns `obs`, `actions`) or a dict of arrays;
the dataset path streams batches through the data library's executor.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import JaxLearner


class BCLearner(JaxLearner):
    def __init__(self, spec, cfg: "BCConfig", mesh=None):
        self.cfg = cfg
        super().__init__(spec, lr=cfg.lr, grad_clip=cfg.grad_clip,
                         seed=cfg.seed, mesh=mesh)

    def loss(self, params, batch, rng) -> Tuple[jnp.ndarray, dict]:
        dist = self.module.dist(params, batch["obs"])
        logp = dist.log_prob(batch["actions"])
        nll = -logp.mean()
        return nll, {"bc_nll": nll}


class BC(Algorithm):
    # offline-pipeline hooks (MARWIL etc. extend, never re-implement):
    # the columns ingested, a post-load step, and the learner factory
    offline_columns = ("obs", "actions")

    def _load_offline(self, data) -> dict:
        if isinstance(data, dict):
            cols = {k: np.asarray(data[k]) for k in self.offline_columns}
        else:  # ray_tpu.data.Dataset
            acc = {k: [] for k in self.offline_columns}
            for b in data.iter_batches(batch_size=4096):
                for k in acc:
                    acc[k].append(np.asarray(b[k]))
            if not acc["obs"]:
                raise ValueError("offline dataset is empty")
            cols = {k: np.concatenate(v) for k, v in acc.items()}
        if len(cols["obs"]) == 0:
            raise ValueError("offline dataset is empty")
        return cols

    def _post_load(self, cols: dict) -> None:
        """Subclass hook: derive extra per-sample training columns into
        self._extras (sampled alongside obs/actions each minibatch)."""

    def _make_learner(self, mesh):
        return BCLearner(self.module_spec, self.config, mesh=mesh)

    def _build_learner(self, mesh):
        c = self.config
        data = c.offline_data
        if data is None:
            raise ValueError(
                f"{type(c).__name__}.offline(offline_data=...) is required")
        cols = self._load_offline(data)
        self._obs = cols["obs"].astype(np.float32)
        self._acts = cols["actions"]
        if not self.module_spec.discrete:
            # logged actions are in ENV space; the module (and the env
            # runner, which multiplies by action_scale on the way out)
            # work in module space [-1, 1]
            self._acts = self._acts / self.module_spec.action_scale
        self._extras: dict = {}
        self._post_load(cols)
        self._rng = np.random.default_rng(c.seed)
        return self._make_learner(mesh)

    def training_step(self) -> dict:
        c = self.config
        n = len(self._obs)
        bs = min(c.train_batch_size, n)
        metrics = {}
        for _ in range(c.num_updates_per_iteration):
            idx = self._rng.integers(0, n, size=bs)
            batch = {"obs": self._obs[idx], "actions": self._acts[idx],
                     **{k: v[idx] for k, v in self._extras.items()}}
            metrics = self.learner.update(batch)
        self._timesteps += c.num_updates_per_iteration * bs
        return metrics


class BCConfig(AlgorithmConfig):
    algo_class = BC

    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.train_batch_size = 256
        self.num_updates_per_iteration = 64
        self.offline_data = None

    def offline(self, *, offline_data=None):
        """Reference parity: `.offline_data(input_=...)`."""
        if offline_data is not None:
            self.offline_data = offline_data
        return self

    def __deepcopy__(self, memo):
        # build()/as_trainable deepcopy configs; cloning gigabytes of
        # offline arrays per trial would double peak RAM — share them
        import copy

        cls = self.__class__
        new = cls.__new__(cls)
        memo[id(self)] = new
        for k, v in self.__dict__.items():
            if k == "offline_data":
                new.offline_data = v
            else:
                setattr(new, k, copy.deepcopy(v, memo))
        return new
