"""IMPALA: asynchronous actor-learner training with V-trace correction.

Parity: `rllib/algorithms/impala/` — the architecture (decoupled rollout
actors feeding a central learner through aggregation actors, with
off-policy V-trace importance correction for the policy lag) and the loss
math of the reference's torch learner
(`rllib/algorithms/impala/torch/impala_torch_learner.py`), re-done the
XLA way: V-trace is one `lax.scan` jitted alongside the policy update.

Async pipeline shape (reference `impala.py` training_step +
`aggregator_actor.py`):

    env-runner actors --sample.remote()--> fragment refs
        --add.remote(ref)--> aggregation actor (concat to train batches)
        --driver--> jitted V-trace learner update
        --set_weights on the runner that just reported (per-runner async)

Runners keep sampling with slightly stale weights — V-trace's clipped
rho/c weights are exactly the correction for that staleness, which is why
throughput beats PPO's strict on-policy collect-then-train barrier.
"""

from __future__ import annotations

import time
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import JaxLearner


def vtrace(behavior_logp, target_logp, rewards, values, dones, last_values,
           gamma, rho_bar=1.0, c_bar=1.0):
    """V-trace targets and pg advantages, [T, N] time-major
    (reference vtrace_torch.py / the IMPALA paper recursion)."""
    rhos = jnp.exp(target_logp - behavior_logp)
    clipped_rho = jnp.minimum(rho_bar, rhos)
    cs = jnp.minimum(c_bar, rhos)
    not_done = 1.0 - dones
    next_values = jnp.concatenate([values[1:], last_values[None]], axis=0)
    deltas = clipped_rho * (rewards + gamma * not_done * next_values - values)

    def step(carry, xs):
        acc = carry
        delta, c, nd = xs
        acc = delta + gamma * nd * c * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        step, jnp.zeros_like(last_values), (deltas, cs, not_done),
        reverse=True)
    vs = vs_minus_v + values
    next_vs = jnp.concatenate([vs[1:], last_values[None]], axis=0)
    pg_adv = clipped_rho * (rewards + gamma * not_done * next_vs - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class ImpalaLearner(JaxLearner):
    def __init__(self, spec, cfg: "IMPALAConfig", mesh=None):
        self.cfg = cfg
        super().__init__(spec, lr=cfg.lr, grad_clip=cfg.grad_clip,
                         seed=cfg.seed, mesh=mesh)

    def loss(self, params, batch, rng):
        c = self.cfg
        # [T, N] time-major leaves
        obs = batch["obs"]
        T, N = obs.shape[:2]
        flat_obs = obs.reshape((T * N,) + obs.shape[2:])
        dist = self.module.dist(params, flat_obs)
        target_logp = dist.log_prob(
            batch["actions"].reshape((T * N,) + batch["actions"].shape[2:])
        ).reshape(T, N)
        v = self.module.value(params, flat_obs).reshape(T, N)
        vs, pg_adv = vtrace(batch["logp"], target_logp, batch["rewards"],
                            v, batch["dones"], batch["last_values"],
                            c.gamma, c.vtrace_rho_bar, c.vtrace_c_bar)
        pg_loss = -(target_logp * pg_adv).mean()
        vf_loss = 0.5 * ((v - vs) ** 2).mean()
        entropy = dist.entropy().mean()
        total = (pg_loss + c.vf_loss_coeff * vf_loss
                 - c.entropy_coeff * entropy)
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy}


@ray_tpu.remote
class _Aggregator:
    """Aggregation actor (reference aggregator_actor.py): concatenates
    runner fragments into learner-sized batches off the driver thread."""

    def __init__(self, fragments_per_batch: int):
        self.k = fragments_per_batch
        self.buf: List[dict] = []
        self.metrics: List[dict] = []

    def add(self, fragment: dict):
        self.metrics.append(fragment.pop("_metrics", {}))
        self.buf.append(fragment)
        if len(self.buf) < self.k:
            return None
        frags, self.buf = self.buf[:self.k], self.buf[self.k:]
        out = {k: np.concatenate([f[k] for f in frags], axis=1)
               for k in frags[0] if k not in ("last_values", "next_obs")}
        out["last_values"] = np.concatenate([f["last_values"] for f in frags])
        out["_metrics"], self.metrics = self.metrics, []
        return out


class IMPALA(Algorithm):
    def _build_learner(self, mesh):
        return ImpalaLearner(self.module_spec, self.config, mesh=mesh)

    def _setup_async(self):
        c = self.config
        self._agg = _Aggregator.remote(max(1, c.fragments_per_batch))
        # one outstanding sample per runner, always in flight
        self._inflight: Dict[object, int] = {}
        for i, a in enumerate(self.env_runner_group.actors):
            self._inflight[a.sample.remote(c.rollout_fragment_length)] = i

    def training_step(self) -> dict:
        c = self.config
        if self.env_runner_group.local is not None:
            return self._training_step_local()
        if not hasattr(self, "_agg"):
            self._setup_async()
        metrics: Dict[str, float] = {}
        updates = 0
        deadline = time.monotonic() + c.min_time_s_per_iteration
        weights = self.learner.get_weights()
        while updates < c.updates_per_iteration or time.monotonic() < deadline:
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=60)
            if not ready:
                break
            ref = ready[0]
            i = self._inflight.pop(ref)
            actor = self.env_runner_group.actors[i]
            batch_ref = self._agg.add.remote(ref)
            # per-runner async continuation: fresh weights, keep sampling
            actor.set_weights.remote(weights)
            self._inflight[actor.sample.remote(
                c.rollout_fragment_length)] = i
            batch = ray_tpu.get(batch_ref, timeout=60)
            if batch is None:
                continue
            ep_metrics = batch.pop("_metrics", [])
            batch = self._prepare(batch)
            metrics = self.learner.update(batch)
            metrics.update(self._episode_metrics(ep_metrics))
            weights = self.learner.get_weights()
            updates += 1
            self._timesteps += int(batch["obs"].shape[0]
                                   * batch["obs"].shape[1])
        metrics["num_learner_updates"] = updates
        return metrics

    def _prepare(self, batch: dict) -> dict:
        c = self.config
        boot = batch["truncateds"] & ~batch["terminateds"]
        rewards = batch["rewards"] + c.gamma * batch["final_values"] * boot
        return {"obs": batch["obs"], "actions": batch["actions"],
                "logp": batch["logp"], "rewards": rewards,
                "dones": batch["dones"].astype(np.float32),
                "last_values": batch["last_values"]}

    def _training_step_local(self) -> dict:
        """num_env_runners=0 debug mode: synchronous, still V-trace."""
        c = self.config
        self.env_runner_group.sync_weights(self.learner.get_weights())
        frags = self.env_runner_group.sample(c.rollout_fragment_length)
        ep_metrics = [f.pop("_metrics") for f in frags]
        cat = {k: np.concatenate([f[k] for f in frags], axis=1)
               for k in frags[0] if k not in ("last_values", "next_obs")}
        cat["last_values"] = np.concatenate([f["last_values"] for f in frags])
        metrics = self.learner.update(self._prepare(cat))
        self._timesteps += int(cat["obs"].shape[0] * cat["obs"].shape[1])
        metrics.update(self._episode_metrics(ep_metrics))
        return metrics

    def stop(self) -> None:
        if hasattr(self, "_agg"):
            try:
                ray_tpu.kill(self._agg)
            except Exception:
                pass
        super().stop()


class IMPALAConfig(AlgorithmConfig):
    algo_class = IMPALA

    def __init__(self):
        super().__init__()
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.vtrace_rho_bar = 1.0
        self.vtrace_c_bar = 1.0
        self.fragments_per_batch = 2
        self.updates_per_iteration = 8
        self.min_time_s_per_iteration = 0.0
