"""SAC: soft actor-critic with twin Q, target nets, auto-tuned temperature.

Parity: `rllib/algorithms/sac/` (sac.py, torch learner) — squashed-Gaussian
policy, twin Q with min-target, polyak-averaged target networks, entropy
temperature auto-tuned toward -|A| (the reference's default target entropy).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import JaxLearner
from ray_tpu.rllib.core.replay import ReplayBuffer
from ray_tpu.rllib.core.rl_module import ModuleSpec, spec_from_env


class SACLearner(JaxLearner):
    def __init__(self, spec, cfg: "SACConfig", mesh=None):
        self.cfg = cfg
        super().__init__(spec, lr=cfg.lr, grad_clip=cfg.grad_clip,
                         seed=cfg.seed, mesh=mesh)
        self.target_params = jax.tree.map(jnp.asarray, self.params)
        self.log_alpha = jnp.zeros(())
        self.alpha_opt = optax.adam(cfg.lr)
        self.alpha_opt_state = self.alpha_opt.init(self.log_alpha)
        self.target_entropy = -float(spec.action_dim)

        @jax.jit
        def _alpha_update(log_alpha, opt_state, logp):
            def alpha_loss(la):
                return -(jnp.exp(la) * jax.lax.stop_gradient(
                    logp + self.target_entropy)).mean()

            g = jax.grad(alpha_loss)(log_alpha)
            upd, opt_state = self.alpha_opt.update(g, opt_state)
            return optax.apply_updates(log_alpha, upd), opt_state

        self._alpha_update = _alpha_update

    def loss(self, params, batch, rng) -> Tuple[jnp.ndarray, dict]:
        c = self.cfg
        alpha = jnp.exp(batch["_log_alpha"])
        k1, k2 = jax.random.split(rng)
        # critic loss: y = r + γ(1-d)(min Q_targ(s', a') - α logπ(a'|s'))
        next_dist = self.module.dist(params, batch["next_obs"])
        next_a, next_logp = next_dist.sample_with_logp(k1)
        q1_t, q2_t = self.module.q_values(batch["_target"], batch["next_obs"],
                                          next_a)
        y = batch["rewards"] + c.gamma * (1 - batch["dones"]) * \
            jax.lax.stop_gradient(jnp.minimum(q1_t, q2_t) - alpha * next_logp)
        q1, q2 = self.module.q_values(params, batch["obs"], batch["actions"])
        critic_loss = ((q1 - y) ** 2).mean() + ((q2 - y) ** 2).mean()
        # actor loss: α logπ(a|s) - min Q(s, a), through the reparam sample
        dist = self.module.dist(params, batch["obs"])
        a, logp = dist.sample_with_logp(k2)
        q1_pi, q2_pi = self.module.q_values(
            jax.lax.stop_gradient(params), batch["obs"], a)
        actor_loss = (alpha * logp - jnp.minimum(q1_pi, q2_pi)).mean()
        total = critic_loss + actor_loss
        return total, {"critic_loss": critic_loss, "actor_loss": actor_loss,
                       "alpha": alpha, "logp_mean": logp.mean()}

    def update(self, batch) -> dict:
        batch = dict(batch)
        batch["_target"] = self.target_params
        batch["_log_alpha"] = self.log_alpha
        out = super().update(batch)
        # polyak target update + temperature step
        tau = self.cfg.tau
        self.target_params = jax.tree.map(
            lambda t, p: (1 - tau) * t + tau * p, self.target_params, self.params)
        dist = self.module.dist(self.params, jnp.asarray(batch["obs"]))
        self._rng, sub = jax.random.split(self._rng)
        _, logp = dist.sample_with_logp(sub)
        self.log_alpha, self.alpha_opt_state = self._alpha_update(
            self.log_alpha, self.alpha_opt_state, logp)
        return out

    def get_state(self) -> dict:
        s = super().get_state()
        s["target_params"] = jax.tree.map(np.asarray, self.target_params)
        s["log_alpha"] = np.asarray(self.log_alpha)
        return s

    def set_state(self, state) -> None:
        super().set_state(state)
        self.target_params = jax.tree.map(jnp.asarray, state["target_params"])
        self.log_alpha = jnp.asarray(state["log_alpha"])


class SAC(Algorithm):
    def _module_spec(self, env) -> ModuleSpec:
        spec = spec_from_env(env)
        if spec.discrete:
            raise ValueError("this SAC implementation targets Box action spaces")
        return ModuleSpec(**{**spec.__dict__, "squashed": True,
                             "hiddens": tuple(self.config.hiddens)})

    def _build_learner(self, mesh):
        self.replay = ReplayBuffer(self.config.replay_buffer_capacity,
                                   self.module_spec.obs_dim, discrete=False,
                                   action_dim=self.module_spec.action_dim,
                                   seed=self.config.seed)
        return SACLearner(self.module_spec, self.config, mesh=mesh)

    def training_step(self) -> dict:
        return self._off_policy_step()


class SACConfig(AlgorithmConfig):
    algo_class = SAC

    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.train_batch_size = 256
        self.replay_buffer_capacity = 100_000
        self.tau = 0.005
        self.num_steps_sampled_before_learning_starts = 500
        self.num_updates_per_iteration = 32
