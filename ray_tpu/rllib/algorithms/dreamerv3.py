"""DreamerV3 (compact): world-model RL with imagination training.

Behavioral parity (scoped) with `rllib/algorithms/dreamerv3/` — the
three-part DreamerV3 recipe on vector observations and discrete actions:

1. **RSSM world model**: deterministic GRU path + categorical stochastic
   latents (Kx8 one-hots, straight-through gradients); posterior
   q(z | h, obs) vs prior p(z | h) trained with KL-balance and free
   bits; symlog MSE decoder and reward heads, Bernoulli continue head.
2. **Imagination actor-critic**: trajectories dreamed from posterior
   states with the ACTOR (the world model is frozen for these grads);
   critic regresses lambda-returns on symlog targets; discrete actor
   uses REINFORCE with the critic baseline + entropy bonus, with
   returns normalized by an EMA percentile scale (the v3 trick that
   removes per-env reward tuning).

Deliberate simplifications (documented, not hidden): MLP encoders only
(no CNN — vector envs), plain symlog-MSE instead of twohot distributional
heads, one shared imagination horizon. The pieces the reference's tests
check — RSSM posterior/prior geometry, KL balance, symlog, imagination
rollouts detached from the world model, percentile return scaling —
are all here.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.rl_module import spec_from_env
from ray_tpu.rllib.env.envs import make_env


def symlog(x):
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def _mlp_init(key, sizes):
    out = []
    for m, n in zip(sizes[:-1], sizes[1:]):
        key, sub = jax.random.split(key)
        out.append({"w": jax.random.normal(sub, (m, n)) * jnp.sqrt(2.0 / m),
                    "b": jnp.zeros(n)})
    return out


def _mlp(params, x, act=jax.nn.silu, final_act=None):
    for i, p in enumerate(params):
        x = x @ p["w"] + p["b"]
        if i < len(params) - 1:
            x = act(x)
        elif final_act is not None:
            x = final_act(x)
    return x


class DreamerV3Learner:
    """Owns world-model, actor, and critic params + their optimizers."""

    def __init__(self, obs_dim: int, n_actions: int, cfg: "DreamerV3Config"):
        c = cfg
        self.cfg = cfg
        self.obs_dim = obs_dim
        self.n_actions = n_actions
        self.zdim = c.stoch_groups * c.stoch_classes
        key = jax.random.key(c.seed)
        ks = jax.random.split(key, 12)
        D, H, Z, A = c.deter_dim, c.hidden, self.zdim, n_actions
        wm = {
            "enc": _mlp_init(ks[0], [obs_dim, H, H]),
            # GRU over [z + a] with deter state h
            "gru_x": _mlp_init(ks[1], [Z + A, 3 * D]),
            "gru_h": _mlp_init(ks[2], [D, 3 * D]),
            "prior": _mlp_init(ks[3], [D, H, Z]),
            "post": _mlp_init(ks[4], [D + H, H, Z]),
            "dec": _mlp_init(ks[5], [D + Z, H, obs_dim]),
            "rew": _mlp_init(ks[6], [D + Z, H, 1]),
            "cont": _mlp_init(ks[7], [D + Z, H, 1]),
        }
        self.wm = wm
        self.actor = _mlp_init(ks[8], [D + Z, H, A])
        self.critic = _mlp_init(ks[9], [D + Z, H, 1])
        self.wm_opt = optax.chain(optax.clip_by_global_norm(100.0),
                                  optax.adam(c.wm_lr))
        self.ac_opt = optax.chain(optax.clip_by_global_norm(100.0),
                                  optax.adam(c.ac_lr))
        self.wm_opt_state = self.wm_opt.init(self.wm)
        self.actor_opt_state = self.ac_opt.init(self.actor)
        self.critic_opt_state = self.ac_opt.init(self.critic)
        self._rng = jax.random.key(c.seed + 1)
        # EMA percentile scale for return normalization (v3 §actor)
        self.ret_scale = jnp.float32(1.0)
        self._wm_update = jax.jit(self._make_wm_update())
        self._ac_update = jax.jit(self._make_ac_update())

    # ------------------------------------------------------- RSSM pieces
    def _gru(self, wm, h, x):
        gates_x = _mlp(wm["gru_x"], x)
        gates_h = _mlp(wm["gru_h"], h)
        r_x, u_x, c_x = jnp.split(gates_x, 3, -1)
        r_h, u_h, c_h = jnp.split(gates_h, 3, -1)
        r = jax.nn.sigmoid(r_x + r_h)
        u = jax.nn.sigmoid(u_x + u_h)
        cand = jnp.tanh(c_x + r * c_h)
        return u * cand + (1 - u) * h

    def _sample_categorical(self, logits, rng):
        """Straight-through one-hot sample over stoch groups.
        logits [..., G*C] -> one-hot sample [..., G*C]."""
        c = self.cfg
        shape = logits.shape[:-1] + (c.stoch_groups, c.stoch_classes)
        lg = logits.reshape(shape)
        # unimix: 1% uniform mixed in (v3's fix for determinism collapse)
        probs = 0.99 * jax.nn.softmax(lg, -1) + 0.01 / c.stoch_classes
        lg = jnp.log(probs)
        idx = jax.random.categorical(rng, lg)
        one = jax.nn.one_hot(idx, c.stoch_classes)
        # straight-through: sample forward, softmax gradient backward
        one = one + probs - jax.lax.stop_gradient(probs)
        return one.reshape(logits.shape), lg

    def _unimix_logp(self, logits):
        c = self.cfg
        shape = logits.shape[:-1] + (c.stoch_groups, c.stoch_classes)
        probs = (0.99 * jax.nn.softmax(logits.reshape(shape), -1)
                 + 0.01 / c.stoch_classes)
        return jnp.log(probs)

    def _kl(self, lhs_logits, rhs_logits):
        """KL(lhs || rhs) summed over groups, on the SAME 1%-unimix
        distributions sampling uses — the floor must protect the KL too,
        or a saturating prior makes it ill-conditioned."""
        lp = self._unimix_logp(lhs_logits)
        rp = self._unimix_logp(rhs_logits)
        return (jnp.exp(lp) * (lp - rp)).sum(-1).sum(-1)

    # ------------------------------------------------------ world model
    def _make_wm_update(self):
        c = self.cfg

        def wm_loss(wm, batch, rng):
            obs = symlog(batch["obs"])            # [B, L, obs]
            acts = jax.nn.one_hot(batch["actions"], self.n_actions)
            # h_t must condition on the PREVIOUS action (what act() has
            # at inference time), never the action chosen after obs_t
            acts_prev = jnp.concatenate(
                [jnp.zeros_like(acts[:, :1]), acts[:, :-1]], 1)
            # episode starts inside the window: reset (h, z) so the RSSM
            # never bridges a reset teleport (is_first handling)
            firsts = jnp.concatenate(
                [jnp.ones_like(batch["firsts"][:, :1]),
                 batch["firsts"][:, 1:]], 1)
            B, L = obs.shape[:2]
            emb = _mlp(wm["enc"], obs)            # [B, L, H]
            h0 = jnp.zeros((B, c.deter_dim))
            z0 = jnp.zeros((B, self.zdim))
            keys = jax.random.split(rng, L)

            def step(carry, xt):
                h, z = carry
                e_t, a_t, f_t, k_t = xt
                h = jnp.where(f_t[:, None], 0.0, h)
                z = jnp.where(f_t[:, None], 0.0, z)
                a_t = jnp.where(f_t[:, None], 0.0, a_t)
                h = self._gru(wm, h, jnp.concatenate([z, a_t], -1))
                prior_logits = _mlp(wm["prior"], h)
                post_logits = _mlp(wm["post"],
                                   jnp.concatenate([h, e_t], -1))
                z, _post_lg = self._sample_categorical(post_logits, k_t)
                return (h, z), (h, z, prior_logits, post_logits)

            (_, _), (hs, zs, priors, posts) = jax.lax.scan(
                step, (h0, z0),
                (emb.swapaxes(0, 1), acts_prev.swapaxes(0, 1),
                 firsts.swapaxes(0, 1).astype(bool), keys))
            feat = jnp.concatenate([hs, zs], -1)          # [L, B, D+Z]
            obs_hat = _mlp(wm["dec"], feat)
            rew_hat = _mlp(wm["rew"], feat)[..., 0]
            cont_logit = _mlp(wm["cont"], feat)[..., 0]
            obs_t = obs.swapaxes(0, 1)
            rec = ((obs_hat - obs_t) ** 2).sum(-1).mean()
            rew = ((rew_hat - symlog(batch["rewards"].swapaxes(0, 1)))
                   ** 2).mean()
            cont_t = 1.0 - batch["dones"].swapaxes(0, 1)
            cont = optax.sigmoid_binary_cross_entropy(
                cont_logit, cont_t).mean()
            # KL balance with free bits (v3: dyn 0.5 / rep 0.1, clip 1.0)
            dyn = jnp.maximum(self._kl(jax.lax.stop_gradient(posts),
                                       priors), 1.0).mean()
            rep = jnp.maximum(self._kl(posts,
                                       jax.lax.stop_gradient(priors)),
                              1.0).mean()
            loss = rec + rew + cont + 0.5 * dyn + 0.1 * rep
            return loss, {"wm_rec": rec, "wm_rew": rew, "wm_cont": cont,
                          "wm_kl_dyn": dyn,
                          "feat": jax.lax.stop_gradient(feat)}

        def update(wm, opt_state, batch, rng):
            (l, aux), g = jax.value_and_grad(wm_loss, has_aux=True)(
                wm, batch, rng)
            upd, opt_state = self.wm_opt.update(g, opt_state)
            return optax.apply_updates(wm, upd), opt_state, l, aux

        return update

    # --------------------------------------------------- actor + critic
    def _make_ac_update(self):
        c = self.cfg

        def imagine(wm, actor, start_feat, rng):
            """Dream H steps from start states. Returns feats [H+1, N, F],
            actions, rewards, continues (world model frozen)."""
            D = c.deter_dim
            h = start_feat[..., :D]
            z = start_feat[..., D:]
            N = h.shape[0]

            def step(carry, k):
                h, z = carry
                feat = jnp.concatenate([h, z], -1)
                logits = _mlp(actor, feat)
                ka, kz = jax.random.split(k)
                a = jax.random.categorical(ka, logits)
                a1 = jax.nn.one_hot(a, self.n_actions)
                h2 = self._gru(wm, h, jnp.concatenate([z, a1], -1))
                prior_logits = _mlp(wm["prior"], h2)
                z2, _ = self._sample_categorical(prior_logits, kz)
                return (h2, z2), (feat, a)

            keys = jax.random.split(rng, c.horizon)
            (h, z), (feats, acts) = jax.lax.scan(step, (h, z), keys)
            last = jnp.concatenate([h, z], -1)[None]
            feats = jnp.concatenate([feats, last], 0)   # [H+1, N, F]
            rew = symexp(_mlp(wm["rew"], feats)[..., 0])
            cont = jax.nn.sigmoid(_mlp(wm["cont"], feats)[..., 0])
            return feats, acts, rew, cont

        def lambda_returns(rew, cont, values):
            """TD(lambda) over imagined steps: [H+1, N] inputs."""
            disc = cont * c.gamma

            def step(nxt, xt):
                r_t, d_t, v_t1 = xt
                ret = r_t + d_t * ((1 - c.lam) * v_t1 + c.lam * nxt)
                return ret, ret

            last = values[-1]
            _, rets = jax.lax.scan(
                step, last,
                (rew[:-1][::-1], disc[1:][::-1], values[1:][::-1]))
            return rets[::-1]                            # [H, N]

        def ac_loss(actor, critic, wm, start_feat, ret_scale, rng):
            feats, acts, rew, cont = imagine(wm, actor, start_feat, rng)
            feats = jax.lax.stop_gradient(feats)   # REINFORCE actor: no
            acts = jax.lax.stop_gradient(acts)     # grads through dynamics
            raw_v = _mlp(critic, feats)[..., 0]
            values = symexp(raw_v)
            rets = lambda_returns(rew, cont,
                                  jax.lax.stop_gradient(values))
            # critic: symlog MSE toward lambda-returns (one forward)
            critic_loss = ((raw_v[:-1]
                            - jax.lax.stop_gradient(symlog(rets))) ** 2
                           ).mean()
            # actor: REINFORCE with critic baseline, percentile-scaled
            adv = (rets - values[:-1]) / jnp.maximum(ret_scale, 1.0)
            logits = _mlp(actor, feats[:-1])
            logp = jax.nn.log_softmax(logits)
            lp_a = jnp.take_along_axis(logp, acts[..., None], -1)[..., 0]
            ent = -(jnp.exp(logp) * logp).sum(-1)
            # weight imagined steps by survival probability
            weight = jnp.cumprod(
                jnp.concatenate([jnp.ones_like(cont[:1]),
                                 cont[:-2] * c.gamma], 0), 0)
            weight = jax.lax.stop_gradient(weight)
            actor_loss = -(weight * (
                jax.lax.stop_gradient(adv) * lp_a
                + c.entropy_coef * ent)).mean()
            new_scale = jnp.percentile(rets, 95) - jnp.percentile(rets, 5)
            return actor_loss + critic_loss, {
                "actor_loss": actor_loss, "critic_loss": critic_loss,
                "imag_return_mean": rets.mean(), "actor_entropy": ent.mean(),
                "ret_scale": new_scale}

        def update(actor, critic, a_state, c_state, wm, start_feat,
                   ret_scale, rng):
            (l, metrics), (ga, gc) = jax.value_and_grad(
                ac_loss, argnums=(0, 1), has_aux=True)(
                actor, critic, wm, start_feat, ret_scale, rng)
            ua, a_state = self.ac_opt.update(ga, a_state)
            uc, c_state = self.ac_opt.update(gc, c_state)
            return (optax.apply_updates(actor, ua),
                    optax.apply_updates(critic, uc), a_state, c_state,
                    metrics)

        return update

    # ------------------------------------------------------------ public
    def update(self, batch: Dict[str, np.ndarray]) -> dict:
        self._rng, k1, k2 = jax.random.split(self._rng, 3)
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self.wm, self.wm_opt_state, wm_l, aux = self._wm_update(
            self.wm, self.wm_opt_state, jb, k1)
        feat = aux.pop("feat")                        # [L, B, F]
        start = feat.reshape(-1, feat.shape[-1])
        if len(start) > self.cfg.imag_starts:
            self._rng, ks = jax.random.split(self._rng)
            idx = jax.random.choice(ks, len(start),
                                    (self.cfg.imag_starts,), replace=False)
            start = start[idx]
        (self.actor, self.critic, self.actor_opt_state,
         self.critic_opt_state, m) = self._ac_update(
            self.actor, self.critic, self.actor_opt_state,
            self.critic_opt_state, self.wm, start, self.ret_scale, k2)
        # EMA of the return percentile scale
        self.ret_scale = 0.99 * self.ret_scale + 0.01 * m.pop("ret_scale")
        out = {"wm_loss": float(wm_l)}
        out.update({k: float(v) for k, v in aux.items()})
        out.update({k: float(v) for k, v in m.items()})
        out["ret_scale"] = float(self.ret_scale)
        return out

    def act(self, obs: np.ndarray, state, rng_np) -> Tuple[np.ndarray, tuple]:
        """Environment-side policy: posterior filtering + actor sample.
        state = (h, z, last_action_onehot) per env."""
        c = self.cfg
        obs = symlog(jnp.asarray(obs, jnp.float32))
        B = obs.shape[0]
        if state is None:
            state = (jnp.zeros((B, c.deter_dim)),
                     jnp.zeros((B, self.zdim)),
                     jnp.zeros((B, self.n_actions)))
        h, z, a1 = state
        emb = _mlp(self.wm["enc"], obs)
        h = self._gru(self.wm, h, jnp.concatenate([z, a1], -1))
        post_logits = _mlp(self.wm["post"], jnp.concatenate([h, emb], -1))
        self._rng, kz, ka = jax.random.split(self._rng, 3)
        z, _ = self._sample_categorical(post_logits, kz)
        logits = _mlp(self.actor, jnp.concatenate([h, z], -1))
        a = jax.random.categorical(ka, logits)
        a1 = jax.nn.one_hot(a, self.n_actions)
        return np.asarray(a), (h, z, a1)

    # Algorithm-base compatibility: the generic env-runner group syncs
    # "policy weights" at init; Dreamer drives its own env loop (the
    # posterior filter is part of the policy), so these are only a
    # checkpoint-shaped view of the actor
    def get_weights(self):
        return jax.tree.map(np.asarray, self.actor)

    def set_weights(self, params) -> None:
        pass   # runner-side no-op; Dreamer's act() lives on the learner

    def get_state(self) -> dict:
        t = lambda p: jax.tree.map(np.asarray, p)  # noqa: E731
        return {"wm": t(self.wm), "actor": t(self.actor),
                "critic": t(self.critic),
                "wm_opt_state": t(self.wm_opt_state),
                "actor_opt_state": t(self.actor_opt_state),
                "critic_opt_state": t(self.critic_opt_state),
                "ret_scale": float(self.ret_scale)}

    def set_state(self, state: dict) -> None:
        t = lambda p: jax.tree.map(jnp.asarray, p)  # noqa: E731
        self.wm = t(state["wm"])
        self.actor = t(state["actor"])
        self.critic = t(state["critic"])
        if "wm_opt_state" in state:   # Adam moments resume with params
            self.wm_opt_state = t(state["wm_opt_state"])
            self.actor_opt_state = t(state["actor_opt_state"])
            self.critic_opt_state = t(state["critic_opt_state"])
        self.ret_scale = jnp.float32(state["ret_scale"])


class _SeqBuffer:
    """Uniform sequence replay: stores transitions in ring order, samples
    contiguous [B, L] windows (the reference's episodic replay, flat)."""

    def __init__(self, capacity: int, obs_dim: int, seed: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros(capacity, np.int32)
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.float32)     # TERMINATION only
        self.firsts = np.zeros(capacity, np.float32)    # episode starts
        self.size = 0
        self._i = 0
        self._rng = np.random.default_rng(seed)

    def add(self, obs, action, reward, done, first):
        i = self._i
        self.obs[i] = obs
        self.actions[i] = action
        self.rewards[i] = reward
        self.dones[i] = done
        self.firsts[i] = first
        self._i = (i + 1) % self.capacity
        self.size = min(self.size + 1, self.capacity)

    def sample(self, batch: int, length: int) -> Dict[str, np.ndarray]:
        starts = np.empty(batch, np.int64)
        for b in range(batch):
            while True:
                st = int(self._rng.integers(0, self.size - length))
                # a full ring has a logical seam at the write head: a
                # window crossing it would splice newest->oldest data
                if self.size == self.capacity:
                    seam = self._i
                    if (st < seam <= st + length):
                        continue
                starts[b] = st
                break
        idx = starts[:, None] + np.arange(length)[None]
        return {"obs": self.obs[idx], "actions": self.actions[idx],
                "rewards": self.rewards[idx], "dones": self.dones[idx],
                "firsts": self.firsts[idx]}


class DreamerV3(Algorithm):
    def _module_spec(self, env):
        spec = spec_from_env(env)
        if not spec.discrete:
            raise ValueError("this DreamerV3 targets discrete actions")
        return spec

    def _build_learner(self, mesh):
        spec = self.module_spec
        self._buffer = _SeqBuffer(self.config.replay_capacity,
                                  spec.obs_dim, self.config.seed)
        return DreamerV3Learner(spec.obs_dim, spec.action_dim, self.config)

    # Dreamer drives its own env loop (posterior filtering state is part
    # of the policy), so it bypasses the generic env-runner group.
    def _init_env_loop(self):
        if getattr(self, "_env", None) is None:
            self._env = make_env(self.config.env, **self.config.env_kwargs)
            self._obs, _ = self._env.reset(seed=self.config.seed)
            self._policy_state = None

    def training_step(self) -> dict:
        c = self.config
        self._init_env_loop()
        ep_returns = []
        ep_ret = getattr(self, "_ep_ret", 0.0)
        first = getattr(self, "_first", True)
        for _ in range(c.env_steps_per_iteration):
            a, self._policy_state = self.learner.act(
                self._obs[None], self._policy_state, None)
            nxt, r, term, trunc, _ = self._env.step(int(a[0]))
            self._buffer.add(self._obs, int(a[0]), r, float(term),
                             float(first))
            first = False
            ep_ret += r
            self._obs = nxt
            if term or trunc:
                ep_returns.append(ep_ret)
                ep_ret = 0.0
                self._obs, _ = self._env.reset()
                self._policy_state = None
                first = True
        self._first = first
        self._ep_ret = ep_ret
        self._timesteps += c.env_steps_per_iteration
        metrics = {}
        if self._buffer.size > c.seq_len * 2 + c.batch_size:
            for _ in range(c.updates_per_iteration):
                batch = self._buffer.sample(c.batch_size, c.seq_len)
                metrics = self.learner.update(batch)
        if ep_returns:
            metrics["episode_return_mean"] = float(np.mean(ep_returns))
        return metrics

    def evaluate(self, num_episodes: int = None) -> dict:
        """Posterior-filter policy evaluation (the generic env-runner
        evaluate cannot drive a world-model policy)."""
        n = num_episodes or self.config.evaluation_num_episodes
        env = make_env(self.config.env, **self.config.env_kwargs)
        rets = []
        for ep in range(n):
            obs, _ = env.reset(seed=self.config.seed + 7919 + ep)
            state = None
            total, done = 0.0, False
            while not done:
                a, state = self.learner.act(obs[None], state, None)
                obs, r, term, trunc, _ = env.step(int(a[0]))
                total += r
                done = term or trunc
            rets.append(total)
        env.close()
        return {"evaluation": {
            "episode_return_mean": float(np.mean(rets)),
            "num_episodes": n}}

    def stop(self):
        if getattr(self, "_env", None) is not None:
            self._env.close()
        super().stop()


class DreamerV3Config(AlgorithmConfig):
    algo_class = DreamerV3

    def __init__(self):
        super().__init__()
        self.wm_lr = 1e-3
        self.ac_lr = 3e-4
        self.deter_dim = 128
        self.hidden = 128
        self.stoch_groups = 8
        self.stoch_classes = 8
        self.horizon = 15
        self.lam = 0.95
        self.entropy_coef = 3e-3
        self.replay_capacity = 100_000
        self.seq_len = 16
        self.batch_size = 8
        self.imag_starts = 128
        self.env_steps_per_iteration = 200
        self.updates_per_iteration = 4
