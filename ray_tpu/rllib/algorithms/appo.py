"""APPO: asynchronous PPO — IMPALA's actor-learner pipeline with a
PPO-style clipped surrogate on V-trace advantages.

Parity: `rllib/algorithms/appo/appo.py` + the torch learner
(`appo/torch/appo_torch_learner.py`): same decoupled rollout/aggregation
architecture as IMPALA (reused wholesale here), but the policy update is
the clipped surrogate ratio against the ROLLOUT policy, advantages come
from V-trace, and a periodically-updated target network regularizes the
update (optional KL term, reference `use_kl_loss`/`kl_coeff`). The
target params ride the batch as a replicated aux pytree, so the whole
update — V-trace scan, surrogate, KL, apply — is one jitted XLA program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.impala import (IMPALA, IMPALAConfig,
                                             ImpalaLearner, vtrace)


class APPOLearner(ImpalaLearner):
    """Clipped-surrogate V-trace learner with a target network."""

    def __init__(self, spec, cfg: "APPOConfig", mesh=None):
        super().__init__(spec, cfg, mesh=mesh)
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self._updates_since_target = 0

    def loss(self, params, batch, rng):
        c = self.cfg
        obs = batch["obs"]                     # [T, N, ...] time-major
        T, N = obs.shape[:2]
        flat_obs = obs.reshape((T * N,) + obs.shape[2:])
        flat_act = batch["actions"].reshape(
            (T * N,) + batch["actions"].shape[2:])
        dist = self.module.dist(params, flat_obs)
        logp = dist.log_prob(flat_act).reshape(T, N)
        v = self.module.value(params, flat_obs).reshape(T, N)

        # V-trace targets/advantages under the TARGET policy (reference
        # APPO: old_policy corrects the off-policy gap; it lags several
        # updates, so the surrogate clip below bounds the step)
        target_dist = self.module.dist(batch["_target_params"], flat_obs)
        old_logp = target_dist.log_prob(flat_act).reshape(T, N)
        vs, pg_adv = vtrace(batch["logp"], old_logp, batch["rewards"],
                            v, batch["dones"], batch["last_values"],
                            c.gamma, c.vtrace_rho_bar, c.vtrace_c_bar)
        if c.normalize_advantages:
            pg_adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)

        # PPO clipped surrogate vs the ROLLOUT (behavior) policy
        ratio = jnp.exp(logp - batch["logp"])
        clipped = jnp.clip(ratio, 1.0 - c.clip_param, 1.0 + c.clip_param)
        pg_loss = -jnp.minimum(ratio * pg_adv, clipped * pg_adv).mean()
        vf_loss = 0.5 * ((v - vs) ** 2).mean()
        entropy = dist.entropy().mean()
        total = (pg_loss + c.vf_loss_coeff * vf_loss
                 - c.entropy_coeff * entropy)
        metrics = {"policy_loss": pg_loss, "vf_loss": vf_loss,
                   "entropy": entropy,
                   "mean_ratio": ratio.mean()}
        if c.use_kl_loss:
            # KL(target || current) over the batch states (reference
            # appo_torch_learner KL term against the old policy)
            kl = target_dist.kl(dist).mean()
            total = total + c.kl_coeff * kl
            metrics["kl"] = kl
        return total, metrics

    def update(self, batch):
        batch = dict(batch)
        batch["_target_params"] = self.target_params
        metrics = super().update(batch)
        self._updates_since_target += 1
        if self._updates_since_target >= self.cfg.target_update_freq:
            self._updates_since_target = 0
            # NETWORK_TARGET_UPDATE: full copy (reference tau=1.0 default)
            self.target_params = jax.tree.map(jnp.copy, self.params)
        return metrics


class APPO(IMPALA):
    def _build_learner(self, mesh):
        return APPOLearner(self.module_spec, self.config, mesh=mesh)


class APPOConfig(IMPALAConfig):
    algo_class = APPO

    def __init__(self):
        super().__init__()
        self.clip_param = 0.4           # reference APPOConfig.clip_param
        self.use_kl_loss = False
        self.kl_coeff = 1.0
        self.normalize_advantages = False
        self.target_update_freq = 4     # learner updates per target copy
