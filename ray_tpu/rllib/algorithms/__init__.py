"""algorithms subpackage."""
