"""CQL: conservative Q-learning for offline RL.

Parity: `rllib/algorithms/cql/` (cql.py + torch learner) — SAC machinery
(twin Q, squashed-Gaussian actor, target nets, auto temperature) plus the
CQL(H) conservative penalty: for each state, the critic is pushed DOWN on
out-of-distribution actions (logsumexp over random + policy actions with
importance correction) and UP on the dataset action, so the learned Q
never over-values actions the behavior policy never took. Trains from the
same offline-data seam as BC/MARWIL (`rllib/offline/` role).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.bc import BC, BCConfig
from ray_tpu.rllib.algorithms.sac import SACLearner
from ray_tpu.rllib.core.rl_module import ModuleSpec, spec_from_env


class CQLLearner(SACLearner):
    def loss(self, params, batch, rng) -> Tuple[jnp.ndarray, dict]:
        sac_loss, metrics = super().loss(params, batch, rng)
        c = self.cfg
        n = c.cql_n_actions
        obs = batch["obs"]
        B = obs.shape[0]
        A = self.module.spec.action_dim
        k_rand, k_cur, k_next = jax.random.split(jax.random.fold_in(rng, 7), 3)

        # candidate action sets (n, B, A): uniform + current-policy +
        # next-state-policy samples (the CQL(H) estimator's proposal mix)
        rand_a = jax.random.uniform(k_rand, (n, B, A), minval=-1.0,
                                    maxval=1.0)
        dist_cur = self.module.dist(params, obs)
        dist_next = self.module.dist(params, batch["next_obs"])
        cur_a, cur_logp = jax.vmap(dist_cur.sample_with_logp)(
            jax.random.split(k_cur, n))
        next_a, next_logp = jax.vmap(dist_next.sample_with_logp)(
            jax.random.split(k_next, n))

        def q_set(acts):
            return jax.vmap(
                lambda a: self.module.q_values(params, obs, a))(acts)

        q1_r, q2_r = q_set(rand_a)
        q1_c, q2_c = q_set(cur_a)
        q1_n, q2_n = q_set(next_a)
        # importance correction: uniform density (1/2)^A, policy densities
        # exp(logp) — subtract log-density from each candidate's Q
        log_unif = -A * jnp.log(2.0)
        cat1 = jnp.concatenate(
            [q1_r - log_unif, q1_c - cur_logp, q1_n - next_logp], axis=0)
        cat2 = jnp.concatenate(
            [q2_r - log_unif, q2_c - cur_logp, q2_n - next_logp], axis=0)
        q1_data, q2_data = self.module.q_values(params, obs,
                                                batch["actions"])
        gap1 = (jax.scipy.special.logsumexp(cat1, axis=0)
                - jnp.log(3 * n) - q1_data).mean()
        gap2 = (jax.scipy.special.logsumexp(cat2, axis=0)
                - jnp.log(3 * n) - q2_data).mean()
        penalty = c.cql_alpha * (gap1 + gap2)
        total = sac_loss + penalty
        metrics = {**metrics, "cql_penalty": penalty,
                   "cql_gap": 0.5 * (gap1 + gap2)}
        return total, metrics


class CQL(BC):
    offline_columns = ("obs", "actions", "rewards", "next_obs", "dones")

    def _module_spec(self, env) -> ModuleSpec:
        spec = spec_from_env(env)
        if spec.discrete:
            raise ValueError("CQL targets Box action spaces (SAC-based)")
        return ModuleSpec(**{**spec.__dict__, "squashed": True,
                             "hiddens": tuple(self.config.hiddens)})

    def _post_load(self, cols: dict) -> None:
        self._extras = {
            "rewards": cols["rewards"].astype(np.float32),
            "next_obs": cols["next_obs"].astype(np.float32),
            "dones": cols["dones"].astype(np.float32),
        }

    def _make_learner(self, mesh):
        return CQLLearner(self.module_spec, self.config, mesh=mesh)


class CQLConfig(BCConfig):
    algo_class = CQL

    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.tau = 0.005
        self.train_batch_size = 256
        self.num_updates_per_iteration = 32
        self.cql_alpha = 1.0
        self.cql_n_actions = 4
