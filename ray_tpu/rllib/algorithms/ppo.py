"""PPO: clipped-surrogate policy optimization with GAE.

Parity: `rllib/algorithms/ppo/` (ppo.py, ppo_learner.py, default configs) —
the loss math follows the reference's torch learner
(`rllib/algorithms/ppo/torch/ppo_torch_learner.py`): clip objective, value
clipping, entropy bonus, GAE(λ). GAE and the minibatch epochs are all jitted;
the minibatch update shards over the mesh dp axis when configured.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import JaxLearner


def compute_gae(rewards, values, dones, last_values, gamma, lam):
    """[T, N] leaves → (advantages, value_targets), vectorized lax.scan over
    time (reference: `rllib/evaluation/postprocessing.py` compute_advantages)."""
    def step(carry, xs):
        r, v, d = xs
        next_v, adv = carry
        delta = r + gamma * next_v * (1 - d) - v
        adv = delta + gamma * lam * (1 - d) * adv
        return (v, adv), adv

    (_, _), advs = jax.lax.scan(
        step, (last_values, jnp.zeros_like(last_values)),
        (rewards, values, dones), reverse=True)
    return advs, advs + values


# Module-level jit so the traced/compiled GAE is cached across training
# steps instead of re-wrapped (and re-traced) inside every training_step.
_jitted_gae = jax.jit(compute_gae, static_argnums=(4, 5))


class PPOLearner(JaxLearner):
    def __init__(self, spec, cfg: "PPOConfig", mesh=None):
        self.cfg = cfg
        super().__init__(spec, lr=cfg.lr, grad_clip=cfg.grad_clip,
                         seed=cfg.seed, mesh=mesh)

    def loss(self, params, batch, rng) -> Tuple[jnp.ndarray, dict]:
        c = self.cfg
        dist = self.module.dist(params, batch["obs"])
        logp = dist.log_prob(batch["actions"])
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["advantages"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        pg = -jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - c.clip_param, 1 + c.clip_param) * adv).mean()
        v = self.module.value(params, batch["obs"])
        v_clipped = batch["values"] + jnp.clip(
            v - batch["values"], -c.vf_clip_param, c.vf_clip_param)
        vf_loss = jnp.maximum((v - batch["value_targets"]) ** 2,
                              (v_clipped - batch["value_targets"]) ** 2).mean()
        entropy = dist.entropy().mean()
        total = pg + c.vf_loss_coeff * vf_loss - c.entropy_coeff * entropy
        return total, {"policy_loss": pg, "vf_loss": vf_loss, "entropy": entropy,
                       "mean_kl": (batch["logp"] - logp).mean()}


class PPO(Algorithm):
    def _build_learner(self, mesh):
        return PPOLearner(self.module_spec, self.config, mesh=mesh)

    def training_step(self) -> dict:
        if self._multi_agent:
            return self._multi_agent_training_step()
        c = self.config
        self.env_runner_group.sync_weights(self.learner.get_weights())
        fragments = self.env_runner_group.sample(c.rollout_fragment_length)
        if not fragments:
            # every remote runner failed this iteration; they've been
            # replaced — skip the update rather than crash
            return {"num_failed_sample_rounds": 1}
        ep_metrics = [f.pop("_metrics") for f in fragments]

        # concatenate runner fragments along the env axis, compute GAE, flatten
        cat = {k: np.concatenate([f[k] for f in fragments], axis=1)
               for k in fragments[0] if k not in ("next_obs", "last_values")}
        cat["last_values"] = np.concatenate(
            [f["last_values"] for f in fragments])
        rng = np.random.default_rng(c.seed + self.iteration)
        metrics = self._ppo_update_on_fragment(self.learner, cat, rng)
        metrics.update(self._episode_metrics(ep_metrics))
        return metrics

    def _ppo_update_on_fragment(self, learner, frag: dict, rng) -> dict:
        """GAE (truncation-aware) + minibatch epochs on one [T, N]
        fragment — shared by the single-agent and per-policy multi-agent
        paths so the recursion can never silently diverge between them.
        Bootstrap through time-limit truncation: fold γV(final_obs) into
        the reward at truncated (non-terminated) steps, then treat the
        step as done — an exact rewrite of the truncation-aware GAE."""
        c = self.config
        boot = frag["truncateds"] & ~frag["terminateds"]
        rewards = frag["rewards"] + c.gamma * frag["final_values"] * boot
        advs, targets = _jitted_gae(
            rewards, frag["values"], frag["dones"].astype(np.float32),
            frag["last_values"], c.gamma, c.lambda_)
        T, N = frag["rewards"].shape
        flat = lambda x: np.asarray(x).reshape(T * N, *x.shape[2:])
        batch = {"obs": flat(frag["obs"]), "actions": flat(frag["actions"]),
                 "logp": flat(frag["logp"]), "values": flat(frag["values"]),
                 "advantages": flat(advs), "value_targets": flat(targets)}
        self._timesteps += T * N
        n = batch["obs"].shape[0]
        mb = min(c.minibatch_size, n)
        metrics: Dict[str, float] = {}
        for _ in range(c.num_epochs):
            perm = rng.permutation(n)
            for st in range(0, n - mb + 1, mb):
                idx = perm[st:st + mb]
                metrics = learner.update({k: v[idx]
                                          for k, v in batch.items()})
        return metrics

    def _multi_agent_training_step(self) -> dict:
        """Independent PPO per policy (reference multi-agent PPO with a
        MultiRLModule): one shared rollout, per-policy GAE + minibatch
        epochs on that policy's [T, N_agents] fragment."""
        c = self.config
        self.ma_runner.set_weights({p: l.get_weights()
                                    for p, l in self.learners.items()})
        frags = self.ma_runner.sample(c.rollout_fragment_length)
        rng = np.random.default_rng(c.seed + self.iteration)
        metrics: Dict[str, float] = {}
        for pid, f in frags.items():
            m = self._ppo_update_on_fragment(self.learners[pid], f, rng)
            metrics.update({f"{pid}/{k}": v for k, v in m.items()})
        em = self.ma_runner.episode_metrics()
        if em["episodes"]:
            # per-agent mean return over the window (all agents pooled)
            metrics["episode_return_mean"] = em["return_sum"] / em["episodes"]
        metrics["episodes_this_iter"] = em["episodes"]
        return metrics


class PPOConfig(AlgorithmConfig):
    algo_class = PPO

    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.lambda_ = 0.95
        self.num_epochs = 4
        self.minibatch_size = 128
