"""DQN: double Q-learning with target network and epsilon-greedy exploration.

Parity: `rllib/algorithms/dqn/` (dqn.py, default_dqn_rl_module.py, torch
learner) — double-DQN target per the reference's default config, uniform
replay (`rllib/utils/replay_buffers/`), linear epsilon schedule.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import JaxLearner
from ray_tpu.rllib.core.replay import ReplayBuffer
from ray_tpu.rllib.core.rl_module import ModuleSpec, spec_from_env


class DQNLearner(JaxLearner):
    def __init__(self, spec, cfg: "DQNConfig", mesh=None):
        self.cfg = cfg
        super().__init__(spec, lr=cfg.lr, grad_clip=cfg.grad_clip,
                         seed=cfg.seed, mesh=mesh)
        self.target_params = jax.tree.map(jnp.asarray, self.params)
        self._steps = 0

    def loss(self, params, batch, rng) -> Tuple[jnp.ndarray, dict]:
        c = self.cfg
        q = self.module.pi_out(params, batch["obs"])
        q_taken = jnp.take_along_axis(
            q, batch["actions"][:, None].astype(jnp.int32), axis=-1)[:, 0]
        # double DQN: online net picks the argmax, target net evaluates it
        next_q_online = self.module.pi_out(params, batch["next_obs"])
        next_a = jnp.argmax(next_q_online, axis=-1)
        next_q_target = self.module.pi_out(batch["_target"], batch["next_obs"])
        next_q = jnp.take_along_axis(next_q_target, next_a[:, None], axis=-1)[:, 0]
        target = batch["rewards"] + c.gamma * (1 - batch["dones"]) * \
            jax.lax.stop_gradient(next_q)
        td = q_taken - target
        loss = jnp.where(jnp.abs(td) < 1.0, 0.5 * td**2,
                         jnp.abs(td) - 0.5).mean()  # Huber
        return loss, {"qf_loss": loss, "q_mean": q_taken.mean()}

    def update(self, batch) -> dict:
        batch = dict(batch)
        batch["_target"] = self.target_params
        out = super().update(batch)
        self._steps += 1
        if self._steps % self.cfg.target_network_update_freq == 0:
            self.target_params = jax.tree.map(jnp.asarray, self.params)
        return out

    def get_state(self) -> dict:
        s = super().get_state()
        s["target_params"] = jax.tree.map(np.asarray, self.target_params)
        return s

    def set_state(self, state) -> None:
        super().set_state(state)
        self.target_params = jax.tree.map(jnp.asarray, state["target_params"])


class DQN(Algorithm):
    needs_epsilon = True

    def _module_spec(self, env) -> ModuleSpec:
        spec = spec_from_env(env)
        if not spec.discrete:
            raise ValueError("DQN requires a discrete action space")
        return ModuleSpec(**{**spec.__dict__, "q_network": True,
                             "hiddens": tuple(self.config.hiddens)})

    def _build_learner(self, mesh):
        self.replay = ReplayBuffer(self.config.replay_buffer_capacity,
                                   self.module_spec.obs_dim, discrete=True,
                                   seed=self.config.seed)
        return DQNLearner(self.module_spec, self.config, mesh=mesh)

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._timesteps / max(1, c.epsilon_timesteps))
        return c.initial_epsilon + frac * (c.final_epsilon - c.initial_epsilon)

    def training_step(self) -> dict:
        metrics = self._off_policy_step(epsilon=self._epsilon())
        metrics["epsilon"] = self._epsilon()
        return metrics


class DQNConfig(AlgorithmConfig):
    algo_class = DQN

    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.train_batch_size = 64
        self.replay_buffer_capacity = 50_000
        self.target_network_update_freq = 100
        self.initial_epsilon = 1.0
        self.final_epsilon = 0.05
        self.epsilon_timesteps = 5_000
        self.num_steps_sampled_before_learning_starts = 500
        self.num_updates_per_iteration = 32
