"""Multi-agent environments + rollout collection.

Parity (simultaneous-action core) with the reference's multi-agent stack
(`rllib/env/multi_agent_env.py`, `rllib/env/multi_agent_env_runner.py`,
`rllib/examples/envs/classes/...`): every agent acts each step, rewards
are per-agent dicts, episodes end via the `"__all__"` flag, and a
policy-mapping function assigns each agent to a policy (parameter
sharing = many agents → one policy). TPU-first collection: each step,
agents are GROUPED BY POLICY and batched through one jitted policy step,
so N agents sharing a policy cost one device call, not N.

Scope note (documented constraint): agents live for the whole episode —
the simultaneous-game model; per-agent early exits are not supported.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.core.rl_module import ModuleSpec, RLModule
from ray_tpu.rllib.env.envs import Box, Discrete


class MultiAgentEnv:
    """Simultaneous-action multi-agent env protocol.

    - `agents`: list of agent ids
    - `reset(seed) -> (obs_dict, info_dict)`
    - `step(action_dict) -> (obs, rewards, terminateds, truncateds, infos)`
      dicts; `terminateds["__all__"] | truncateds["__all__"]` ends the
      episode for everyone
    - `observation_space(agent)` / `action_space(agent)`
    """

    agents: List[str] = []

    def reset(self, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError

    def observation_space(self, agent: str):
        raise NotImplementedError

    def action_space(self, agent: str):
        raise NotImplementedError


class TargetMatch(MultiAgentEnv):
    """Cooperative toy game (test env, reference examples-classes role):
    both agents see a one-hot target; each earns 1 for matching it, plus
    a shared bonus when BOTH match — learnable independently, with a
    cooperative component visible in the reward curves."""

    def __init__(self, num_targets: int = 4, episode_len: int = 16):
        self.agents = ["agent_0", "agent_1"]
        self.n = num_targets
        self.episode_len = episode_len
        self._rng = np.random.default_rng(0)
        self._t = 0
        self._target = 0

    def observation_space(self, agent: str):
        return Box(0.0, 1.0, (self.n,))

    def action_space(self, agent: str):
        return Discrete(self.n)

    def _obs(self):
        o = np.zeros(self.n, np.float32)
        o[self._target] = 1.0
        return {a: o.copy() for a in self.agents}

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._target = int(self._rng.integers(self.n))
        return self._obs(), {}

    def step(self, action_dict: Dict[str, Any]):
        hits = {a: float(int(action_dict[a]) == self._target)
                for a in self.agents}
        both = all(hits.values())
        rewards = {a: hits[a] + (0.5 if both else 0.0) for a in self.agents}
        self._t += 1
        self._target = int(self._rng.integers(self.n))
        done = self._t >= self.episode_len
        term = {a: False for a in self.agents}
        term["__all__"] = False
        trunc = {a: done for a in self.agents}
        trunc["__all__"] = done
        return self._obs(), rewards, term, trunc, {}


def spec_for_agent(env: MultiAgentEnv, agent: str,
                   hiddens=(64, 64)) -> ModuleSpec:
    space = env.action_space(agent)
    obs_dim = int(np.prod(env.observation_space(agent).shape))
    if isinstance(space, Discrete):
        return ModuleSpec(obs_dim=obs_dim, action_dim=space.n,
                          discrete=True, hiddens=tuple(hiddens))
    return ModuleSpec(obs_dim=obs_dim,
                      action_dim=int(np.prod(space.shape)), discrete=False,
                      hiddens=tuple(hiddens),
                      action_scale=float(np.max(np.abs(
                          np.asarray([space.low, space.high])))))


class MultiAgentEnvRunner:
    """Collects per-POLICY rollout fragments from one multi-agent env.

    Fragments have the same [T, N, ...] layout as the single-agent
    runner's (N = number of agents mapped to the policy), so the PPO
    GAE/minibatch path applies unchanged per policy."""

    def __init__(self, env_factory: Callable[[], MultiAgentEnv],
                 module_specs: Dict[str, ModuleSpec],
                 policy_mapping_fn: Callable[[str], str],
                 seed: int = 0, explore: bool = True):
        self.env = env_factory()
        self.modules = {p: RLModule(spec)
                        for p, spec in module_specs.items()}
        self.mapping = {a: policy_mapping_fn(a) for a in self.env.agents}
        # policy -> its agents, in stable order (the batch row order)
        self.policy_agents: Dict[str, List[str]] = {}
        for a in self.env.agents:
            self.policy_agents.setdefault(self.mapping[a], []).append(a)
        unknown = set(self.mapping.values()) - set(module_specs)
        if unknown:
            raise ValueError(f"policy_mapping_fn produced unknown "
                             f"policies {sorted(unknown)}")
        self.explore = explore
        self._rng = jax.random.key(seed + 29)
        self._params: Dict[str, Any] = {}
        self._obs, _ = self.env.reset(seed=seed)
        self._ep_return = {a: 0.0 for a in self.env.agents}
        self._ep_returns: List[float] = []

        def make_step(module):
            def _step(params, obs, rng):
                dist = module.dist(params, obs)
                a = dist.sample(rng) if self.explore else dist.mode()
                return a, dist.log_prob(a), module.value(params, obs)

            return jax.jit(_step)

        self._steps = {p: make_step(m) for p, m in self.modules.items()}
        self._values = {p: jax.jit(m.value) for p, m in self.modules.items()}

    def set_weights(self, params_by_policy: Dict[str, Any]) -> None:
        self._params = {p: jax.tree.map(jnp.asarray, w)
                        for p, w in params_by_policy.items()}

    def _stacked_obs(self, policy: str) -> np.ndarray:
        return np.stack([self._obs[a] for a in self.policy_agents[policy]])

    def sample(self, num_steps: int) -> Dict[str, Dict[str, np.ndarray]]:
        bufs = {p: {k: [] for k in ("obs", "actions", "rewards", "dones",
                                    "terminateds", "truncateds", "logp",
                                    "values", "final_values")}
                for p in self.policy_agents}
        for _ in range(num_steps):
            actions: Dict[str, Any] = {}
            per_policy = {}
            for p, agents in self.policy_agents.items():
                obs = self._stacked_obs(p)
                self._rng, sub = jax.random.split(self._rng)
                a, logp, v = self._steps[p](self._params[p], obs, sub)
                a = np.asarray(a)
                per_policy[p] = (obs, a, np.asarray(logp), np.asarray(v))
                spec = self.modules[p].spec
                for i, agent in enumerate(agents):
                    actions[agent] = (int(a[i]) if spec.discrete
                                      else a[i] * spec.action_scale)
            nxt, rew, term, trunc, _ = self.env.step(actions)
            done_all = bool(term.get("__all__")) or bool(trunc.get("__all__"))
            for p, agents in self.policy_agents.items():
                obs, a, logp, v = per_policy[p]
                b = bufs[p]
                b["obs"].append(obs)
                b["actions"].append(a)
                b["logp"].append(logp)
                b["values"].append(v)
                b["rewards"].append(np.asarray(
                    [rew.get(ag, 0.0) for ag in agents], np.float32))
                t = np.asarray([bool(term.get(ag)) or
                                bool(term.get("__all__")) for ag in agents])
                tr = np.asarray([bool(trunc.get(ag)) or
                                 bool(trunc.get("__all__")) for ag in agents])
                b["terminateds"].append(t)
                # episode ending without termination is a truncation
                b["truncateds"].append(tr | (done_all & ~t))
                b["dones"].append(t | tr | done_all)
            for a_id in self.env.agents:
                self._ep_return[a_id] += rew.get(a_id, 0.0)
            if done_all:
                # truncation bootstrap: V(final obs) per agent
                for p, agents in self.policy_agents.items():
                    final = np.stack([nxt[ag] for ag in agents])
                    fv = np.asarray(self._values[p](self._params[p], final))
                    t = bufs[p]["terminateds"][-1]
                    bufs[p]["final_values"].append(
                        np.where(t, 0.0, fv).astype(np.float32))
                self._ep_returns.extend(self._ep_return.values())
                self._obs, _ = self.env.reset()
                self._ep_return = {a: 0.0 for a in self.env.agents}
            else:
                for p, agents in self.policy_agents.items():
                    bufs[p]["final_values"].append(
                        np.zeros(len(agents), np.float32))
                self._obs = nxt
        out: Dict[str, Dict[str, np.ndarray]] = {}
        for p, agents in self.policy_agents.items():
            b = bufs[p]
            frag = {k: np.stack(v) for k, v in b.items()}
            last_obs = self._stacked_obs(p)
            frag["last_values"] = np.asarray(
                self._values[p](self._params[p], last_obs))
            out[p] = frag
        return out

    def episode_metrics(self) -> dict:
        rets, self._ep_returns = self._ep_returns, []
        return {"episodes": len(rets),
                "return_sum": float(np.sum(rets)) if rets else 0.0}

    def evaluate(self, num_episodes: int = 5) -> dict:
        """Greedy episodes; mean per-agent return."""
        explore, self.explore = self.explore, False
        # greedy needs fresh jits? _steps closed over self.explore at
        # trace time — rebuild with mode() explicitly
        rets = []
        try:
            for _ in range(num_episodes):
                obs, _ = self.env.reset()
                total = {a: 0.0 for a in self.env.agents}
                done = False
                while not done:
                    actions = {}
                    for p, agents in self.policy_agents.items():
                        batch = np.stack([obs[a] for a in agents])
                        dist = self.modules[p].dist(self._params[p],
                                                    jnp.asarray(batch))
                        a = np.asarray(dist.mode())
                        spec = self.modules[p].spec
                        for i, agent in enumerate(agents):
                            actions[agent] = (int(a[i]) if spec.discrete
                                              else a[i] * spec.action_scale)
                    obs, rew, term, trunc, _ = self.env.step(actions)
                    for agent in self.env.agents:
                        total[agent] += rew.get(agent, 0.0)
                    done = bool(term.get("__all__")) or \
                        bool(trunc.get("__all__"))
                rets.extend(total.values())
        finally:
            self.explore = explore
            self._obs, _ = self.env.reset()
            # the sampled episode we abandoned is gone: stale partial
            # returns must not inflate the next recorded episode
            self._ep_return = {a: 0.0 for a in self.env.agents}
        return {"episode_return_mean": float(np.mean(rets))}
