"""Minimal environment API + built-in envs (numpy, CPU-side).

The reference's RLlib consumes gymnasium envs (`rllib/env/single_agent_env_runner.py`
wraps `gym.vector`); gymnasium is not in this image, so the framework ships a
gymnasium-compatible surface (`reset(seed)->(obs, info)`,
`step(a)->(obs, reward, terminated, truncated, info)`) plus classic-control
envs used by the reference's own CI (CartPole, Pendulum). User envs following
the same protocol — including real gymnasium envs, which match it exactly —
plug in via ``config.environment(env_creator)``.

Rollouts are host-side numpy by design: TPU chips run the learner update
(jitted, mesh-sharded); env physics stays on CPU in env-runner actors.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class Discrete:
    n: int

    def sample(self, rng: np.random.Generator):
        return int(rng.integers(self.n))

    @property
    def shape(self):
        return ()


@dataclasses.dataclass(frozen=True)
class Box:
    low: Any
    high: Any
    shape: Tuple[int, ...]

    def sample(self, rng: np.random.Generator):
        return rng.uniform(self.low, self.high, size=self.shape).astype(np.float32)


class Env:
    """Gymnasium-compatible single env protocol."""

    observation_space: Any
    action_space: Any

    def reset(self, *, seed: Optional[int] = None) -> Tuple[np.ndarray, dict]:
        raise NotImplementedError

    def step(self, action) -> Tuple[np.ndarray, float, bool, bool, dict]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class CartPole(Env):
    """Classic cart-pole balancing (dynamics per Barto-Sutton-Anderson 1983,
    matching gymnasium CartPole-v1: +1 reward/step, 500-step truncation)."""

    def __init__(self, max_episode_steps: int = 500):
        self.observation_space = Box(-np.inf, np.inf, (4,))
        self.action_space = Discrete(2)
        self.max_episode_steps = max_episode_steps
        self._rng = np.random.default_rng()
        self._state = None
        self._t = 0
        self.gravity = 9.8
        self.masscart, self.masspole = 1.0, 0.1
        self.length = 0.5          # half pole length
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_limit = 12 * 2 * np.pi / 360
        self.x_limit = 2.4

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=(4,))
        self._t = 0
        return self._state.astype(np.float32), {}

    def step(self, action):
        x, x_dot, theta, theta_dot = self._state
        force = self.force_mag if action == 1 else -self.force_mag
        costh, sinth = np.cos(theta), np.sin(theta)
        total_mass = self.masscart + self.masspole
        polemass_length = self.masspole * self.length
        temp = (force + polemass_length * theta_dot**2 * sinth) / total_mass
        theta_acc = (self.gravity * sinth - costh * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costh**2 / total_mass))
        x_acc = temp - polemass_length * theta_acc * costh / total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * x_acc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot])
        self._t += 1
        terminated = bool(abs(x) > self.x_limit or abs(theta) > self.theta_limit)
        truncated = self._t >= self.max_episode_steps
        return self._state.astype(np.float32), 1.0, terminated, truncated, {}


class Pendulum(Env):
    """Torque-controlled pendulum swing-up (gymnasium Pendulum-v1 dynamics)."""

    def __init__(self, max_episode_steps: int = 200):
        self.observation_space = Box(-np.inf, np.inf, (3,))
        self.action_space = Box(-2.0, 2.0, (1,))
        self.max_episode_steps = max_episode_steps
        self._rng = np.random.default_rng()
        self.dt, self.g, self.m, self.l = 0.05, 10.0, 1.0, 1.0
        self._th = self._thdot = 0.0
        self._t = 0

    def _obs(self):
        return np.array([np.cos(self._th), np.sin(self._th), self._thdot],
                        dtype=np.float32)

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._th = self._rng.uniform(-np.pi, np.pi)
        self._thdot = self._rng.uniform(-1.0, 1.0)
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0], -2.0, 2.0))
        th, thdot = self._th, self._thdot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th**2 + 0.1 * thdot**2 + 0.001 * u**2
        thdot = thdot + (3 * self.g / (2 * self.l) * np.sin(th)
                         + 3.0 / (self.m * self.l**2) * u) * self.dt
        thdot = float(np.clip(thdot, -8.0, 8.0))
        th = th + thdot * self.dt
        self._th, self._thdot = th, thdot
        self._t += 1
        return self._obs(), -cost, False, self._t >= self.max_episode_steps, {}


_REGISTRY: dict = {"CartPole-v1": CartPole, "Pendulum-v1": Pendulum}


def register_env(name: str, creator: Callable[..., Env]) -> None:
    """Reference parity: `ray.tune.registry.register_env`."""
    _REGISTRY[name] = creator


def make_env(spec, **kwargs) -> Env:
    if isinstance(spec, str):
        if spec not in _REGISTRY:
            raise ValueError(f"unknown env {spec!r}; registered: {sorted(_REGISTRY)}")
        return _REGISTRY[spec](**kwargs)
    if isinstance(spec, Env):
        return spec
    return spec(**kwargs)  # creator callable / class


class VectorEnv:
    """N independent envs stepped as a batch with auto-reset on episode end
    (the vectorization the reference gets from `gymnasium.vector.SyncVectorEnv`)."""

    def __init__(self, spec, num_envs: int, seed: int = 0, **kwargs):
        self.envs = [make_env(spec, **kwargs) for _ in range(num_envs)]
        self.num_envs = num_envs
        self.observation_space = self.envs[0].observation_space
        self.action_space = self.envs[0].action_space
        self._seed = seed
        self._returns = np.zeros(num_envs)

    def reset(self) -> np.ndarray:
        obs = [e.reset(seed=self._seed + i)[0] for i, e in enumerate(self.envs)]
        self._seed += self.num_envs
        self._returns[:] = 0.0
        return np.stack(obs)

    def step(self, actions):
        """Returns (obs, rewards, terminateds, truncateds, final_obs,
        episode_returns). Finished envs auto-reset: `obs` then holds the
        reset observation while `final_obs` holds the pre-reset one (needed
        to bootstrap through time-limit truncation, the reason gymnasium
        splits terminated from truncated). `episode_returns` carries the
        completed-episode return at finished positions (nan elsewhere)."""
        obs, rews, terms, truncs = [], [], [], []
        final_obs = np.zeros((self.num_envs,) + tuple(
            self.observation_space.shape), np.float32)
        ep_returns = np.full(self.num_envs, np.nan)
        for i, (e, a) in enumerate(zip(self.envs, actions)):
            o, r, term, trunc, _ = e.step(a)
            self._returns[i] += r
            final_obs[i] = o
            if term or trunc:
                ep_returns[i] = self._returns[i]
                self._returns[i] = 0.0
                o, _ = e.reset(seed=self._seed)
                self._seed += 1
            obs.append(o)
            rews.append(r)
            terms.append(term)
            truncs.append(trunc)
        return (np.stack(obs), np.array(rews, dtype=np.float32),
                np.array(terms, dtype=bool), np.array(truncs, dtype=bool),
                final_obs, ep_returns)

    def start(self):
        """Reset all sub-envs and zero episode-return accounting."""
        return self.reset()
