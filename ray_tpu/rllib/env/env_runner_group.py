"""EnvRunnerGroup: actor fan-out over env runners.

Parity: `rllib/env/env_runner_group.py` — remote rollout workers with
sync_weights() broadcast and fault-tolerant sampling (a dead runner is
restarted rather than failing the iteration, per the reference's
`ignore_ray_errors_on_env_runners` behavior).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core.rl_module import ModuleSpec
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner


@ray_tpu.remote
class _RemoteEnvRunner:
    def __init__(self, env_spec, module_spec, num_envs, seed, epsilon,
                 env_kwargs, env_to_module_connector=None,
                 module_to_env_connector=None):
        self.runner = SingleAgentEnvRunner(
            env_spec, module_spec, num_envs=num_envs, seed=seed, epsilon=epsilon,
            env_kwargs=env_kwargs,
            env_to_module_connector=env_to_module_connector,
            module_to_env_connector=module_to_env_connector)

    def set_weights(self, params):
        self.runner.set_weights(params)
        return True

    def sample(self, num_steps, epsilon=0.0):
        batch = self.runner.sample(num_steps, epsilon=epsilon)
        batch["_metrics"] = self.runner.episode_metrics()
        return batch

    def evaluate(self, num_episodes):
        return self.runner.evaluate(num_episodes)


class EnvRunnerGroup:
    """num_runners == 0 → a single in-process runner (reference local-worker
    mode); otherwise N runner actors sampled in parallel."""

    def __init__(self, env_spec, module_spec: ModuleSpec, *, num_runners: int = 0,
                 num_envs_per_runner: int = 1, seed: int = 0,
                 epsilon: Optional[float] = None,
                 env_kwargs: Optional[dict] = None,
                 env_to_module_connector=None,
                 module_to_env_connector=None):
        self._env_spec = env_spec
        self._module_spec = module_spec
        self._num_envs = num_envs_per_runner
        self._seed = seed
        self._epsilon = epsilon
        self._env_kwargs = dict(env_kwargs or {})
        self._e2m = env_to_module_connector
        self._m2e = module_to_env_connector
        self.num_runners = num_runners
        if num_runners == 0:
            self.local = SingleAgentEnvRunner(
                env_spec, module_spec, num_envs=num_envs_per_runner, seed=seed,
                epsilon=epsilon, env_kwargs=self._env_kwargs,
                env_to_module_connector=env_to_module_connector,
                module_to_env_connector=module_to_env_connector)
            self.actors: List = []
        else:
            self.local = None
            self.actors = [self._make_actor(i) for i in range(num_runners)]

    def _make_actor(self, i: int):
        return _RemoteEnvRunner.options(max_restarts=2).remote(
            self._env_spec, self._module_spec, self._num_envs,
            self._seed + 1000 * (i + 1), self._epsilon, self._env_kwargs,
            self._e2m, self._m2e)

    def sync_weights(self, params) -> None:
        if self.local is not None:
            self.local.set_weights(params)
        else:
            ray_tpu.get([a.set_weights.remote(params) for a in self.actors])

    def sample(self, num_steps_per_runner: int, epsilon: float = 0.0
               ) -> List[Dict[str, np.ndarray]]:
        """One rollout fragment per runner; failed runners are replaced and
        their fragment skipped this iteration."""
        if self.local is not None:
            batch = self.local.sample(num_steps_per_runner, epsilon=epsilon)
            batch["_metrics"] = self.local.episode_metrics()
            return [batch]
        refs = [a.sample.remote(num_steps_per_runner, epsilon) for a in self.actors]
        out = []
        for i, ref in enumerate(refs):
            try:
                out.append(ray_tpu.get(ref, timeout=120))
            except Exception:
                self.actors[i] = self._make_actor(i)
        return out

    def evaluate(self, num_episodes: int = 5) -> dict:
        if self.local is not None:
            return self.local.evaluate(num_episodes)
        return ray_tpu.get(self.actors[0].evaluate.remote(num_episodes))

    def stop(self) -> None:
        for a in self.actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
