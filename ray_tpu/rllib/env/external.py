"""External-env service: simulators connect over TCP and ship episodes.

Behavioral parity with the reference's external-inference EnvRunner
(`rllib/env/external/env_runner_server_for_external_inference.py`, the
`tcp_client_inference_env_runner` service): the CLIENT owns the
environment AND runs inference locally — the server pushes policy
weights down (`set_state` with a monotonically increasing seq-no) and
turns the episode stream coming back into the [T, N, ...] batches the
learners consume. One client per runner (reference assumption).

Wire protocol: length-prefixed pickled dicts
  client -> server: {"type": "hello"}
                    {"type": "episodes", "episodes": [...]}   (bulk)
                    {"type": "ping"}
  server -> client: {"type": "set_config", "config": {...}}
                    {"type": "set_state", "weights": ..., "seq_no": n}
                    {"type": "pong"}
An episode dict carries obs/actions/rewards (+ optional logp/values for
GAE-based learners) and terminated/truncated flags.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np


def send_msg(sock: socket.socket, msg: dict) -> None:
    data = pickle.dumps(msg)
    sock.sendall(struct.pack("<I", len(data)) + data)


def recv_msg(sock: socket.socket) -> Optional[dict]:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack("<I", hdr)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(1 << 16, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return pickle.loads(buf)


class ExternalEnvServer:
    """EnvRunner-shaped server for ONE external simulator client.

    Drop-in for the sampling side of SingleAgentEnvRunner: set_weights()
    pushes to the client; sample(num_steps) blocks until the episode
    stream covers the request and returns the standard [T, 1, ...]
    batch."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 config: Optional[dict] = None):
        self._srv = socket.create_server((host, port))
        self.port = self._srv.getsockname()[1]
        self.config = config or {}
        self._client: Optional[socket.socket] = None
        self._client_lock = threading.Lock()
        # serializes every send on the client socket: set_weights (trainer
        # thread) races _client_loop replies (server thread), and two
        # interleaved sendall()s would corrupt the length-prefixed stream
        self._send_lock = threading.Lock()
        self._episodes: deque = deque()
        self._steps_buffered = 0
        self._cv = threading.Condition()
        self._weights = None
        self._seq_no = 0
        self._stop = threading.Event()
        self._ep_returns: List[float] = []
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name=f"extenv-{self.port}")
        self._thread.start()

    # ------------------------------------------------------------- server
    def _serve(self) -> None:
        self._srv.settimeout(0.5)
        while not self._stop.is_set():
            try:
                sock, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._client_lock:
                self._client = sock
            try:
                self._client_loop(sock)
            except (OSError, EOFError, pickle.PickleError):
                pass
            finally:
                with self._client_lock:
                    if self._client is sock:
                        self._client = None
                try:
                    sock.close()
                except OSError:
                    pass

    def _client_loop(self, sock: socket.socket) -> None:
        while not self._stop.is_set():
            msg = recv_msg(sock)
            if msg is None:
                return
            t = msg.get("type")
            if t == "hello":
                with self._send_lock:
                    send_msg(sock, {"type": "set_config",
                                    "config": self.config})
                with self._cv:
                    weights, seq = self._weights, self._seq_no
                if weights is not None:
                    with self._send_lock:
                        send_msg(sock, {"type": "set_state",
                                        "weights": weights,
                                        "seq_no": seq})
            elif t == "ping":
                with self._send_lock:
                    send_msg(sock, {"type": "pong"})
            elif t == "episodes":
                with self._cv:
                    for ep in msg["episodes"]:
                        steps = len(ep["actions"])
                        self._episodes.append(ep)
                        self._steps_buffered += steps
                        self._ep_returns.append(
                            float(np.sum(ep["rewards"])))
                    self._cv.notify_all()

    # ----------------------------------------------- EnvRunner interface
    def set_weights(self, params) -> None:
        """New policy weights: bump seq-no and push to the live client
        (reference WEIGHTS_SEQ_NO semantics)."""
        import jax

        host = jax.tree.map(np.asarray, params)
        with self._cv:
            self._weights = host
            self._seq_no += 1
            seq = self._seq_no
        with self._client_lock:
            sock = self._client
        if sock is not None:
            try:
                with self._send_lock:
                    send_msg(sock, {"type": "set_state", "weights": host,
                                    "seq_no": seq})
            except OSError:
                pass

    @property
    def weights_seq_no(self) -> int:
        return self._seq_no

    def sample(self, num_steps: int, epsilon: float = 0.0,
               timeout: float = 60.0) -> Dict[str, np.ndarray]:
        """Block until the client has shipped >= num_steps env steps;
        return the standard [T, N=1, ...] batch."""
        deadline = time.monotonic() + timeout
        eps: List[dict] = []
        got = 0
        with self._cv:
            while got < num_steps:
                while not self._episodes:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise TimeoutError(
                            f"external client shipped {got}/{num_steps} "
                            f"steps in {timeout}s")
                    self._cv.wait(left)
                ep = self._episodes.popleft()
                n = len(ep["actions"])
                self._steps_buffered -= n
                got += n
                eps.append(ep)

        def cat(key, default=None):
            parts = []
            for ep in eps:
                if key in ep:
                    parts.append(np.asarray(ep[key]))
                elif default is not None:
                    parts.append(np.full(len(ep["actions"]), default,
                                         np.float32))
                else:
                    raise KeyError(key)
            return np.concatenate(parts)

        T = got
        obs = cat("obs").astype(np.float32)
        terms = np.zeros(T, bool)
        truncs = np.zeros(T, bool)
        next_obs_seq = np.concatenate(
            [np.asarray(ep.get("next_obs", ep["obs"])) for ep in eps]
        ).astype(np.float32)
        i = 0
        for ep in eps:
            n = len(ep["actions"])
            terms[i + n - 1] = bool(ep.get("terminated", True))
            truncs[i + n - 1] = bool(ep.get("truncated", False)) \
                and not terms[i + n - 1]
            i += n
        batch = {
            "obs": obs[:, None],
            "actions": cat("actions")[:, None],
            "rewards": cat("rewards").astype(np.float32)[:, None],
            "terminateds": terms[:, None],
            "truncateds": truncs[:, None],
            "dones": (terms | truncs)[:, None],
            "next_obs_seq": next_obs_seq[:, None],
            "logp": cat("logp", 0.0).astype(np.float32)[:, None],
            "values": cat("values", 0.0).astype(np.float32)[:, None],
            "final_values": np.zeros((T, 1), np.float32),
            "next_obs": next_obs_seq[-1:][:].astype(np.float32),
            "last_values": np.zeros((1,), np.float32),
        }
        return batch

    def episode_metrics(self) -> dict:
        rets, self._ep_returns = self._ep_returns, []
        return {"episodes": len(rets),
                "episode_return_mean": float(np.mean(rets)) if rets
                else float("nan")}

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._client_lock:
            if self._client is not None:
                try:
                    self._client.close()
                except OSError:
                    pass


class ExternalEnvClient:
    """Reference client helper (the simulator side): connect, receive
    config/weights, ship episodes. Real deployments embed this loop in
    the game/simulator process."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        send_msg(self.sock, {"type": "hello"})
        self.config: dict = {}
        self.weights = None
        self.seq_no = -1
        msg = recv_msg(self.sock)
        if msg and msg.get("type") == "set_config":
            self.config = msg["config"]

    def poll(self, timeout: float = 0.1) -> None:
        """Drain pending server messages (weight updates)."""
        self.sock.settimeout(timeout)
        try:
            while True:
                msg = recv_msg(self.sock)
                if msg is None:
                    return
                if msg.get("type") == "set_state":
                    self.weights = msg["weights"]
                    self.seq_no = msg["seq_no"]
        except socket.timeout:
            pass
        finally:
            self.sock.settimeout(None)

    def wait_for_weights(self, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        while self.weights is None:
            if time.monotonic() > deadline:
                raise TimeoutError("no weights from server")
            self.poll(0.2)

    def send_episodes(self, episodes: List[dict]) -> None:
        send_msg(self.sock, {"type": "episodes", "episodes": episodes})

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
