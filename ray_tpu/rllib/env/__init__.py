"""env subpackage."""
