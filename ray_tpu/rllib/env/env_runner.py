"""EnvRunner: vectorized rollout collection with a jitted policy step.

Parity: `rllib/env/single_agent_env_runner.py` (sample() over vectorized
gymnasium envs) — but the action-selection path is one jitted JAX function,
so on-device inference batches across the env vector.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.core.rl_module import RLModule, ModuleSpec
from ray_tpu.rllib.env.envs import VectorEnv


class SingleAgentEnvRunner:
    """Collects fixed-length rollout fragments; usable in-process or as an
    actor via EnvRunnerGroup (`rllib/env/env_runner_group.py`)."""

    def __init__(self, env_spec, module_spec: ModuleSpec, num_envs: int = 1,
                 seed: int = 0, explore: bool = True,
                 epsilon: Optional[float] = None, env_kwargs: Optional[dict] = None,
                 env_to_module_connector=None,
                 module_to_env_connector=None):
        from ray_tpu.rllib.connectors import build_pipeline

        self._env_spec = env_spec
        self._env_kwargs = dict(env_kwargs or {})
        # connector pipelines (reference rllib/connectors): per-runner
        # stateful transforms between env and module
        self.env_to_module = build_pipeline(env_to_module_connector)
        self.module_to_env = build_pipeline(module_to_env_connector)
        self.vec = VectorEnv(env_spec, num_envs, seed=seed, **self._env_kwargs)
        self.module = RLModule(module_spec)
        self.explore = explore
        self.epsilon = epsilon  # when set: epsilon-greedy over q-values (DQN)
        self._rng = jax.random.key(seed + 17)
        self._obs = self.vec.start()
        self._ep_returns: list = []
        self._params = None

        @jax.jit
        def _step(params, obs, rng, eps):
            dist = self.module.dist(params, obs)
            k1, k2, k3 = jax.random.split(rng, 3)
            if self.epsilon is not None:
                greedy = dist.mode()
                rand = jax.random.randint(k2, greedy.shape, 0,
                                          self.module.spec.action_dim)
                take_rand = jax.random.uniform(k3, greedy.shape) < eps
                a = jnp.where(take_rand, rand, greedy)
                logp = jnp.zeros(a.shape[0])
            elif self.explore:
                a = dist.sample(k1)
                logp = dist.log_prob(a)
            else:
                a = dist.mode()
                logp = dist.log_prob(a)
            v = self.module.value(params, obs)
            return a, logp, v

        self._policy_step = _step
        self._greedy_step = jax.jit(
            lambda params, obs: self.module.dist(params, obs).mode())
        self._value_fn = jax.jit(self.module.value)

    def set_weights(self, params) -> None:
        self._params = jax.tree.map(jnp.asarray, params)

    def get_weights(self):
        return self._params

    def sample(self, num_steps: int, epsilon: float = 0.0) -> Dict[str, np.ndarray]:
        """Collect `num_steps` env steps per sub-env. Returns a flat batch with
        [T, N, ...] leaves plus bootstrap values for GAE."""
        assert self._params is not None, "set_weights() before sample()"
        obs_buf, act_buf, rew_buf, logp_buf, val_buf = ([] for _ in range(5))
        term_buf, trunc_buf, next_buf, finalv_buf = ([] for _ in range(4))
        for _ in range(num_steps):
            self._rng, sub = jax.random.split(self._rng)
            mod_obs = (self.env_to_module(self._obs)
                       if self.env_to_module else self._obs)
            a, logp, v = self._policy_step(self._params, mod_obs, sub,
                                           jnp.float32(epsilon))
            a_np = np.asarray(a)
            obs_buf.append(mod_obs)
            env_a = a_np if self.module.spec.discrete else \
                a_np * self.module.spec.action_scale
            if self.module_to_env is not None:
                env_a = self.module_to_env(env_a)
            next_obs, r, term, trunc, final_obs, ep_ret = self.vec.step(env_a)
            act_buf.append(a_np)
            rew_buf.append(r)
            term_buf.append(term)
            trunc_buf.append(trunc)
            # the true successor state: pre-reset final obs at episode ends
            next_buf.append(final_obs)
            # V(final_obs) where truncated (not terminated): lets consumers
            # bootstrap through time limits (gymnasium-correct semantics)
            boot = trunc & ~term
            fv = np.zeros(self.vec.num_envs, np.float32)
            if boot.any():
                bobs = (self.env_to_module.transform(final_obs[boot])
                        if self.env_to_module else final_obs[boot])
                fv[boot] = np.asarray(self._value_fn(self._params, bobs))
            finalv_buf.append(fv)
            logp_buf.append(np.asarray(logp))
            val_buf.append(np.asarray(v))
            self._ep_returns.extend(ep_ret[~np.isnan(ep_ret)].tolist())
            self._obs = next_obs
        self._rng, sub = jax.random.split(self._rng)
        tail_obs = (self.env_to_module.transform(self._obs)
                    if self.env_to_module else self._obs)
        _, _, last_v = self._policy_step(self._params, tail_obs, sub,
                                         jnp.float32(epsilon))
        terms = np.stack(term_buf)
        truncs = np.stack(trunc_buf)
        return {
            "obs": np.stack(obs_buf), "actions": np.stack(act_buf),
            "rewards": np.stack(rew_buf), "dones": terms | truncs,
            "terminateds": terms, "truncateds": truncs,
            "next_obs_seq": np.stack(next_buf),
            "final_values": np.stack(finalv_buf),
            "logp": np.stack(logp_buf), "values": np.stack(val_buf),
            "next_obs": self._obs.copy(), "last_values": np.asarray(last_v),
        }

    def episode_metrics(self) -> dict:
        """Drain completed-episode returns collected since the last call."""
        rets, self._ep_returns = self._ep_returns, []
        return {"episodes": len(rets),
                "episode_return_mean": float(np.mean(rets)) if rets else float("nan")}

    def evaluate(self, num_episodes: int = 5) -> dict:
        """Greedy evaluation on a fresh env (same spec + kwargs as training)."""
        from ray_tpu.rllib.env.envs import make_env

        env = make_env(self._env_spec, **self._env_kwargs)
        rets = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=10_000 + ep)
            total, done = 0.0, False
            while not done:
                a = np.asarray(self._greedy_step(self._params, obs[None]))[0]
                if not self.module.spec.discrete:
                    a = a * self.module.spec.action_scale
                obs, r, term, trunc, _ = env.step(a)
                total += r
                done = term or trunc
            rets.append(total)
        return {"episode_return_mean": float(np.mean(rets))}
