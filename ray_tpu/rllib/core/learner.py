"""Learner: jitted, mesh-shardable gradient updates.

Parity: `rllib/core/learner/learner.py:106` + the torch DDP learner
(`rllib/core/learner/torch/torch_learner.py:432`) — re-done the XLA way: one
jitted `update(state, batch) -> (state, metrics)` whose batch is sharded over
the mesh's `dp` axis, so data-parallel gradient averaging is an XLA psum over
ICI instead of NCCL DDP hooks.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.core.rl_module import RLModule, ModuleSpec

Params = Any


class JaxLearner:
    """Subclasses define `loss(params, batch, rng) -> (scalar, metrics)`."""

    def __init__(self, module_spec: ModuleSpec, *, lr: float = 3e-4,
                 grad_clip: Optional[float] = 0.5, seed: int = 0,
                 mesh: Optional[jax.sharding.Mesh] = None):
        self.module = RLModule(module_spec)
        self.mesh = mesh
        tx = [optax.clip_by_global_norm(grad_clip)] if grad_clip else []
        self.optimizer = optax.chain(*tx, optax.adam(lr))
        self._rng = jax.random.key(seed)
        self.params = self.module.init(jax.random.key(seed + 1))
        self.opt_state = self.optimizer.init(self.params)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(mesh, P())
            self.params = jax.device_put(self.params, repl)
            self.opt_state = jax.device_put(self.opt_state, repl)
        self._update = self._build_update()

    # ----------------------------------------------------------------- loss
    def loss(self, params: Params, batch: Dict[str, jnp.ndarray], rng
             ) -> Tuple[jnp.ndarray, dict]:
        raise NotImplementedError

    # --------------------------------------------------------------- update
    def _build_update(self) -> Callable:
        def step(params, opt_state, batch, rng):
            (l, metrics), grads = jax.value_and_grad(self.loss, has_aux=True)(
                params, batch, rng)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics = {**metrics, "total_loss": l,
                       "grad_norm": optax.global_norm(grads)}
            return params, opt_state, metrics

        # sharding comes from input placement (_shard_batch + the replicated
        # params committed in __init__); XLA inserts the dp-axis grad psum
        return jax.jit(step)

    def _shard_batch(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        # "_"-prefixed keys are auxiliary pytrees (e.g. DQN target params):
        # replicated, never row-sharded
        if self.mesh is None:
            return {k: jax.tree.map(jnp.asarray, v) if k.startswith("_")
                    else jnp.asarray(v) for k, v in batch.items()}
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(self.mesh, P())
        row = NamedSharding(self.mesh, P("dp"))
        ndp = self.mesh.shape["dp"]
        out = {}
        for k, v in batch.items():
            if k.startswith("_"):
                out[k] = jax.device_put(v, repl)
            else:
                n = (v.shape[0] // ndp) * ndp  # drop the ragged tail
                out[k] = jax.device_put(np.asarray(v[:n]), row)
        return out

    def update(self, batch: Dict[str, np.ndarray]) -> dict:
        self._rng, sub = jax.random.split(self._rng)
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, self._shard_batch(batch), sub)
        return {k: float(v) for k, v in metrics.items()}

    # ---------------------------------------------------------- checkpoints
    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, params) -> None:
        self.params = jax.tree.map(jnp.asarray, params)

    def get_state(self) -> dict:
        return {"params": jax.tree.map(np.asarray, self.params),
                "opt_state": jax.tree.map(
                    lambda x: np.asarray(x) if isinstance(x, jnp.ndarray) else x,
                    self.opt_state)}

    def set_state(self, state: dict) -> None:
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.opt_state = jax.tree.map(
            lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x,
            state["opt_state"])
