"""RLModule: policy/value networks + action distributions, plain JAX.

Parity target: the reference's RLModule abstraction
(`rllib/core/rl_module/rl_module.py` — forward_inference / forward_exploration
/ forward_train) re-done as pure functions over parameter pytrees so the whole
learner update jits and shards under a mesh (pjit DP), instead of torch
modules wrapped in DDP (`rllib/core/learner/torch/torch_learner.py:432`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Any


def _init_mlp(rng, sizes: Sequence[int], scale_last: float = 0.01) -> Params:
    """Orthogonal-init MLP (the reference's default for PPO-style nets)."""
    layers = []
    keys = jax.random.split(rng, len(sizes) - 1)
    for i, k in enumerate(keys):
        fan_in, fan_out = sizes[i], sizes[i + 1]
        w = jax.nn.initializers.orthogonal(
            np.sqrt(2) if i < len(keys) - 1 else scale_last)(
                k, (fan_in, fan_out), jnp.float32)
        layers.append({"w": w, "b": jnp.zeros((fan_out,), jnp.float32)})
    return layers


def _apply_mlp(layers: Params, x: jnp.ndarray) -> jnp.ndarray:
    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(layers) - 1:
            x = jnp.tanh(x)
    return x


@dataclasses.dataclass(frozen=True)
class ModuleSpec:
    """What the reference calls RLModuleSpec (`rllib/core/rl_module/rl_module.py`)."""
    obs_dim: int
    action_dim: int
    discrete: bool
    hiddens: Tuple[int, ...] = (64, 64)
    # DQN-style modules output one Q-value per action instead of a policy head
    q_network: bool = False
    # SAC-style modules: tanh-squashed state-dependent Gaussian + twin Q(s,a)
    squashed: bool = False
    # Box envs with bounds beyond [-1, 1]: policy outputs are scaled by this
    action_scale: float = 1.0


class RLModule:
    """Separate policy and value MLP towers (reference default catalog config)."""

    def __init__(self, spec: ModuleSpec):
        self.spec = spec

    def init(self, rng) -> Params:
        s = self.spec
        k_pi, k_v, k_q1, k_q2 = jax.random.split(rng, 4)
        head = 2 * s.action_dim if s.squashed else s.action_dim
        params = {
            "pi": _init_mlp(k_pi, (s.obs_dim, *s.hiddens, head),
                            scale_last=1.0 if s.q_network else 0.01),
            "vf": _init_mlp(k_v, (s.obs_dim, *s.hiddens, 1), scale_last=1.0),
        }
        if s.squashed:
            params["q1"] = _init_mlp(
                k_q1, (s.obs_dim + s.action_dim, *s.hiddens, 1), scale_last=1.0)
            params["q2"] = _init_mlp(
                k_q2, (s.obs_dim + s.action_dim, *s.hiddens, 1), scale_last=1.0)
        elif not s.discrete and not s.q_network:
            params["log_std"] = jnp.zeros((s.action_dim,), jnp.float32)
        return params

    def q_values(self, params: Params, obs, act) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x = jnp.concatenate([obs, act], axis=-1)
        return (_apply_mlp(params["q1"], x)[..., 0],
                _apply_mlp(params["q2"], x)[..., 0])

    # --- forward passes (reference: forward_inference/_exploration/_train) ---
    def value(self, params: Params, obs) -> jnp.ndarray:
        return _apply_mlp(params["vf"], obs)[..., 0]

    def pi_out(self, params: Params, obs) -> jnp.ndarray:
        """Logits (discrete / q_network) or mean (continuous)."""
        return _apply_mlp(params["pi"], obs)

    def dist(self, params: Params, obs):
        out = self.pi_out(params, obs)
        if self.spec.discrete or self.spec.q_network:
            return Categorical(out)
        if self.spec.squashed:
            mean, log_std = jnp.split(out, 2, axis=-1)
            return SquashedGaussian(mean, jnp.clip(log_std, -20.0, 2.0))
        return DiagGaussian(out, params["log_std"])


class Categorical:
    def __init__(self, logits):
        self.logits = logits - jax.scipy.special.logsumexp(
            logits, axis=-1, keepdims=True)

    def sample(self, rng):
        return jax.random.categorical(rng, self.logits)

    def log_prob(self, a):
        return jnp.take_along_axis(
            self.logits, a[..., None].astype(jnp.int32), axis=-1)[..., 0]

    def entropy(self):
        p = jnp.exp(self.logits)
        return -jnp.sum(p * self.logits, axis=-1)

    def kl(self, other: "Categorical"):
        p = jnp.exp(self.logits)
        return jnp.sum(p * (self.logits - other.logits), axis=-1)

    def mode(self):
        return jnp.argmax(self.logits, axis=-1)


class DiagGaussian:
    def __init__(self, mean, log_std):
        self.mean, self.log_std = mean, log_std

    def sample(self, rng):
        return self.mean + jnp.exp(self.log_std) * jax.random.normal(
            rng, self.mean.shape)

    def log_prob(self, a):
        var = jnp.exp(2 * self.log_std)
        return jnp.sum(-((a - self.mean) ** 2) / (2 * var) - self.log_std
                       - 0.5 * jnp.log(2 * jnp.pi), axis=-1)

    def entropy(self):
        return jnp.sum(self.log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)

    def kl(self, other: "DiagGaussian"):
        var, ovar = jnp.exp(2 * self.log_std), jnp.exp(2 * other.log_std)
        return jnp.sum(other.log_std - self.log_std
                       + (var + (self.mean - other.mean) ** 2) / (2 * ovar)
                       - 0.5, axis=-1)

    def mode(self):
        return self.mean


class SquashedGaussian:
    """tanh(Normal) with the change-of-variables log-prob correction
    (reference: `rllib/models/torch/torch_distributions.py` TorchSquashedGaussian)."""

    def __init__(self, mean, log_std):
        self.mean, self.log_std = mean, log_std

    def _base(self):
        return DiagGaussian(self.mean, self.log_std)

    def sample_with_logp(self, rng):
        u = self.mean + jnp.exp(self.log_std) * jax.random.normal(
            rng, self.mean.shape)
        a = jnp.tanh(u)
        # log|det tanh'(u)| = sum 2(log2 - u - softplus(-2u))
        logp = self._base().log_prob(u) - jnp.sum(
            2 * (jnp.log(2.0) - u - jax.nn.softplus(-2 * u)), axis=-1)
        return a, logp

    def sample(self, rng):
        return self.sample_with_logp(rng)[0]

    def log_prob(self, a):
        a = jnp.clip(a, -1 + 1e-6, 1 - 1e-6)
        u = jnp.arctanh(a)
        return self._base().log_prob(u) - jnp.sum(
            2 * (jnp.log(2.0) - u - jax.nn.softplus(-2 * u)), axis=-1)

    def entropy(self):
        return self._base().entropy()  # gaussian entropy (upper bound)

    def mode(self):
        return jnp.tanh(self.mean)


def spec_from_env(env) -> ModuleSpec:
    from ray_tpu.rllib.env.envs import Discrete

    space = env.action_space
    if isinstance(space, Discrete):
        return ModuleSpec(obs_dim=int(np.prod(env.observation_space.shape)),
                          action_dim=space.n, discrete=True)
    return ModuleSpec(obs_dim=int(np.prod(env.observation_space.shape)),
                      action_dim=int(np.prod(space.shape)), discrete=False,
                      action_scale=float(np.max(np.abs(
                          np.asarray([space.low, space.high])))))
