"""core subpackage."""
