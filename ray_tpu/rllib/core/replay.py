"""Uniform replay buffer for off-policy algorithms (DQN/SAC).

Parity: `rllib/utils/replay_buffers/` (EpisodeReplayBuffer, uniform sampling)
— numpy ring buffer on the learner host; sampled minibatches move to device
per update.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, obs_dim: int, discrete: bool,
                 action_dim: int = 1, seed: int = 0):
        self.capacity = capacity
        self._rng = np.random.default_rng(seed)
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        if discrete:
            self.actions = np.zeros((capacity,), np.int32)
        else:
            self.actions = np.zeros((capacity, action_dim), np.float32)
        self.rewards = np.zeros((capacity,), np.float32)
        self.dones = np.zeros((capacity,), np.float32)
        self.size = 0
        self._idx = 0

    def add_batch(self, obs, actions, rewards, dones, next_obs) -> None:
        """Add [T, N, ...] rollout leaves transition-by-transition. next_obs
        here is obs shifted by one step with the final vector-env obs last."""
        T, N = rewards.shape
        flat = lambda x: x.reshape(T * N, *x.shape[2:])
        for o, a, r, d, no in zip(flat(obs), flat(actions), flat(rewards),
                                  flat(dones), flat(next_obs)):
            i = self._idx
            self.obs[i], self.actions[i] = o, a
            self.rewards[i], self.dones[i], self.next_obs[i] = r, d, no
            self._idx = (i + 1) % self.capacity
            self.size = min(self.size + 1, self.capacity)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self.size, size=batch_size)
        return {"obs": self.obs[idx], "actions": self.actions[idx],
                "rewards": self.rewards[idx], "dones": self.dones[idx],
                "next_obs": self.next_obs[idx]}
