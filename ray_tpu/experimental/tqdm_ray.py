"""Cluster-safe tqdm: progress bars from any worker, rendered in one place.

Capability-equivalent of the reference's `ray.experimental.tqdm_ray`
(`python/ray/experimental/tqdm_ray.py`): worker processes forward bar
updates to a central manager so concurrent bars from many processes don't
corrupt each other's terminal output. Updates are batched (at most ~10/s per
bar) to keep actor-call overhead negligible.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Any, Iterable, Optional

import ray_tpu

_MANAGER_NAME = "_tqdm_ray_manager"
_lock = threading.Lock()


@ray_tpu.remote
class _TqdmManager:
    """Holds the real tqdm bars; all processes funnel updates here."""

    def __init__(self):
        self._bars = {}
        self._positions = {}   # bar_id -> terminal row (freed on close)

    def _alloc_position(self, bar_id: str) -> int:
        used = set(self._positions.values())
        pos = 0
        while pos in used:
            pos += 1
        self._positions[bar_id] = pos
        return pos

    def update(self, bar_id: str, desc: str, total: Optional[int],
               delta: int, close: bool = False):
        try:
            import tqdm as _tqdm
            if bar_id not in self._bars and not close:
                self._bars[bar_id] = _tqdm.tqdm(
                    desc=desc, total=total,
                    position=self._alloc_position(bar_id))
            bar = self._bars.get(bar_id)
            if bar is None:
                return True
            if bar.desc != desc:
                bar.set_description(desc, refresh=False)
            if delta:
                bar.update(delta)
            if close:
                bar.close()
                del self._bars[bar_id]
                self._positions.pop(bar_id, None)
        except Exception:
            pass
        return True


def _manager():
    with _lock:
        try:
            return ray_tpu.get_actor(_MANAGER_NAME)
        except Exception:
            return _TqdmManager.options(
                name=_MANAGER_NAME, get_if_exists=True, lifetime="detached",
                max_concurrency=16).remote()


class tqdm:
    """Drop-in tqdm for remote tasks/actors.

    Example (inside a remote function):
        from ray_tpu.experimental import tqdm_ray
        for row in tqdm_ray.tqdm(rows, desc="scoring"):
            ...
    """

    def __init__(self, iterable: Optional[Iterable] = None, desc: str = "",
                 total: Optional[int] = None, flush_interval_s: float = 0.1,
                 **_ignored: Any):
        self._iterable = iterable
        self.desc = desc
        if total is None and iterable is not None:
            try:
                total = len(iterable)  # type: ignore[arg-type]
            except TypeError:
                total = None
        self.total = total
        self._id = uuid.uuid4().hex
        self._pending = 0
        self._last_flush = 0.0
        self._flush_interval = flush_interval_s
        self._closed = False
        self._mgr = _manager()
        self._flush(force=True)  # create the bar eagerly

    def update(self, n: int = 1) -> None:
        self._pending += n
        self._flush()

    def set_description(self, desc: str) -> None:
        self.desc = desc
        self._flush(force=True)

    def _flush(self, force: bool = False, close: bool = False) -> None:
        now = time.monotonic()
        if not (force or close) and now - self._last_flush < self._flush_interval:
            return
        self._last_flush = now
        delta, self._pending = self._pending, 0
        try:
            self._mgr.update.remote(self._id, self.desc, self.total, delta,
                                    close)
        except Exception:
            pass

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._flush(close=True)

    def refresh(self) -> None:
        self._flush(force=True)

    def __iter__(self):
        if self._iterable is None:
            raise TypeError("this tqdm was not given an iterable")
        try:
            for item in self._iterable:
                yield item
                self.update(1)
        finally:
            self.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def safe_print(*args: Any, **kwargs: Any) -> None:
    """Print without corrupting active bars (reference tqdm_ray.safe_print)."""
    print(*args, **kwargs)
