"""Per-process device object store: zero-copy `jax.Array` handoff.

Capability parity with the reference's RDT / GPU object store
(`python/ray/experimental/gpu_object_manager/gpu_object_manager.py:22-56`):
device-resident values (jax Arrays, or pytrees containing them) stay in
the producing process — only a small meta (kind="device") travels through
the control plane. A same-process `get()` returns the LIVING value with
no copy (buffer identity preserved); a cross-process `get()` asks the
owner worker's direct server for a host-serialized snapshot and, for a
top-level jax.Array, re-materializes it on the consumer's default device.

Why per-process: TPU HBM buffers are PJRT process-local — true
cross-process device sharing does not exist; the workable design is
owner-resident values + on-demand transfer (host staging today, ICI
send/recv via the collective layer for gang-scheduled meshes).

Lifetime rides the distributed refcounting layer: the head's directory
entry for a device object pins it; when the head drops the object it
tells the owner worker to release the value.
"""

from __future__ import annotations

import sys
import threading
from typing import Any, Dict, Optional

from ray_tpu.core.ids import ObjectID


def _nbytes_estimate(value: Any) -> int:
    jax = sys.modules.get("jax")
    if jax is not None and isinstance(value, jax.Array):
        return int(value.size) * value.dtype.itemsize
    import numpy as np

    if isinstance(value, np.ndarray):
        return value.nbytes
    try:
        import jax.tree_util as jtu

        return sum(_nbytes_estimate(leaf) for leaf in jtu.tree_leaves(value)
                   if leaf is not value)
    except Exception:
        return 0


def is_device_value(value: Any) -> bool:
    jax = sys.modules.get("jax")
    return jax is not None and isinstance(value, jax.Array)


class DeviceObjectStore:
    """Values held alive by the owning process, keyed by ObjectID."""

    def __init__(self):
        self._objects: Dict[ObjectID, Any] = {}
        self._lock = threading.Lock()

    def put(self, oid: ObjectID, value: Any) -> int:
        with self._lock:
            self._objects[oid] = value
        return _nbytes_estimate(value)

    def get(self, oid: ObjectID) -> Any:
        with self._lock:
            return self._objects[oid]

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._objects

    def pop(self, oid: ObjectID) -> Optional[Any]:
        with self._lock:
            return self._objects.pop(oid, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._objects)


# consumer-side rematerialization now lives in device_transport (leaves
# are tagged at serialization and re-placed inside load_snapshot)
