"""runtime_env: per-task/actor env vars, working_dir, py_modules.

Parity (core subset) with `python/ray/_private/runtime_env/` + the per-node
agent (`runtime_env_agent.py:165 GetOrCreateRuntimeEnv`): the driver
packages local directories into the cluster KV (content-addressed zips);
executing workers download + extract once per process and apply env vars /
sys.path / cwd around the user code. Supported keys: `env_vars` (dict),
`working_dir` (local dir path or previously-packaged URI), `py_modules`
(list of dir paths). conda/pip/container isolation is not reproducible
without network access and is intentionally out of scope (gated with a
clear error).
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile
from typing import Any, Dict, List, Optional

from ray_tpu.utils.platform import STATE_DIR

_EXTRACT_CACHE: Dict[str, str] = {}   # uri -> extracted dir (per process)
_UNSUPPORTED = ("conda", "uv", "container", "image_uri", "java_jars")
_SUPPORTED = ("env_vars", "working_dir", "py_modules", "pip", "pip_key")


def _zip_dir(path: str, prefix: str = "") -> bytes:
    """prefix: entry-name prefix inside the zip — py_modules zips keep the
    module dir name so `import <basename>` works after extraction (Ray's
    documented py_modules semantics)."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in
                       ("__pycache__", ".git", ".venv", "node_modules")]
            for f in files:
                full = os.path.join(root, f)
                rel = os.path.relpath(full, path)
                zf.write(full, os.path.join(prefix, rel) if prefix else rel)
    return buf.getvalue()


def package_runtime_env(client, renv: Optional[dict]) -> Optional[dict]:
    """Driver side: normalize + upload dirs → content-addressed URIs."""
    if not renv:
        return None
    for key in _UNSUPPORTED:
        if renv.get(key):
            raise ValueError(
                f"runtime_env[{key!r}] is not supported in this offline "
                "build; ship dependencies via py_modules/working_dir")
    unknown = set(renv) - set(_SUPPORTED) - set(_UNSUPPORTED)
    if unknown:
        # a typo'd key silently vanishing means the task runs without the
        # intended environment — fail loudly instead
        raise ValueError(f"unknown runtime_env key(s): {sorted(unknown)}; "
                         f"supported: {list(_SUPPORTED)}")
    out: Dict[str, Any] = {}
    if renv.get("env_vars"):
        out["env_vars"] = {str(k): str(v) for k, v in renv["env_vars"].items()}

    def upload(path: str, prefix: str = "") -> str:
        if path.startswith("rtenv://"):
            return path
        if not os.path.isdir(path):
            raise ValueError(f"runtime_env dir {path!r} does not exist")
        data = _zip_dir(path, prefix)
        digest = hashlib.sha256(data).hexdigest()[:24]
        uri = f"rtenv://{digest}"
        # probe before shipping: re-uploading a multi-MB zip per call when
        # the head already has the digest is pure waste
        if not client.head_request("kv_keys", ns="_runtime_env",
                                   prefix=uri.encode()):
            client.head_request("kv_put", ns="_runtime_env",
                                key=uri.encode(), value=data, overwrite=False)
        return uri

    if renv.get("pip"):
        pip = renv["pip"]
        if isinstance(pip, dict):
            pip = pip.get("packages", [])
        pip = sorted(str(p) for p in pip)
        out["pip"] = pip
        out["pip_key"] = pip_env_key(pip)
    if renv.get("working_dir"):
        out["working_dir"] = upload(renv["working_dir"])
    if renv.get("py_modules"):
        # each entry is a MODULE directory; keep its name inside the zip so
        # `import <basename>` works on the worker
        out["py_modules"] = [
            upload(p, prefix=os.path.basename(os.path.normpath(p)))
            for p in renv["py_modules"]]
    return out or None


def pip_env_key(pip: List[str]) -> str:
    """Content address of a pip requirement set — the worker-pool routing
    key (reference: runtime env hash keying per-env worker pools,
    `worker_pool.h` per-runtime-env pools)."""
    import json

    return hashlib.sha256(
        json.dumps(sorted(pip)).encode()).hexdigest()[:16]


def materialize_venv(pip: List[str], key: Optional[str] = None) -> str:
    """Node side: build (or reuse) a content-addressed virtualenv with
    `pip` installed; returns its python executable. Parity with the
    reference's pip plugin (`python/ray/_private/runtime_env/pip.py` +
    `agent/runtime_env_agent.py:298 GetOrCreateRuntimeEnv`).

    The venv is created with --system-site-packages so the base image's
    jax/numpy stay visible; installed requirements shadow them. Offline
    clusters point pip at local wheels the standard way (PIP_NO_INDEX /
    PIP_FIND_LINKS env vars, which pip reads natively).

    Concurrency: first creator wins via atomic rename; losers reuse."""
    import shutil
    import subprocess

    key = key or pip_env_key(pip)
    root = os.path.join(STATE_DIR, "venvs")
    dest = os.path.join(root, key)
    python = os.path.join(dest, "bin", "python")
    marker = os.path.join(dest, ".rtpu_ready")
    if os.path.exists(marker):
        return python
    os.makedirs(root, exist_ok=True)
    tmp = dest + f".tmp{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    subprocess.run([sys.executable, "-m", "venv", "--system-site-packages",
                    tmp], check=True, capture_output=True)
    # When THIS interpreter is itself a venv (common: /opt/venv images),
    # --system-site-packages resolves to the BASE python's site dir, not
    # ours — jax/numpy/cloudpickle would vanish. Graft our site-packages
    # in via a .pth (processed after the venv's own dir, so installed
    # requirements still shadow the parent's versions).
    parent_sites = [p for p in sys.path
                    if p.endswith(("site-packages", "dist-packages"))
                    and os.path.isdir(p)]
    if parent_sites:
        vsite = os.path.join(
            tmp, "lib", f"python{sys.version_info[0]}.{sys.version_info[1]}",
            "site-packages")
        with open(os.path.join(vsite, "_rtpu_parent_env.pth"), "w") as f:
            f.write("\n".join(parent_sites) + "\n")
    if pip:
        proc = subprocess.run(
            [os.path.join(tmp, "bin", "python"), "-m", "pip", "install",
             "--no-input", "--disable-pip-version-check", *pip],
            capture_output=True, text=True)
        if proc.returncode != 0:
            shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeError(
                f"pip install {pip} failed:\n{proc.stdout}{proc.stderr}")
    with open(os.path.join(tmp, ".rtpu_ready"), "w") as f:
        f.write(" ".join(pip))
    try:
        os.replace(tmp, dest)   # atomic publish; POSIX replaces empty only
    except OSError:
        shutil.rmtree(tmp, ignore_errors=True)
        if not os.path.exists(marker):
            raise
    return python


def _fetch_extract(client, uri: str) -> str:
    """Worker side: download a packaged URI and extract (cached per proc)."""
    if uri in _EXTRACT_CACHE:
        return _EXTRACT_CACHE[uri]
    dest = os.path.join(STATE_DIR, client.session, "runtime_env",
                        uri.replace("rtenv://", ""))
    if not os.path.isdir(dest) or not os.listdir(dest):
        data = client.head_request("kv_get", ns="_runtime_env",
                                   key=uri.encode())
        if data is None:
            raise RuntimeError(f"runtime_env package {uri} missing from KV")
        tmp = dest + f".tmp{os.getpid()}"
        os.makedirs(tmp, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(data)) as zf:
            zf.extractall(tmp)
        try:
            os.replace(tmp, dest)
        except OSError:
            # another worker won the race; use theirs
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    _EXTRACT_CACHE[uri] = dest
    return dest


class AppliedEnv:
    """Worker side: apply a normalized runtime_env; .restore() undoes the
    env-var/cwd changes (sys.path additions persist for the process, as in
    the reference's dedicated-worker model)."""

    def __init__(self, client, renv: Optional[dict]):
        self._saved_env: Dict[str, Optional[str]] = {}
        self._saved_cwd: Optional[str] = None
        if not renv:
            return
        try:
            for uri in renv.get("py_modules") or []:
                path = _fetch_extract(client, uri)
                if path not in sys.path:
                    sys.path.insert(0, path)
            if renv.get("working_dir"):
                path = _fetch_extract(client, renv["working_dir"])
                if path not in sys.path:
                    sys.path.insert(0, path)
                self._saved_cwd = os.getcwd()
                os.chdir(path)
            for k, v in (renv.get("env_vars") or {}).items():
                self._saved_env[k] = os.environ.get(k)
                os.environ[k] = v
        except BaseException:
            # partial construction must not leak cwd/env onto the pooled
            # worker (e.g. a cancel async-exc landing mid-apply)
            self.restore()
            raise

    def restore(self) -> None:
        for k, old in self._saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        if self._saved_cwd is not None:
            try:
                os.chdir(self._saved_cwd)
            except OSError:
                pass
