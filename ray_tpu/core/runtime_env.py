"""runtime_env: per-task/actor env vars, working_dir, py_modules.

Parity (core subset) with `python/ray/_private/runtime_env/` + the per-node
agent (`runtime_env_agent.py:165 GetOrCreateRuntimeEnv`): the driver
packages local directories into the cluster KV (content-addressed zips);
executing workers download + extract once per process and apply env vars /
sys.path / cwd around the user code. Supported keys: `env_vars` (dict),
`working_dir` (local dir path or previously-packaged URI), `py_modules`
(list of dir paths). conda/pip/container isolation is not reproducible
without network access and is intentionally out of scope (gated with a
clear error).
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile
from typing import Any, Dict, List, Optional

from ray_tpu.utils.platform import STATE_DIR

_EXTRACT_CACHE: Dict[str, str] = {}   # uri -> extracted dir (per process)
_UNSUPPORTED = ("conda", "pip", "uv", "container", "image_uri", "java_jars")
_SUPPORTED = ("env_vars", "working_dir", "py_modules")


def _zip_dir(path: str, prefix: str = "") -> bytes:
    """prefix: entry-name prefix inside the zip — py_modules zips keep the
    module dir name so `import <basename>` works after extraction (Ray's
    documented py_modules semantics)."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in
                       ("__pycache__", ".git", ".venv", "node_modules")]
            for f in files:
                full = os.path.join(root, f)
                rel = os.path.relpath(full, path)
                zf.write(full, os.path.join(prefix, rel) if prefix else rel)
    return buf.getvalue()


def package_runtime_env(client, renv: Optional[dict]) -> Optional[dict]:
    """Driver side: normalize + upload dirs → content-addressed URIs."""
    if not renv:
        return None
    for key in _UNSUPPORTED:
        if renv.get(key):
            raise ValueError(
                f"runtime_env[{key!r}] is not supported in this offline "
                "build; ship dependencies via py_modules/working_dir")
    unknown = set(renv) - set(_SUPPORTED) - set(_UNSUPPORTED)
    if unknown:
        # a typo'd key silently vanishing means the task runs without the
        # intended environment — fail loudly instead
        raise ValueError(f"unknown runtime_env key(s): {sorted(unknown)}; "
                         f"supported: {list(_SUPPORTED)}")
    out: Dict[str, Any] = {}
    if renv.get("env_vars"):
        out["env_vars"] = {str(k): str(v) for k, v in renv["env_vars"].items()}

    def upload(path: str, prefix: str = "") -> str:
        if path.startswith("rtenv://"):
            return path
        if not os.path.isdir(path):
            raise ValueError(f"runtime_env dir {path!r} does not exist")
        data = _zip_dir(path, prefix)
        digest = hashlib.sha256(data).hexdigest()[:24]
        uri = f"rtenv://{digest}"
        # probe before shipping: re-uploading a multi-MB zip per call when
        # the head already has the digest is pure waste
        if not client.head_request("kv_keys", ns="_runtime_env",
                                   prefix=uri.encode()):
            client.head_request("kv_put", ns="_runtime_env",
                                key=uri.encode(), value=data, overwrite=False)
        return uri

    if renv.get("working_dir"):
        out["working_dir"] = upload(renv["working_dir"])
    if renv.get("py_modules"):
        # each entry is a MODULE directory; keep its name inside the zip so
        # `import <basename>` works on the worker
        out["py_modules"] = [
            upload(p, prefix=os.path.basename(os.path.normpath(p)))
            for p in renv["py_modules"]]
    return out or None


def _fetch_extract(client, uri: str) -> str:
    """Worker side: download a packaged URI and extract (cached per proc)."""
    if uri in _EXTRACT_CACHE:
        return _EXTRACT_CACHE[uri]
    dest = os.path.join(STATE_DIR, client.session, "runtime_env",
                        uri.replace("rtenv://", ""))
    if not os.path.isdir(dest) or not os.listdir(dest):
        data = client.head_request("kv_get", ns="_runtime_env",
                                   key=uri.encode())
        if data is None:
            raise RuntimeError(f"runtime_env package {uri} missing from KV")
        tmp = dest + f".tmp{os.getpid()}"
        os.makedirs(tmp, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(data)) as zf:
            zf.extractall(tmp)
        try:
            os.replace(tmp, dest)
        except OSError:
            # another worker won the race; use theirs
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    _EXTRACT_CACHE[uri] = dest
    return dest


class AppliedEnv:
    """Worker side: apply a normalized runtime_env; .restore() undoes the
    env-var/cwd changes (sys.path additions persist for the process, as in
    the reference's dedicated-worker model)."""

    def __init__(self, client, renv: Optional[dict]):
        self._saved_env: Dict[str, Optional[str]] = {}
        self._saved_cwd: Optional[str] = None
        if not renv:
            return
        try:
            for uri in renv.get("py_modules") or []:
                path = _fetch_extract(client, uri)
                if path not in sys.path:
                    sys.path.insert(0, path)
            if renv.get("working_dir"):
                path = _fetch_extract(client, renv["working_dir"])
                if path not in sys.path:
                    sys.path.insert(0, path)
                self._saved_cwd = os.getcwd()
                os.chdir(path)
            for k, v in (renv.get("env_vars") or {}).items():
                self._saved_env[k] = os.environ.get(k)
                os.environ[k] = v
        except BaseException:
            # partial construction must not leak cwd/env onto the pooled
            # worker (e.g. a cancel async-exc landing mid-apply)
            self.restore()
            raise

    def restore(self) -> None:
        for k, old in self._saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        if self._saved_cwd is not None:
            try:
                os.chdir(self._saved_cwd)
            except OSError:
                pass
