"""Asyncio message transport: length-prefixed pickle frames + RPC layer.

Plays the role of the reference's gRPC wrappers (`src/ray/rpc/`): typed
request/reply with correlation ids over persistent connections, plus
server-push messages. Includes the reference's `rpc_chaos`-style fault
injection (SURVEY.md §4.2 pattern 4) grown into a deterministic fault
plane: seeded per-method/per-edge drop, delay, and duplicate delivery,
nth-call triggers, timed partition windows, and process-kill schedules —
so tests can reproduce exact failure interleavings via config, not
external tooling (see `configure_chaos` / README "Failure model").
"""

from __future__ import annotations

import asyncio
import fnmatch
import itertools
import os
import pickle
import random
import time as _time
from collections import deque
from typing import Any, Awaitable, Callable, Dict, List, Optional

HEADER = 12  # u64 pickle-payload length + u32 out-of-band buffer count

# --- fault injection (env: RAY_TPU_TESTING_RPC_FAILURE="method:prob") -------
_chaos: Dict[str, float] = {}

# --- RPC interposition: every outbound request/push is reported as
# (connection_name, kind, method) with kind in {"req", "push"}. The warm-path
# scheduling tests count head-bound traffic through this hook to PROVE a
# dispatch never touched the head (same role as the reference's rpc_chaos
# interposition layer, minus the fault). Interposers that accept extra
# keyword arguments additionally receive "rep" events when a request's
# reply lands, carrying duration_s — the flight recorder's per-RPC
# latency feed (core/flight_recorder.py) rides this without changing the
# 3-arg hooks tests already use.
_interposers: list = []   # (fn, wants_extra)
_n_extra = 0              # count of extra-accepting interposers


def _wants_extra(fn) -> bool:
    import inspect

    try:
        params = list(inspect.signature(fn).parameters.values())
    except (TypeError, ValueError):
        return False
    return (len(params) > 3
            or any(p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
                   for p in params))


def add_rpc_interposer(fn) -> None:
    global _n_extra
    wants = _wants_extra(fn)
    _interposers.append((fn, wants))
    if wants:
        _n_extra += 1


def remove_rpc_interposer(fn) -> None:
    global _n_extra
    for ent in list(_interposers):
        if ent[0] is fn:
            _interposers.remove(ent)
            if ent[1]:
                _n_extra -= 1
            return


def _interpose(name: str, kind: str, method: str, **extra) -> None:
    for fn, wants in _interposers:
        try:
            if wants:
                fn(name, kind, method, **extra)
            elif kind in ("req", "push"):
                # 3-arg hooks keep the original req/push-only contract —
                # reply and chaos events exist only for extra-kwarg
                # interposers (the flight recorder)
                fn(name, kind, method)
        except Exception:
            pass


# ------------------------------------------------------------ chaos plane
# Deterministic fault plans (reference `rpc_chaos.h` grown up): every rule
# names a fault KIND, a method glob, optionally an edge (connection-name)
# glob, and a trigger. Same seed + same spec ⇒ the same injected-fault
# sequence. Every injection is reported through the RPC interposers as a
# "chaos" event, which the flight recorder turns into
# `chaos_injected_total{method,kind}` — injected faults are observable on
# /metrics, not invisible test magic.

CHAOS_KINDS = ("drop", "delay", "dup", "partition", "kill")


class _ChaosRule:
    __slots__ = ("kind", "method", "edge", "nth", "every", "prob",
                 "delay_s", "after_s", "for_s", "count", "rng")

    def __init__(self, kind: str, method: str = "*", edge: str = "*",
                 nth: Optional[int] = None, every: Optional[int] = None,
                 prob: Optional[float] = None, delay_s: float = 0.0,
                 after_s: Optional[float] = None,
                 for_s: Optional[float] = None):
        self.kind, self.method, self.edge = kind, method, edge
        self.nth, self.every, self.prob = nth, every, prob
        self.delay_s, self.after_s, self.for_s = delay_s, after_s, for_s
        self.count = 0
        self.rng: Optional[random.Random] = None


class ChaosPlan:
    """A parsed fault plan: rules + a seed. Trigger state (per-rule call
    counters, per-rule seeded PRNGs) lives here, so two plans built from
    the same spec replay the identical fault sequence."""

    def __init__(self, rules: List[_ChaosRule], seed: int = 0):
        self.rules = rules
        self.seed = seed
        self.t0 = _time.monotonic()
        self.injected: List[tuple] = []  # (method, kind) log, bounded
        for i, r in enumerate(rules):
            if r.prob is not None:
                # int-derived per-rule stream: reproducible, and rule order
                # in the spec is part of the plan identity
                r.rng = random.Random(seed * 1_000_003 + i)

    @classmethod
    def parse(cls, spec: str) -> "ChaosPlan":
        """Build a plan from a spec string, ignoring legacy 'method:prob'
        parts (configure_chaos routes those to the probabilistic table)."""
        rules, seed, _legacy = _parse_chaos_spec(spec)
        return cls(rules, seed)

    # ------------------------------------------------------------ decisions
    def _window_open(self, r: _ChaosRule) -> bool:
        if r.after_s is None and r.for_s is None:
            return True
        dt = _time.monotonic() - self.t0
        start = r.after_s or 0.0
        return dt >= start and (r.for_s is None or dt < start + r.for_s)

    def _fires(self, r: _ChaosRule) -> bool:
        r.count += 1
        if r.nth is not None:
            return r.count == r.nth
        if r.every is not None:
            return r.count % r.every == 0
        if r.rng is not None:
            return r.rng.random() < r.prob
        return True

    def _record(self, edge: str, method: str, kind: str) -> None:
        if len(self.injected) < 10_000:
            self.injected.append((method, kind))
        _interpose(edge, "chaos", method, chaos_kind=kind)

    def partitioned(self, edge: str) -> bool:
        """True while a partition rule's window severs this edge."""
        for r in self.rules:
            if (r.kind == "partition"
                    and fnmatch.fnmatchcase(edge, r.edge)
                    and self._window_open(r)):
                return True
        return False

    def actions(self, edge: str, method: str) -> List[_ChaosRule]:
        """Evaluate all non-partition rules for one outbound message;
        fired rules are recorded and returned for the caller to apply."""
        out: List[_ChaosRule] = []
        for r in self.rules:
            if r.kind == "partition":
                continue
            if not fnmatch.fnmatchcase(method, r.method):
                continue
            if not fnmatch.fnmatchcase(edge, r.edge):
                continue
            if not self._window_open(r):
                continue
            if self._fires(r):
                self._record(edge, method, r.kind)
                out.append(r)
        return out


def _parse_chaos_rule(part: str) -> _ChaosRule:
    fields = part.split(":")
    kind = fields[0]
    kw: dict = {}
    pos = 1
    if len(fields) > 1 and "=" not in fields[1]:
        target = fields[1]
        pos = 2
        if kind == "partition":
            kw["edge"] = target  # partition targets an EDGE, not a method
        elif "@" in target:
            kw["method"], kw["edge"] = target.split("@", 1)
        else:
            kw["method"] = target
    for f in fields[pos:]:
        if "=" not in f:
            raise ValueError(f"bad chaos rule arg {f!r} in {part!r}")
        k, v = f.split("=", 1)
        if k == "n":
            kw["nth"] = int(v)
        elif k == "every":
            kw["every"] = int(v)
        elif k == "p":
            kw["prob"] = float(v)
        elif k == "t":
            kw["delay_s"] = float(v)
        elif k == "after":
            kw["after_s"] = float(v)
        elif k == "for":
            kw["for_s"] = float(v)
        else:
            raise ValueError(f"unknown chaos rule arg {k!r} in {part!r}")
    return _ChaosRule(kind, **kw)


def _parse_chaos_spec(spec: Optional[str]):
    """Split a spec into (plan rules, seed, legacy {method: prob})."""
    rules: List[_ChaosRule] = []
    legacy: Dict[str, float] = {}
    seed = 0
    for part in filter(None, (p.strip() for p in (spec or "").split(","))):
        if part.startswith("seed="):
            seed = int(part[5:])
        elif part.split(":", 1)[0] in CHAOS_KINDS:
            rules.append(_parse_chaos_rule(part))
        else:
            method, prob = part.rsplit(":", 1)
            legacy[method] = float(prob)
    return rules, seed, legacy


_chaos_plan: Optional[ChaosPlan] = None


def configure_chaos(spec: Optional[str] = None) -> None:
    """(Re)configure fault injection from a spec string. Legacy
    'method:prob' parts keep their probabilistic-drop semantics; parts
    with a kind prefix (drop/delay/dup/partition/kill) build a seeded
    deterministic ChaosPlan. With no argument, reads both the legacy
    `testing_rpc_failure` flag and the `chaos` flag (RAY_TPU_CHAOS)."""
    global _chaos_plan
    _chaos.clear()
    if spec is None:
        from ray_tpu.core import config as _config

        spec = ",".join(filter(None, (_config.get("testing_rpc_failure"),
                                      _config.get("chaos"))))
    rules, seed, legacy = _parse_chaos_spec(spec)
    _chaos.update(legacy)
    _chaos_plan = ChaosPlan(rules, seed) if rules else None


def get_chaos_plan() -> Optional[ChaosPlan]:
    return _chaos_plan


configure_chaos()


def enable_eager_tasks(loop) -> None:
    """Python 3.12 eager tasks: a dispatched handler runs synchronously up
    to its first true suspension instead of paying a full schedule round
    trip — most control-plane handlers (task_done, put_meta, ref_update)
    complete without ever suspending, so this removes the dominant
    per-message event-loop cost."""
    factory = getattr(asyncio, "eager_task_factory", None)
    if factory is not None:
        loop.set_task_factory(factory)


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class RemoteError(RpcError):
    """The handler raised; carries the remote traceback string."""

    def __init__(self, message: str, cause: Optional[BaseException] = None):
        super().__init__(message)
        self.cause = cause


async def read_frame(reader: asyncio.StreamReader) -> Any:
    try:
        header = await reader.readexactly(HEADER)
        payload = await reader.readexactly(
            int.from_bytes(header[:8], "little"))
        n_bufs = int.from_bytes(header[8:12], "little")
        buffers = []
        for _ in range(n_bufs):
            ln = int.from_bytes(await reader.readexactly(8), "little")
            buffers.append(await reader.readexactly(ln))
    except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError) as e:
        raise ConnectionLost(str(e)) from e
    return pickle.loads(payload, buffers=buffers)


def _set_nodelay(writer) -> None:
    """Small request/reply frames + Nagle's algorithm = ~40ms stalls per
    round trip; every control-plane socket must be TCP_NODELAY."""
    import socket as _socket

    sock = writer.get_extra_info("socket")
    if sock is not None:
        try:
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        except OSError:
            pass


def write_frame(writer: asyncio.StreamWriter, msg: Any) -> None:
    """Frame = header + pickle payload + out-of-band buffers.

    `pickle.PickleBuffer`-wrapped values in `msg` travel as separate
    buffers, skipping pickle's in-band copy on both sides — the bulk-data
    path (object chunk transfer) rides this zero-copy."""
    buffers: list = []
    payload = pickle.dumps(msg, protocol=5, buffer_callback=buffers.append)
    writer.write(len(payload).to_bytes(8, "little")
                 + len(buffers).to_bytes(4, "little") + payload)
    for b in buffers:
        raw = b.raw()
        writer.write(raw.nbytes.to_bytes(8, "little"))
        writer.write(raw if raw.contiguous else bytes(raw))


class Connection:
    """Bidirectional RPC over one TCP connection.

    Either side may call `request`; either side serves via its handler table.
    Message shapes: ("req", id, method, args_dict), ("rep", id, result),
    ("err", id, repr_string), ("push", method, args_dict).
    """

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 handlers: Optional[Dict[str, Callable[..., Awaitable[Any]]]] = None,
                 name: str = "?"):
        self.reader, self.writer = reader, writer
        self.handlers = handlers or {}
        self.name = name
        self._seq = itertools.count()
        self._pending: Dict[int, asyncio.Future] = {}
        self._task: Optional[asyncio.Task] = None
        self._closed = asyncio.Event()
        self.on_close: Optional[Callable[["Connection"], None]] = None
        # at-most-once dispatch: duplicate request frames (chaos `dup`
        # faults, or a confused peer resending on one connection) must not
        # run a handler twice — remember recently seen request ids
        self._rid_seen: set = set()
        self._rid_order: deque = deque()

    def start(self) -> None:
        self._task = asyncio.create_task(self._read_loop(), name=f"conn-{self.name}")

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    async def _read_loop(self) -> None:
        try:
            while True:
                msg = await read_frame(self.reader)
                kind = msg[0]
                if kind in ("req", "push") and _chaos_plan is not None \
                        and _chaos_plan.partitioned(self.name):
                    # inbound half of a severed edge: the frame arrived on
                    # the wire but the partition drops it before dispatch
                    # (replies still land so pre-window requests resolve)
                    _chaos_plan._record(self.name, msg[2] if kind == "req"
                                        else msg[1], "partition")
                    continue
                if kind == "req":
                    _, rid, method, kwargs = msg
                    if rid in self._rid_seen:
                        continue  # duplicate delivery: dispatched already
                    self._rid_seen.add(rid)
                    self._rid_order.append(rid)
                    if len(self._rid_order) > 2048:
                        self._rid_seen.discard(self._rid_order.popleft())
                    asyncio.create_task(self._dispatch(rid, method, kwargs))
                elif kind == "push":
                    _, method, kwargs = msg
                    asyncio.create_task(self._dispatch(None, method, kwargs))
                elif kind == "rep":
                    fut = self._pending.pop(msg[1], None)
                    if fut is not None and not fut.done():
                        fut.set_result(msg[2])
                elif kind == "err":
                    fut = self._pending.pop(msg[1], None)
                    if fut is not None and not fut.done():
                        fut.set_exception(RemoteError(msg[2]))
        except (ConnectionLost, asyncio.CancelledError):
            pass
        finally:
            self._closed.set()
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionLost(f"connection {self.name} closed"))
            self._pending.clear()
            try:
                self.writer.close()
            except Exception:
                pass
            if self.on_close:
                self.on_close(self)

    async def _dispatch(self, rid: Optional[int], method: str, kwargs: dict) -> None:
        try:
            handler = self.handlers[method]
            result = await handler(**kwargs)
            if rid is not None:
                write_frame(self.writer, ("rep", rid, result))
        except Exception as e:  # noqa: BLE001 - must serialize any failure
            import traceback

            if rid is not None:
                try:
                    write_frame(self.writer, ("err", rid, traceback.format_exc()))
                except Exception:
                    pass
            else:
                print(f"[ray_tpu] push handler {method} failed: {e}", flush=True)

    def request_future(self, rpc: str, **kwargs) -> asyncio.Future:
        """Send the request now; return the reply future without awaiting.

        Lets callers pipeline ordered requests (write in program order, await
        replies concurrently) — the role of the reference's async gRPC
        callbacks in the actor submit queue."""
        if prob := _chaos.get(rpc):
            if random.random() < prob:
                _interpose(self.name, "chaos", rpc, chaos_kind="drop")
                raise ConnectionLost(f"chaos: injected failure for {rpc}")
        acts = self._chaos_outbound(rpc)
        if _interposers:
            _interpose(self.name, "req", rpc)
        if self.closed:
            raise ConnectionLost(f"connection {self.name} already closed")
        rid = next(self._seq)
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        self._chaos_write(("req", rid, rpc, kwargs), acts)
        if _n_extra:
            t0 = _time.perf_counter()

            def _report(f, _rpc=rpc, _t0=t0):
                _interpose(self.name, "rep", _rpc,
                           duration_s=_time.perf_counter() - _t0,
                           ok=(not f.cancelled()
                               and f.exception() is None))

            fut.add_done_callback(_report)
        return fut

    async def request(self, rpc: str, **kwargs) -> Any:
        return await self.request_future(rpc, **kwargs)

    def _chaos_outbound(self, rpc: str) -> list:
        """Partition/drop raise or swallow; delay/dup return rules applied
        at frame-write time. No-op (empty list) without an active plan."""
        plan = _chaos_plan
        if plan is None:
            return ()
        if plan.partitioned(self.name):
            plan._record(self.name, rpc, "partition")
            raise ConnectionLost(
                f"chaos: partition severs edge {self.name}")
        acts = plan.actions(self.name, rpc)
        for r in acts:
            if r.kind == "kill":
                # process-kill schedule: the configured nth/every/p call
                # takes the whole process down, SIGKILL-abrupt
                import signal

                os.kill(os.getpid(), signal.SIGKILL)
            if r.kind == "drop":
                raise ConnectionLost(f"chaos: injected failure for {rpc}")
        return acts

    def _chaos_write(self, msg: tuple, acts) -> None:
        dup = any(r.kind == "dup" for r in acts)
        delay = max((r.delay_s for r in acts if r.kind == "delay"),
                    default=0.0)
        if delay > 0:
            asyncio.get_running_loop().call_later(
                delay, self._write_late, msg, dup)
            return
        write_frame(self.writer, msg)
        if dup:
            write_frame(self.writer, msg)

    def _write_late(self, msg: tuple, dup: bool) -> None:
        if self.closed:
            return
        try:
            write_frame(self.writer, msg)
            if dup:
                write_frame(self.writer, msg)
        except Exception:
            pass  # the read loop reaps the connection

    def push(self, rpc: str, **kwargs) -> None:
        if not self.closed:
            try:
                acts = self._chaos_outbound(rpc)
            except ConnectionLost:
                return  # a dropped/partitioned push vanishes silently
            if _interposers:
                _interpose(self.name, "push", rpc)
            self._chaos_write(("push", rpc, kwargs), acts)

    async def close(self) -> None:
        if self._task:
            self._task.cancel()
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass


async def connect(host: str, port: int, handlers=None, name: str = "?") -> Connection:
    reader, writer = await asyncio.open_connection(host, port)
    _set_nodelay(writer)
    conn = Connection(reader, writer, handlers, name=name)
    conn.start()
    return conn


class Server:
    """TCP server that wraps each inbound connection in a Connection."""

    def __init__(self, handlers: Dict[str, Callable[..., Awaitable[Any]]],
                 on_connect: Optional[Callable[[Connection], None]] = None,
                 name: str = "server"):
        self.handlers = handlers
        self.on_connect = on_connect
        self.name = name
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: set[Connection] = set()

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        async def handle(reader, writer):
            conn = Connection(reader, writer, dict(self.handlers), name=self.name)
            self.connections.add(conn)
            conn.on_close = self.connections.discard
            if self.on_connect:
                self.on_connect(conn)
            conn.start()

        def handle_nodelay(r, w):
            _set_nodelay(w)
            return handle(r, w)

        self._server = await asyncio.start_server(handle_nodelay, host, port)
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()
        for conn in list(self.connections):
            await conn.close()
