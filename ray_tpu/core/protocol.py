"""Asyncio message transport: length-prefixed pickle frames + RPC layer.

Plays the role of the reference's gRPC wrappers (`src/ray/rpc/`): typed
request/reply with correlation ids over persistent connections, plus
server-push messages. Includes the reference's `rpc_chaos`-style fault
injection hook (SURVEY.md §4.2 pattern 4) so tests can kill/delay specific
RPCs via config, not external tooling.
"""

from __future__ import annotations

import asyncio
import itertools
import os
import pickle
import random
import time as _time
from typing import Any, Awaitable, Callable, Dict, Optional

HEADER = 12  # u64 pickle-payload length + u32 out-of-band buffer count

# --- fault injection (env: RAY_TPU_TESTING_RPC_FAILURE="method:prob") -------
_chaos: Dict[str, float] = {}

# --- RPC interposition: every outbound request/push is reported as
# (connection_name, kind, method) with kind in {"req", "push"}. The warm-path
# scheduling tests count head-bound traffic through this hook to PROVE a
# dispatch never touched the head (same role as the reference's rpc_chaos
# interposition layer, minus the fault). Interposers that accept extra
# keyword arguments additionally receive "rep" events when a request's
# reply lands, carrying duration_s — the flight recorder's per-RPC
# latency feed (core/flight_recorder.py) rides this without changing the
# 3-arg hooks tests already use.
_interposers: list = []   # (fn, wants_extra)
_n_extra = 0              # count of extra-accepting interposers


def _wants_extra(fn) -> bool:
    import inspect

    try:
        params = list(inspect.signature(fn).parameters.values())
    except (TypeError, ValueError):
        return False
    return (len(params) > 3
            or any(p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
                   for p in params))


def add_rpc_interposer(fn) -> None:
    global _n_extra
    wants = _wants_extra(fn)
    _interposers.append((fn, wants))
    if wants:
        _n_extra += 1


def remove_rpc_interposer(fn) -> None:
    global _n_extra
    for ent in list(_interposers):
        if ent[0] is fn:
            _interposers.remove(ent)
            if ent[1]:
                _n_extra -= 1
            return


def _interpose(name: str, kind: str, method: str, **extra) -> None:
    for fn, wants in _interposers:
        try:
            if wants:
                fn(name, kind, method, **extra)
            elif kind != "rep":
                # 3-arg hooks keep the original req/push-only contract —
                # reply events exist only for extra-kwarg interposers
                fn(name, kind, method)
        except Exception:
            pass


def configure_chaos(spec: Optional[str] = None) -> None:
    _chaos.clear()
    if spec is None:
        from ray_tpu.core import config as _config

        spec = _config.get("testing_rpc_failure")
    for part in filter(None, (spec or "").split(",")):
        method, prob = part.rsplit(":", 1)
        _chaos[method] = float(prob)


configure_chaos()


def enable_eager_tasks(loop) -> None:
    """Python 3.12 eager tasks: a dispatched handler runs synchronously up
    to its first true suspension instead of paying a full schedule round
    trip — most control-plane handlers (task_done, put_meta, ref_update)
    complete without ever suspending, so this removes the dominant
    per-message event-loop cost."""
    factory = getattr(asyncio, "eager_task_factory", None)
    if factory is not None:
        loop.set_task_factory(factory)


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class RemoteError(RpcError):
    """The handler raised; carries the remote traceback string."""

    def __init__(self, message: str, cause: Optional[BaseException] = None):
        super().__init__(message)
        self.cause = cause


async def read_frame(reader: asyncio.StreamReader) -> Any:
    try:
        header = await reader.readexactly(HEADER)
        payload = await reader.readexactly(
            int.from_bytes(header[:8], "little"))
        n_bufs = int.from_bytes(header[8:12], "little")
        buffers = []
        for _ in range(n_bufs):
            ln = int.from_bytes(await reader.readexactly(8), "little")
            buffers.append(await reader.readexactly(ln))
    except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError) as e:
        raise ConnectionLost(str(e)) from e
    return pickle.loads(payload, buffers=buffers)


def _set_nodelay(writer) -> None:
    """Small request/reply frames + Nagle's algorithm = ~40ms stalls per
    round trip; every control-plane socket must be TCP_NODELAY."""
    import socket as _socket

    sock = writer.get_extra_info("socket")
    if sock is not None:
        try:
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        except OSError:
            pass


def write_frame(writer: asyncio.StreamWriter, msg: Any) -> None:
    """Frame = header + pickle payload + out-of-band buffers.

    `pickle.PickleBuffer`-wrapped values in `msg` travel as separate
    buffers, skipping pickle's in-band copy on both sides — the bulk-data
    path (object chunk transfer) rides this zero-copy."""
    buffers: list = []
    payload = pickle.dumps(msg, protocol=5, buffer_callback=buffers.append)
    writer.write(len(payload).to_bytes(8, "little")
                 + len(buffers).to_bytes(4, "little") + payload)
    for b in buffers:
        raw = b.raw()
        writer.write(raw.nbytes.to_bytes(8, "little"))
        writer.write(raw if raw.contiguous else bytes(raw))


class Connection:
    """Bidirectional RPC over one TCP connection.

    Either side may call `request`; either side serves via its handler table.
    Message shapes: ("req", id, method, args_dict), ("rep", id, result),
    ("err", id, repr_string), ("push", method, args_dict).
    """

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 handlers: Optional[Dict[str, Callable[..., Awaitable[Any]]]] = None,
                 name: str = "?"):
        self.reader, self.writer = reader, writer
        self.handlers = handlers or {}
        self.name = name
        self._seq = itertools.count()
        self._pending: Dict[int, asyncio.Future] = {}
        self._task: Optional[asyncio.Task] = None
        self._closed = asyncio.Event()
        self.on_close: Optional[Callable[["Connection"], None]] = None

    def start(self) -> None:
        self._task = asyncio.create_task(self._read_loop(), name=f"conn-{self.name}")

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    async def _read_loop(self) -> None:
        try:
            while True:
                msg = await read_frame(self.reader)
                kind = msg[0]
                if kind == "req":
                    _, rid, method, kwargs = msg
                    asyncio.create_task(self._dispatch(rid, method, kwargs))
                elif kind == "push":
                    _, method, kwargs = msg
                    asyncio.create_task(self._dispatch(None, method, kwargs))
                elif kind == "rep":
                    fut = self._pending.pop(msg[1], None)
                    if fut is not None and not fut.done():
                        fut.set_result(msg[2])
                elif kind == "err":
                    fut = self._pending.pop(msg[1], None)
                    if fut is not None and not fut.done():
                        fut.set_exception(RemoteError(msg[2]))
        except (ConnectionLost, asyncio.CancelledError):
            pass
        finally:
            self._closed.set()
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionLost(f"connection {self.name} closed"))
            self._pending.clear()
            try:
                self.writer.close()
            except Exception:
                pass
            if self.on_close:
                self.on_close(self)

    async def _dispatch(self, rid: Optional[int], method: str, kwargs: dict) -> None:
        try:
            handler = self.handlers[method]
            result = await handler(**kwargs)
            if rid is not None:
                write_frame(self.writer, ("rep", rid, result))
        except Exception as e:  # noqa: BLE001 - must serialize any failure
            import traceback

            if rid is not None:
                try:
                    write_frame(self.writer, ("err", rid, traceback.format_exc()))
                except Exception:
                    pass
            else:
                print(f"[ray_tpu] push handler {method} failed: {e}", flush=True)

    def request_future(self, rpc: str, **kwargs) -> asyncio.Future:
        """Send the request now; return the reply future without awaiting.

        Lets callers pipeline ordered requests (write in program order, await
        replies concurrently) — the role of the reference's async gRPC
        callbacks in the actor submit queue."""
        if prob := _chaos.get(rpc):
            if random.random() < prob:
                raise ConnectionLost(f"chaos: injected failure for {rpc}")
        if _interposers:
            _interpose(self.name, "req", rpc)
        if self.closed:
            raise ConnectionLost(f"connection {self.name} already closed")
        rid = next(self._seq)
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        write_frame(self.writer, ("req", rid, rpc, kwargs))
        if _n_extra:
            t0 = _time.perf_counter()

            def _report(f, _rpc=rpc, _t0=t0):
                _interpose(self.name, "rep", _rpc,
                           duration_s=_time.perf_counter() - _t0,
                           ok=(not f.cancelled()
                               and f.exception() is None))

            fut.add_done_callback(_report)
        return fut

    async def request(self, rpc: str, **kwargs) -> Any:
        return await self.request_future(rpc, **kwargs)

    def push(self, rpc: str, **kwargs) -> None:
        if not self.closed:
            if _interposers:
                _interpose(self.name, "push", rpc)
            write_frame(self.writer, ("push", rpc, kwargs))

    async def close(self) -> None:
        if self._task:
            self._task.cancel()
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass


async def connect(host: str, port: int, handlers=None, name: str = "?") -> Connection:
    reader, writer = await asyncio.open_connection(host, port)
    _set_nodelay(writer)
    conn = Connection(reader, writer, handlers, name=name)
    conn.start()
    return conn


class Server:
    """TCP server that wraps each inbound connection in a Connection."""

    def __init__(self, handlers: Dict[str, Callable[..., Awaitable[Any]]],
                 on_connect: Optional[Callable[[Connection], None]] = None,
                 name: str = "server"):
        self.handlers = handlers
        self.on_connect = on_connect
        self.name = name
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: set[Connection] = set()

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        async def handle(reader, writer):
            conn = Connection(reader, writer, dict(self.handlers), name=self.name)
            self.connections.add(conn)
            conn.on_close = self.connections.discard
            if self.on_connect:
                self.on_connect(conn)
            conn.start()

        def handle_nodelay(r, w):
            _set_nodelay(w)
            return handle(r, w)

        self._server = await asyncio.start_server(handle_nodelay, host, port)
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()
        for conn in list(self.connections):
            await conn.close()
