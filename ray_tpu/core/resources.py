"""Node resource detection, TPU chips as first-class resources.

Behavioral parity with the reference's accelerator plugin semantics
(`python/ray/_private/accelerators/tpu.py`): chip autodetect, valid chip
group sizes {1,2,4,8}, per-process visibility via TPU_VISIBLE_CHIPS, slice
labels for gang scheduling — re-derived for a JAX/PJRT world (detection via
jax.devices / env rather than /dev/accel or GKE metadata).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

VALID_TPU_CHIP_COUNTS = (1, 2, 4, 8)


def detect_num_tpu_chips() -> int:
    """Count locally attached TPU chips without initializing a backend when
    possible: explicit env override first, /dev scan next, jax last."""
    env = os.environ.get("RAY_TPU_NUM_CHIPS")
    if env is not None:
        return int(env)
    try:
        import glob

        accel = glob.glob("/dev/accel*")
        if accel:
            return len(accel)
        vfio = glob.glob("/dev/vfio/[0-9]*")
        if vfio:
            return len(vfio)
    except Exception:
        pass
    # NEVER initialize a jax backend here: detection runs in the head/daemon
    # process, must not grab a chip, and must not block on a remote PJRT
    # tunnel. A tunneled single-chip env (axon) advertises one chip.
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms.startswith(("tpu", "axon")):
        return 1
    return 0


def tpu_pod_type() -> Optional[str]:
    """Slice/pod type, e.g. 'v5e-64' (env-provided in our world)."""
    return os.environ.get("RAY_TPU_POD_TYPE") or os.environ.get("TPU_ACCELERATOR_TYPE")


def tpu_worker_id() -> int:
    return int(os.environ.get("RAY_TPU_WORKER_ID", os.environ.get("TPU_WORKER_ID", "0")))


def tpu_slice_name() -> Optional[str]:
    return os.environ.get("RAY_TPU_SLICE_NAME") or os.environ.get("TPU_NAME")


def node_resources(num_cpus: Optional[float] = None,
                   num_tpu_chips: Optional[int] = None,
                   custom: Optional[Dict[str, float]] = None) -> Dict[str, float]:
    res: Dict[str, float] = {}
    res["CPU"] = float(num_cpus if num_cpus is not None else (os.cpu_count() or 1))
    chips = num_tpu_chips if num_tpu_chips is not None else detect_num_tpu_chips()
    if chips:
        res["TPU"] = float(chips)
        pod = tpu_pod_type()
        if pod and tpu_worker_id() == 0:
            # one head-resource per slice: the gang-scheduling anchor
            res[f"TPU-{pod}-head"] = 1.0
    if custom:
        res.update(custom)
    return res


def node_labels() -> Dict[str, str]:
    labels: Dict[str, str] = {}
    if (name := tpu_slice_name()):
        labels["ray.io/tpu-slice-name"] = name
    if (pod := tpu_pod_type()):
        labels["ray.io/tpu-pod-type"] = pod
    labels["ray.io/tpu-worker-id"] = str(tpu_worker_id())
    if (topo := os.environ.get("TPU_TOPOLOGY")):
        labels["ray.io/tpu-topology"] = topo
    return labels


def strip_device_env(env: Dict[str, str]) -> Dict[str, str]:
    """Env for control-plane / CPU-only child processes: never register a TPU
    PJRT plugin or touch a device tunnel at interpreter start. Workers that
    actually run TPU tasks get the device env restored per-task (runtime_env).
    """
    env = dict(env)
    env["JAX_PLATFORMS"] = "cpu"
    # axon-style environments register a PJRT plugin from sitecustomize when
    # this is set; an empty value disables it
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return with_package_path(env)


def with_package_path(env: Dict[str, str]) -> Dict[str, str]:
    """Child processes must be able to `import ray_tpu` regardless of cwd."""
    import ray_tpu

    pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    parts = env.get("PYTHONPATH", "").split(os.pathsep) if env.get("PYTHONPATH") else []
    if pkg_parent not in parts:
        env = dict(env)
        env["PYTHONPATH"] = os.pathsep.join([pkg_parent] + parts)
    return env


def set_visible_chips(chip_ids) -> None:
    """Restrict this process to a subset of local chips (Serve replica
    pinning). Mirrors TPU_VISIBLE_CHIPS semantics."""
    os.environ["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in chip_ids)
    bounds = {1: "1,1,1", 2: "1,2,1", 4: "2,2,1", 8: "2,2,2"}
    n = len(list(chip_ids))
    if n in bounds:
        os.environ["TPU_CHIPS_PER_PROCESS_BOUNDS"] = bounds[n]
