"""Node resource detection, TPU chips as first-class resources.

Behavioral parity with the reference's accelerator plugin semantics
(`python/ray/_private/accelerators/tpu.py`): chip autodetect, valid chip
group sizes {1,2,4,8}, per-process visibility via TPU_VISIBLE_CHIPS, slice
labels for gang scheduling — re-derived for a JAX/PJRT world (detection via
jax.devices / env rather than /dev/accel or GKE metadata).
"""

from __future__ import annotations

import os
from ray_tpu.core import config as _config
from typing import Dict, Optional

VALID_TPU_CHIP_COUNTS = (1, 2, 4, 8)


def detect_num_tpu_chips() -> int:
    """Count locally attached TPU chips without initializing a backend when
    possible: explicit env override first, /dev scan next, jax last."""
    override = _config.get("num_chips")
    if override >= 0:
        return override
    try:
        import glob

        accel = glob.glob("/dev/accel*")
        if accel:
            return len(accel)
        vfio = glob.glob("/dev/vfio/[0-9]*")
        if vfio:
            return len(vfio)
    except Exception:
        pass
    # NEVER initialize a jax backend here: detection runs in the head/daemon
    # process, must not grab a chip, and must not block on a remote PJRT
    # tunnel. A tunneled single-chip env (axon) advertises one chip.
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms.startswith(("tpu", "axon")):
        return 1
    return 0


# --------------------------------------------------- GKE/GCE pod metadata
# Reference: `python/ray/_private/accelerators/tpu.py:326-433` — GKE pods
# preset env vars; GCE TPU VMs expose the same facts via the metadata
# server. Without this, multi-host pod bring-up cannot self-label slices
# and gang scheduling needs hand-set env vars on every host.
GCE_METADATA_ENDPOINT = (
    "http://metadata.google.internal/computeMetadata/v1/instance/attributes/")
_gce_cache: Dict[str, Optional[str]] = {}
_gce_down = False


def _gce_metadata(key: str) -> Optional[str]:
    """One metadata-server attribute; cached, fast-fails permanently for
    the process once the server proves unreachable (non-GCP hosts).
    `RAY_TPU_GCE_METADATA_ENDPOINT` overrides the endpoint (tests point it
    at a local mock; also enables probing on chip-less hosts)."""
    global _gce_down
    if key in _gce_cache:
        return _gce_cache[key]
    endpoint = _config.get("gce_metadata_endpoint") or GCE_METADATA_ENDPOINT
    if _gce_down and endpoint == GCE_METADATA_ENDPOINT:
        return None
    import urllib.error
    import urllib.request

    req = urllib.request.Request(endpoint.rstrip("/") + "/" + key,
                                 headers={"Metadata-Flavor": "Google"})
    try:
        with urllib.request.urlopen(req, timeout=2) as resp:
            value = resp.read().decode() if resp.status == 200 else None
    except (urllib.error.URLError, OSError, TimeoutError):
        _gce_down = True
        value = None
    _gce_cache[key] = value
    return value


def _probe_metadata() -> bool:
    """Only touch the metadata server when this host plausibly has TPUs
    (or a test mock endpoint is set) — CPU-only nodes must not pay a
    resolve timeout at every bring-up."""
    return (bool(_config.get("gce_metadata_endpoint"))
            or detect_num_tpu_chips() > 0)


def tpu_pod_type() -> Optional[str]:
    """Slice/pod type, e.g. 'v5e-64': env (GKE presets it) → GCE
    metadata `accelerator-type`."""
    explicit = (_config.get("pod_type")
                or os.environ.get("TPU_ACCELERATOR_TYPE"))
    if explicit:
        return explicit
    if _probe_metadata():
        return _gce_metadata("accelerator-type")
    return None


def tpu_worker_id() -> int:
    # empty string == unset: lets a parent scrub inherited TPU identity
    # vars for child nodes without tripping int("")
    env = (_config.get("worker_id")
           or os.environ.get("TPU_WORKER_ID"))
    if env:
        return int(env)
    if _probe_metadata():
        mid = _gce_metadata("agent-worker-number")
        if mid is not None:
            try:
                return int(mid)
            except ValueError:
                pass
    return 0


def tpu_slice_name() -> Optional[str]:
    explicit = (_config.get("slice_name")
                or os.environ.get("TPU_NAME"))
    if explicit:
        return explicit
    if _probe_metadata():
        return _gce_metadata("instance-id")
    return None


def tpu_topology() -> Optional[str]:
    """Physical topology, e.g. '2x4': env (GKE) → GCE `tpu-env` blob."""
    if (topo := os.environ.get("TPU_TOPOLOGY")):
        return topo
    if _probe_metadata():
        blob = _gce_metadata("tpu-env")
        if blob:
            import re

            m = re.search(r"TOPOLOGY:\s*'([^']+)'", blob)
            if m:
                return m.group(1)
    return None


def node_resources(num_cpus: Optional[float] = None,
                   num_tpu_chips: Optional[int] = None,
                   custom: Optional[Dict[str, float]] = None) -> Dict[str, float]:
    res: Dict[str, float] = {}
    res["CPU"] = float(num_cpus if num_cpus is not None else (os.cpu_count() or 1))
    chips = num_tpu_chips if num_tpu_chips is not None else detect_num_tpu_chips()
    if chips:
        res["TPU"] = float(chips)
        pod = tpu_pod_type()
        if pod and tpu_worker_id() == 0:
            # one head-resource per slice: the gang-scheduling anchor
            res[f"TPU-{pod}-head"] = 1.0
    if custom:
        res.update(custom)
    return res


def node_labels() -> Dict[str, str]:
    labels: Dict[str, str] = {}
    if (name := tpu_slice_name()):
        labels["ray.io/tpu-slice-name"] = name
    if (pod := tpu_pod_type()):
        labels["ray.io/tpu-pod-type"] = pod
    labels["ray.io/tpu-worker-id"] = str(tpu_worker_id())
    if (topo := tpu_topology()):
        labels["ray.io/tpu-topology"] = topo
    return labels


def strip_device_env(env: Dict[str, str]) -> Dict[str, str]:
    """Env for control-plane / CPU-only child processes: never register a TPU
    PJRT plugin or touch a device tunnel at interpreter start. Workers that
    actually run TPU tasks get the device env restored per-task (runtime_env).
    """
    env = dict(env)
    env["JAX_PLATFORMS"] = "cpu"
    # axon-style environments register a PJRT plugin from sitecustomize when
    # this is set; an empty value disables it
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return with_package_path(env)


def with_package_path(env: Dict[str, str]) -> Dict[str, str]:
    """Child processes must be able to `import ray_tpu` regardless of cwd."""
    import ray_tpu

    pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    parts = env.get("PYTHONPATH", "").split(os.pathsep) if env.get("PYTHONPATH") else []
    if pkg_parent not in parts:
        env = dict(env)
        env["PYTHONPATH"] = os.pathsep.join([pkg_parent] + parts)
    return env


def set_visible_chips(chip_ids) -> None:
    """Restrict this process to a subset of local chips (Serve replica
    pinning). Mirrors TPU_VISIBLE_CHIPS semantics."""
    os.environ["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in chip_ids)
    bounds = {1: "1,1,1", 2: "1,2,1", 4: "2,2,1", 8: "2,2,2"}
    n = len(list(chip_ids))
    if n in bounds:
        os.environ["TPU_CHIPS_PER_PROCESS_BOUNDS"] = bounds[n]
