"""ObjectRef: a future-like handle to a (possibly not yet created) object."""

from __future__ import annotations

from typing import Optional

from ray_tpu.core.ids import ObjectID


class ObjectRef:
    __slots__ = ("id",)

    def __init__(self, object_id: ObjectID):
        assert isinstance(object_id, ObjectID)
        self.id = object_id

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __hash__(self):
        return hash(self.id)

    def __repr__(self):
        return f"ObjectRef({self.id.hex()[:12]})"

    def __reduce__(self):
        return (ObjectRef, (self.id,))

    # `await ref` inside async actors / drivers with a running loop
    def __await__(self):
        from ray_tpu.core.api import _global_client

        client = _global_client()
        return client.get_async([self]).__await__()
