"""ObjectRef: a future-like handle to a (possibly not yet created) object."""

from __future__ import annotations

from typing import Optional

from ray_tpu.core import refcount
from ray_tpu.core.ids import ObjectID


def _reconstruct_ref(object_id: ObjectID, token=None) -> "ObjectRef":
    """Unpickle path: the inc queued by ObjectRef() precedes the borrow
    commit on this process's ordered update stream, so the head records
    our hold before releasing the sender's borrow pin."""
    ref = ObjectRef(object_id)
    refcount.note_deserialized(object_id, token)
    return ref


class ObjectRef:
    __slots__ = ("id",)

    def __init__(self, object_id: ObjectID):
        assert isinstance(object_id, ObjectID)
        self.id = object_id
        # every live instance counts toward this process's interest in the
        # object (reference ReferenceCounter local refs); deserializing a
        # nested ref runs through here too
        refcount.note_created(object_id)

    def __del__(self):
        try:
            refcount.note_deleted(self.id)
        except Exception:
            pass  # interpreter teardown

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __hash__(self):
        return hash(self.id)

    def __repr__(self):
        return f"ObjectRef({self.id.hex()[:12]})"

    def __reduce__(self):
        # borrower protocol: pickling a ref opens a borrow pin at the head
        # (ordered before any later dec from this process); the token rides
        # the payload and whoever deserializes it commits the borrow
        return (_reconstruct_ref, (self.id, refcount.note_serialized(self.id)))

    # `await ref` inside async actors / drivers with a running loop
    def __await__(self):
        from ray_tpu.core.api import _global_client

        client = _global_client()
        return client.get_async([self]).__await__()


class ObjectRefGenerator:
    """Iterator over the refs a streaming task yields
    (`num_returns="streaming"`; reference ObjectRefGenerator,
    `_raylet.pyx` + SURVEY §2.12b). Each `next()` blocks until the producer
    has yielded the next value, then returns its ObjectRef."""

    def __init__(self, gen_id: ObjectID):
        self._gen_id = gen_id
        self._index = 0
        self._exhausted = False
        self._released = False

    def _release(self) -> None:
        """Tell the head we are done with this stream: undelivered items
        are unpinned head-side (abandoning a generator must not pin its
        queue forever)."""
        if self._released:
            return
        self._released = True
        try:
            from ray_tpu.core.api import _global_client

            import functools

            client = _global_client()
            client.loop.call_soon_threadsafe(functools.partial(
                client.conn.push, "generator_release",
                gen_id=self._gen_id.binary()))
        except Exception:
            pass  # no client / shutdown: head cleans up with the session

    def __del__(self):
        try:
            self._release()
        except Exception:
            pass

    def __iter__(self):
        return self

    def _advance(self, rep) -> ObjectRef:
        if rep.get("done") or self._exhausted:
            self._exhausted = True
            self._release()
            raise StopIteration
        if rep.get("error"):
            # the producer failed: yield its error ref once, then stop
            self._exhausted = True
        self._index += 1
        return ObjectRef(ObjectID(rep["ref"]))

    def __next__(self) -> ObjectRef:
        if self._exhausted:
            raise StopIteration
        from ray_tpu.core.api import _global_client

        rep = _global_client().head_request(
            "generator_next", gen_id=self._gen_id.binary(), index=self._index)
        return self._advance(rep)

    def __aiter__(self):
        return self

    async def __anext__(self) -> ObjectRef:
        if self._exhausted:
            raise StopAsyncIteration
        from ray_tpu.core.api import _global_client

        client = _global_client()
        rep = await client.conn.request(
            "generator_next", gen_id=self._gen_id.binary(), index=self._index)
        try:
            return self._advance(rep)
        except StopIteration:
            raise StopAsyncIteration from None

    def __reduce__(self):
        return (ObjectRefGenerator, (self._gen_id,))
