"""Placement groups: reserve resource bundles, run work inside the reservation.

Capability parity with `python/ray/util/placement_group.py` +
`gcs_placement_group_mgr`/2-phase bundle commit (single-node round: the
reservation is atomic against one node's ledger; multi-node prepare/commit
lands with the multi-node scheduler). Tasks/actors submitted with
`placement_group=pg` draw from the reservation instead of the free pool —
the TPU use case is gang-reserving a slice's chips ahead of SPMD training.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.core.ids import PlacementGroupID


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str, name: str = ""):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name

    def ready(self, timeout: Optional[float] = None) -> bool:
        from ray_tpu.core.api import _global_client

        reply = _global_client().head_request("wait_pg", pg_id=self.id.binary(),
                                              timeout=timeout)
        return reply["state"] == "CREATED"

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles, self.strategy, self.name))

    def __repr__(self):
        return f"PlacementGroup({self.id.hex()[:12]}, {self.strategy}, {self.bundles})"


VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    from ray_tpu.core.api import _auto_init, _global_client

    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"invalid strategy {strategy!r}; one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    _auto_init()
    pg_id = PlacementGroupID.generate()
    _global_client().head_request(
        "create_pg", pg_id=pg_id.binary(),
        bundles=[{k: float(v) for k, v in b.items()} for b in bundles],
        strategy=strategy, name=name)
    return PlacementGroup(pg_id, bundles, strategy, name)


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu.core.api import _global_client

    _global_client().head_request("remove_pg", pg_id=pg.id.binary())
