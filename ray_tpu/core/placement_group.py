"""Placement groups: reserve resource bundles, run work inside the reservation.

Capability parity with `python/ray/util/placement_group.py` +
`gcs_placement_group_mgr`/2-phase bundle commit (single-node round: the
reservation is atomic against one node's ledger; multi-node prepare/commit
lands with the multi-node scheduler). Tasks/actors submitted with
`placement_group=pg` draw from the reservation instead of the free pool —
the TPU use case is gang-reserving a slice's chips ahead of SPMD training.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.core.ids import PlacementGroupID


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str, name: str = ""):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        # creation-time state from the head's create_pg reply: when the
        # reservation committed synchronously (the common case), the first
        # ready() needs no second round trip. One-shot — a later ready()
        # re-verifies with the head (bundles can unplace on node death).
        self._created_state: Optional[str] = None

    def ready(self, timeout: Optional[float] = None) -> bool:
        from ray_tpu.core.api import _global_client

        if self._created_state == "CREATED":
            self._created_state = None
            return True
        reply = _global_client().head_request("wait_pg", pg_id=self.id.binary(),
                                              timeout=timeout)
        return reply["state"] == "CREATED"

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles, self.strategy, self.name))

    def __repr__(self):
        return f"PlacementGroup({self.id.hex()[:12]}, {self.strategy}, {self.bundles})"


VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    from ray_tpu.core.api import _auto_init, _global_client

    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"invalid strategy {strategy!r}; one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    _auto_init()
    pg_id = PlacementGroupID.generate()
    reply = _global_client().head_request(
        "create_pg", pg_id=pg_id.binary(),
        bundles=[{k: float(v) for k, v in b.items()} for b in bundles],
        strategy=strategy, name=name)
    pg = PlacementGroup(pg_id, bundles, strategy, name)
    if isinstance(reply, dict):
        pg._created_state = reply.get("state")
    return pg


def remove_placement_group(pg: PlacementGroup) -> None:
    """Fire-and-forget removal: the head needs no reply, and same-client
    ordering (a subsequent create_pg reusing the freed resources) is
    guaranteed by per-connection FIFO."""
    from ray_tpu.core.api import _global_client

    _global_client().head_push("remove_pg", pg_id=pg.id.binary())
