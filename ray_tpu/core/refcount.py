"""Client-side reference tracking for automatic object lifetime.

Capability parity with the reference's distributed ReferenceCounter
(`src/ray/core_worker/reference_count.h:73`), re-shaped for this runtime's
head-centric design: each process counts live `ObjectRef` instances per
object; the 0→1 / 1→0 transitions are batched and pushed to the head,
which keeps the global interest set (holders ∪ in-flight task deps ∪
containment edges ∪ lineage pins) and evicts objects when it empties —
so `free()` becomes optional instead of mandatory.

Delivery ordering: a process always sends inc before the matching dec,
and both ride the same head connection (FIFO), so the head never sees a
phantom release. Cross-process handoff races (producer drops its ref
while the consumer's inc is still in flight) are absorbed by the head's
eviction grace period.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Dict, List, Optional

from ray_tpu.core.ids import ObjectID

FLUSH_S = float(os.environ.get("RAY_TPU_REFCOUNT_FLUSH_S", "0.1"))

_active: Optional["RefTracker"] = None


def note_created(oid: ObjectID) -> None:
    t = _active
    if t is not None:
        t.inc(oid)


def note_deleted(oid: ObjectID) -> None:
    t = _active
    if t is not None:
        t.dec(oid)


def activate(tracker: Optional["RefTracker"]) -> None:
    global _active
    _active = tracker


class RefTracker:
    """Per-process live-ObjectRef counts; flushes transitions to the head.

    Lock-free event intake: `inc`/`dec` only append to a deque —
    `ObjectRef.__del__` can fire from a GC triggered at ANY allocation
    point (including inside this module), so taking a lock there would
    self-deadlock the thread that owns it. Counting and transition
    detection happen in `_flush`, which drains the deque in append order
    under a lock no __del__ path ever touches."""

    def __init__(self, client):
        self.client = client
        self.counts: Dict[ObjectID, int] = {}
        self._events: "deque" = deque()  # (is_inc, ObjectID), append-only
        self._flush_lock = threading.Lock()
        self._ops: List[tuple] = []      # unsent ordered transitions
        self._flush_scheduled = False
        self.enabled = os.environ.get("RAY_TPU_REFCOUNT", "1") != "0"

    def inc(self, oid: ObjectID) -> None:
        if not self.enabled:
            return
        self._events.append((True, oid))
        self._schedule()

    def dec(self, oid: ObjectID) -> None:
        if not self.enabled:
            return
        self._events.append((False, oid))
        self._schedule()

    def _schedule(self) -> None:
        # benign race on the flag: worst case an extra no-op flush.
        # Batch for FLUSH_S so ref churn costs one push, not one RPC per
        # ref (reference: batched WaitForRefRemoved).
        if self._flush_scheduled:
            return
        self._flush_scheduled = True
        try:
            self.client.loop.call_soon_threadsafe(
                lambda: self.client.loop.call_later(FLUSH_S, self._flush))
        except RuntimeError:
            self._flush_scheduled = False  # loop closed (shutdown)

    def _drain(self) -> None:
        """Fold queued events into counts; emit 0<->1 transitions in event
        order. _flush_lock held."""
        while True:
            try:
                is_inc, oid = self._events.popleft()
            except IndexError:
                return
            if is_inc:
                c = self.counts.get(oid, 0) + 1
                self.counts[oid] = c
                if c == 1:
                    self._ops.append((True, oid.binary()))
            else:
                c = self.counts.get(oid, 0) - 1
                if c > 0:
                    self.counts[oid] = c
                else:
                    self.counts.pop(oid, None)
                    self._ops.append((False, oid.binary()))

    def _flush(self) -> None:
        # drain + send under one lock: a concurrent flush slipping a newer
        # batch onto the wire while a failed older batch awaits requeue
        # would reorder inc/dec at the head
        with self._flush_lock:
            self._flush_scheduled = False
            self._drain()
            if not self._ops:
                return
            conn = self.client.conn
            if conn is None or conn.closed:
                return  # ops kept; retried on the next transition's flush
            try:
                conn.push("ref_update", ops=self._ops)
                self._ops = []
            except Exception:
                pass  # kept for retry, order preserved

    def flush_now(self) -> None:
        """Synchronous flush (tests / shutdown)."""
        self._flush()
