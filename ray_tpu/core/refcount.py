"""Client-side reference tracking for automatic object lifetime.

Capability parity with the reference's distributed ReferenceCounter
(`src/ray/core_worker/reference_count.h:73`), re-shaped for this runtime's
head-centric design: each process counts live `ObjectRef` instances per
object; the 0→1 / 1→0 transitions are batched and pushed to the head,
which keeps the global interest set (holders ∪ in-flight task deps ∪
containment edges ∪ lineage pins) and evicts objects when it empties —
so `free()` becomes optional instead of mandatory.

Delivery ordering: a process always sends inc before the matching dec,
and both ride the same head connection (FIFO), so the head never sees a
phantom release. Cross-process handoff races (producer drops its ref
while the consumer's inc is still in flight) are absorbed by the head's
eviction grace period.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from ray_tpu.core.ids import ObjectID

FLUSH_S = float(os.environ.get("RAY_TPU_REFCOUNT_FLUSH_S", "0.1"))

_active: Optional["RefTracker"] = None


def note_created(oid: ObjectID) -> None:
    t = _active
    if t is not None:
        t.inc(oid)


def note_deleted(oid: ObjectID) -> None:
    t = _active
    if t is not None:
        t.dec(oid)


def activate(tracker: Optional["RefTracker"]) -> None:
    global _active
    _active = tracker


class RefTracker:
    """Per-process live-ObjectRef counts; flushes transitions to the head."""

    def __init__(self, client):
        self.client = client
        self.counts: Dict[ObjectID, int] = {}
        self.lock = threading.Lock()
        # ordered op log: (is_inc, oid_bytes) — inc/dec interleaving for
        # one object within a batch must reach the head in order, or a
        # drop-then-reacquire inside one flush window reads as a net drop
        self._ops: List[tuple] = []
        self._flush_scheduled = False
        self.enabled = os.environ.get("RAY_TPU_REFCOUNT", "1") != "0"

    def inc(self, oid: ObjectID) -> None:
        if not self.enabled:
            return
        with self.lock:
            c = self.counts.get(oid, 0) + 1
            self.counts[oid] = c
            if c == 1:
                self._ops.append((True, oid.binary()))
                self._schedule()

    def dec(self, oid: ObjectID) -> None:
        if not self.enabled:
            return
        with self.lock:
            c = self.counts.get(oid, 0) - 1
            if c > 0:
                self.counts[oid] = c
                return
            self.counts.pop(oid, None)
            self._ops.append((False, oid.binary()))
            self._schedule()

    def _schedule(self) -> None:
        # lock held. Batch transitions for FLUSH_S so ref churn costs one
        # push, not one RPC per ref (reference: batched WaitForRefRemoved).
        if self._flush_scheduled:
            return
        self._flush_scheduled = True
        try:
            self.client.loop.call_soon_threadsafe(
                lambda: self.client.loop.call_later(FLUSH_S, self._flush))
        except RuntimeError:
            self._flush_scheduled = False  # loop closed (shutdown)

    def _flush(self) -> None:
        with self.lock:
            ops = self._ops
            self._ops = []
            self._flush_scheduled = False
        if not ops:
            return
        conn = self.client.conn
        sent = False
        if conn is not None and not conn.closed:
            try:
                conn.push("ref_update", ops=ops)
                sent = True
            except Exception:
                pass
        if not sent:
            # requeue in order: dropping a batch would lose an inc (eviction
            # of a live object) or a dec (permanent leak)
            with self.lock:
                self._ops = ops + self._ops
                self._schedule()

    def flush_now(self) -> None:
        """Synchronous flush (tests / shutdown)."""
        self._flush()
