"""Client-side reference tracking for automatic object lifetime.

Capability parity with the reference's distributed ReferenceCounter
(`src/ray/core_worker/reference_count.h:73`), re-shaped for this runtime's
head-centric design: each process counts live `ObjectRef` instances per
object; the 0→1 / 1→0 transitions are batched and pushed to the head,
which keeps the global interest set (holders ∪ in-flight task deps ∪
containment edges ∪ borrow pins ∪ lineage pins) and evicts objects when
it empties — so `free()` becomes optional instead of mandatory.

Borrower protocol (reference `reference_count.h:73` borrowers): whenever
an ObjectRef is pickled, the sender queues a `borrow_begin(oid, token)`
on the SAME ordered stream as its inc/dec transitions and embeds the
token in the pickle payload; whoever deserializes the ref queues
`borrow_commit(token)` right AFTER its own inc. The head holds a borrow
pin from begin until commit, so a ref handed off through any channel
(direct actor call, task args, KV, raw bytes) survives the sender
dropping its own refs — no eviction grace window needed. Per-stream FIFO
gives the two orderings that matter: begin-before-sender-dec and
receiver-inc-before-commit. Uncommitted borrows are released when the
sending process dies.

Enablement is negotiated, not read from each process's env: the head
reports its `refcount_enabled` in the `register_worker` reply and every
client follows it, so a process whose environment differs can never
silently stop reporting holds to a head that evicts on their absence.
Until the reply arrives the tracker queues events without sending.
"""

from __future__ import annotations

import itertools
import os
import threading
from collections import deque
from typing import Dict, List, Optional

from ray_tpu.core.ids import ObjectID

def _flush_s() -> float:
    from ray_tpu.core import config

    return config.get("refcount_flush_s")

_active: Optional["RefTracker"] = None


def note_created(oid: ObjectID) -> None:
    t = _active
    if t is not None:
        t.inc(oid)


def note_deleted(oid: ObjectID) -> None:
    t = _active
    if t is not None:
        t.dec(oid)


def note_serialized(oid: ObjectID) -> Optional[bytes]:
    """An ObjectRef is being pickled: open a borrow pin at the head.
    Returns the token to embed in the payload (None when untracked)."""
    t = _active
    if t is not None:
        return t.borrow_begin(oid)
    return None


def note_deserialized(oid: ObjectID, token: Optional[bytes]) -> None:
    """An ObjectRef was just reconstructed from a pickle payload carrying
    `token`; queued after the reconstruction's inc, so the head sees our
    hold before the borrow pin drops."""
    t = _active
    if t is not None and token is not None:
        t.borrow_commit(oid, token)


def activate(tracker: Optional["RefTracker"]) -> None:
    global _active
    _active = tracker


class RefTracker:
    """Per-process live-ObjectRef counts; flushes transitions to the head.

    Lock-free event intake: `inc`/`dec`/borrow events only append to a
    deque — `ObjectRef.__del__` can fire from a GC triggered at ANY
    allocation point (including inside this module), so taking a lock
    there would self-deadlock the thread that owns it. Counting and
    transition detection happen in `_flush`, which drains the deque in
    append order under a lock no __del__ path ever touches."""

    def __init__(self, client):
        self.client = client
        self.counts: Dict[ObjectID, int] = {}
        self._events: "deque" = deque()  # (kind, ObjectID[, token]), append-only
        self._flush_lock = threading.Lock()
        self._ops: List[tuple] = []      # unsent ordered transitions
        self._flush_scheduled = False
        # None = not yet negotiated with the head: queue but don't send.
        # Set from the head's register_worker reply (single source of truth).
        self.enabled: Optional[bool] = None
        self._token_seq = itertools.count()
        self._token_prefix = os.urandom(8)

    def set_enabled(self, value: bool) -> None:
        with self._flush_lock:
            self.enabled = bool(value)
            if not value:
                self._events.clear()
                self._ops = []
                self.counts = {}
        if value:
            self._schedule()

    def inc(self, oid: ObjectID) -> None:
        if self.enabled is False:
            return
        self._events.append(("i", oid))
        self._schedule()

    def dec(self, oid: ObjectID) -> None:
        if self.enabled is False:
            return
        self._events.append(("d", oid))
        self._schedule()

    def borrow_begin(self, oid: ObjectID) -> Optional[bytes]:
        if self.enabled is False:
            return None
        token = self._token_prefix + next(self._token_seq).to_bytes(8, "little")
        self._events.append(("b", oid, token))
        self._schedule()
        return token

    def borrow_commit(self, oid: ObjectID, token: bytes) -> None:
        if self.enabled is False:
            return
        self._events.append(("c", oid, token))
        self._schedule()

    def _schedule(self) -> None:
        # benign race on the flag: worst case an extra no-op flush.
        # Batch for FLUSH_S so ref churn costs one push, not one RPC per
        # ref (reference: batched WaitForRefRemoved).
        if self._flush_scheduled:
            return
        self._flush_scheduled = True
        try:
            self.client.loop.call_soon_threadsafe(
                lambda: self.client.loop.call_later(_flush_s(), self._flush))
        except RuntimeError:
            self._flush_scheduled = False  # loop closed (shutdown)

    def _drain(self) -> None:
        """Fold queued events into counts; emit 0<->1 transitions and
        borrow events in event order. _flush_lock held."""
        while True:
            try:
                ev = self._events.popleft()
            except IndexError:
                return
            kind, oid = ev[0], ev[1]
            if kind == "i":
                c = self.counts.get(oid, 0) + 1
                self.counts[oid] = c
                if c == 1:
                    self._ops.append(("i", oid.binary()))
            elif kind == "d":
                c = self.counts.get(oid, 0) - 1
                if c > 0:
                    self.counts[oid] = c
                else:
                    self.counts.pop(oid, None)
                    self._ops.append(("d", oid.binary()))
            else:  # borrow begin/commit ride the same ordered stream
                self._ops.append((kind, oid.binary(), ev[2]))

    def _flush(self) -> None:
        # drain + send under one lock: a concurrent flush slipping a newer
        # batch onto the wire while a failed older batch awaits requeue
        # would reorder inc/dec at the head
        with self._flush_lock:
            self._flush_scheduled = False
            self._drain()
            if not self._ops or self.enabled is not True:
                return  # enabled None: hold ops until negotiation lands
            conn = self.client.conn
            if conn is None or conn.closed:
                return  # ops kept; retried on the next transition's flush
            try:
                conn.push("ref_update", ops=self._ops)
                self._ops = []
            except Exception:
                pass  # kept for retry, order preserved

    def flush_now(self) -> None:
        """Synchronous flush (tests / shutdown)."""
        self._flush()

    def resync(self) -> None:
        """A restarted head wiped its holder state: re-announce every oid
        this process still holds, ordered BEFORE any queued transitions so
        a pending dec can never race ahead of its re-announced inc."""
        with self._flush_lock:
            self._drain()
            self._ops = [("i", oid.binary()) for oid in self.counts] \
                + self._ops
        self._flush()
