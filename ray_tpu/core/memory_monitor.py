"""Memory monitor + OOM worker-killing policy.

Parity: `src/ray/common/memory_monitor.{h,cc}` + the raylet's
`worker_killing_policy_retriable_fifo.cc` — when node memory crosses the
usage threshold, kill the worker whose task is retriable and most recently
started (LIFO over retriables: the youngest work loses, maximizing saved
progress), falling back to the youngest non-retriable. The killed task
re-queues through the normal worker-death retry path.
"""

from __future__ import annotations

import os
import time
from typing import Callable, List, Optional

from ray_tpu.core import config


def system_memory_fraction() -> float:
    """Fraction of system memory in use, from /proc/meminfo (cgroup-unaware
    fallback; containers with limits can point RAY_TPU_MEMINFO_PATH at a
    synthetic file or use the env override hook in tests)."""
    path = config.get("meminfo_path")
    total = avail = None
    try:
        with open(path) as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1])
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1])
                if total is not None and avail is not None:
                    break
    except OSError:
        return 0.0
    if not total or avail is None:
        # missing MemAvailable must fail SAFE (0.0): treating it as 100%
        # usage would kill one worker per poll interval forever
        return 0.0
    return 1.0 - avail / total


def pick_victim(workers: List[dict]) -> Optional[dict]:
    """Choose which worker to kill. `workers`: dicts with keys
    worker_id, task_start_ts, retriable (bool), is_driver, has_actor.
    Drivers and actors are never chosen (reference: only task workers)."""
    candidates = [w for w in workers
                  if not w["is_driver"] and not w["has_actor"]
                  and w.get("task_start_ts") is not None]
    if not candidates:
        return None
    retriable = [w for w in candidates if w["retriable"]]
    pool = retriable or candidates
    return max(pool, key=lambda w: w["task_start_ts"])


class MemoryMonitor:
    """Runs inside the head's event loop; polls usage, kills one victim per
    breach interval (kill → wait → resample, avoiding kill storms)."""

    def __init__(self, head, *, threshold: float = None,
                 interval_s: float = None,
                 usage_fn: Callable[[], float] = system_memory_fraction):
        self.head = head
        self.threshold = threshold if threshold is not None else float(
            config.get("memory_usage_threshold"))
        self.interval_s = interval_s if interval_s is not None else float(
            config.get("memory_monitor_interval_s"))
        self.usage_fn = usage_fn
        self.num_kills = 0

    def check_once(self) -> Optional[bytes]:
        """One poll: returns the killed worker id (or None)."""
        usage = self.usage_fn()
        if usage < self.threshold:
            return None
        # The usage sample is this (head) node's /proc/meminfo: only workers
        # co-resident on the sampled node are valid victims — killing a
        # remote worker frees nothing here and starves real OOM detection
        # on worker nodes (reference runs the monitor per-raylet).
        views = []
        for w in self.head.workers.values():
            if w.node_id != self.head.node_id:
                continue
            rec = getattr(w, "current_record", None)
            views.append({
                "worker_id": w.worker_id,
                "is_driver": w.is_driver,
                "has_actor": w.actor_id is not None,
                "task_start_ts": getattr(rec, "dispatch_ts", None)
                if rec is not None else None,
                "retriable": (rec is not None and rec.retries_left > 0),
                "_worker": w,
            })
        victim = pick_victim(views)
        if victim is None:
            return None
        w = victim["_worker"]
        self.head._task_event(
            w.running_task or b"", "", "FAILED",
            worker=w, error=f"killed by memory monitor (usage "
                            f"{usage:.0%} >= {self.threshold:.0%})")
        self.head._terminate_worker(w)
        self.num_kills += 1
        return w.worker_id.binary()

    async def run(self) -> None:
        import asyncio

        while not self.head._shutdown:
            await asyncio.sleep(self.interval_s)
            try:
                self.check_once()
            except Exception:
                pass
