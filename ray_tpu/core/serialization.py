"""Object serialization: pickle5 with out-of-band buffers.

Equivalent capability to the reference's msgpack+cloudpickle envelope with
pickle5 out-of-band buffers (`python/ray/_private/serialization.py`) — but we
only need the Python path, and jax/numpy arrays are the hot case:

- protocol-5 `buffer_callback` captures large contiguous buffers (numpy
  arrays, bytes) without copying them into the pickle stream;
- `jax.Array` on device is fetched to host memory first (device buffers are
  process-local in PJRT; zero-copy device handoff is the device object
  store's job, not the byte serializer's);
- the resulting (meta, buffers) pair maps directly onto a shared-memory
  segment: header + concatenated buffers, so readers reconstruct numpy arrays
  as zero-copy views onto shm.
"""

from __future__ import annotations

import io
import pickle
import sys
from typing import Any, List, Optional


def np_copy_into(dst_view: memoryview, offset: int, data) -> int:
    """memcpy `data` into `dst_view` at `offset`; returns bytes written.

    Plain memoryview slice assignment into an mmap-backed buffer takes
    CPython's byte-wise fallback (~30 MB/s); numpy slice assignment is a
    real memcpy (~25x faster). Every bulk copy into shm must ride this."""
    import numpy as np

    src = np.frombuffer(data, dtype=np.uint8)
    np.frombuffer(dst_view, dtype=np.uint8)[offset:offset + src.nbytes] = src
    return src.nbytes


class SerializedObject:
    """Pickle meta + list of out-of-band buffers (zero-copy where possible)."""

    __slots__ = ("meta", "buffers", "contained", "borrow_tokens")

    def __init__(self, meta: bytes, buffers: List[memoryview],
                 contained: Optional[List] = None,
                 borrow_tokens: Optional[List] = None):
        self.meta = meta
        self.buffers = buffers
        # ObjectIDs of ObjectRefs pickled inside this payload — the
        # reference-counting layer pins them while the container lives
        self.contained = contained or []
        # (ObjectID, token) borrow pins opened while pickling nested refs;
        # a sender whose payload provably never reaches a deserializer
        # (terminally failed call) self-commits these to avoid pin leaks
        self.borrow_tokens = borrow_tokens or []

    @property
    def total_bytes(self) -> int:
        return len(self.meta) + sum(b.nbytes for b in self.buffers)

    def to_bytes(self) -> bytes:
        """Flatten into one contiguous frame: [n_buffers][meta_len][meta]
        [buf_len buf]*  (lengths are 8-byte little-endian)."""
        parts = [len(self.buffers).to_bytes(8, "little"),
                 len(self.meta).to_bytes(8, "little"), self.meta]
        for b in self.buffers:
            parts.append(b.nbytes.to_bytes(8, "little"))
            parts.append(bytes(b) if not isinstance(b, bytes) else b)
        return b"".join(parts)

    def write_into(self, out: memoryview) -> int:
        """Serialize into a preallocated buffer (e.g. a shm segment)."""
        off = 0

        def put(data):
            nonlocal off
            off += np_copy_into(out, off, data)

        put(len(self.buffers).to_bytes(8, "little"))
        put(len(self.meta).to_bytes(8, "little"))
        put(self.meta)
        for b in self.buffers:
            put(b.nbytes.to_bytes(8, "little"))
            mv = memoryview(b)
            if not mv.contiguous:
                mv = memoryview(bytes(mv))
            put(mv.cast("B"))
        return off

    @property
    def frame_bytes(self) -> int:
        return 16 + len(self.meta) + sum(8 + b.nbytes for b in self.buffers)

    @classmethod
    def from_view(cls, view: memoryview) -> "SerializedObject":
        """Parse a frame, keeping buffers as zero-copy views into `view`."""
        off = 0
        n_buffers = int.from_bytes(view[off:off + 8], "little"); off += 8
        meta_len = int.from_bytes(view[off:off + 8], "little"); off += 8
        meta = bytes(view[off:off + meta_len]); off += meta_len
        buffers = []
        for _ in range(n_buffers):
            blen = int.from_bytes(view[off:off + 8], "little"); off += 8
            buffers.append(view[off:off + blen]); off += blen
        return cls(meta, buffers)


import cloudpickle


class _Pickler(cloudpickle.Pickler):
    """cloudpickle (closures/lambdas ship by value) + a reducer that lowers
    device-resident jax Arrays to host numpy (device buffers are
    process-local; zero-copy device paths use the device object store
    instead, not byte serialization)."""

    def reducer_override(self, obj):
        from ray_tpu.core.object_ref import ObjectRef, _reconstruct_ref
        from ray_tpu.core import refcount

        if type(obj) is ObjectRef:
            # record nested refs so the refcounting layer can pin them for
            # the container's lifetime (reference: borrowed refs serialized
            # into task args / returned values); the borrow token is kept
            # here too so failed handoffs can be self-released
            self.contained_refs.append(obj.id)
            token = refcount.note_serialized(obj.id)
            if token is not None:
                self.borrow_tokens.append((obj.id, token))
            return (_reconstruct_ref, (obj.id, token))
        jax = sys.modules.get("jax")
        if jax is not None and isinstance(obj, jax.Array):
            import numpy as np

            if self.device_snapshot:
                # tag the leaf so a device consumer's deserialize puts it
                # back on ITS device; the ndarray itself still pickles with
                # an out-of-band buffer (no copy into the stream)
                from ray_tpu.core.device_transport import _remat_leaf

                return (_remat_leaf, (np.asarray(obj),))
            return np.asarray(obj).__reduce_ex__(5)
        return super().reducer_override(obj)

    contained_refs: List = None  # set per instance in serialize()
    borrow_tokens: List = None
    device_snapshot: bool = False


# top-level bytes/bytearray get a marker meta + out-of-band buffer: pickle5's
# buffer_callback only captures PickleBuffer-aware types, so plain bytes would
# be copied INTO the pickle stream (measured ~1.4 vs 4.2 GB/s through the shm
# store). The marker cannot collide with a pickle stream (those start \x80).
_BYTES_META = b"RTPU:bytes"
_BYTEARRAY_META = b"RTPU:bytearray"


def serialize(value: Any, device_snapshot: bool = False) -> SerializedObject:
    if type(value) is bytes:
        return SerializedObject(_BYTES_META, [memoryview(value)])
    if type(value) is bytearray:
        return SerializedObject(_BYTEARRAY_META, [memoryview(value)])
    buffers: List[memoryview] = []

    def callback(pb: pickle.PickleBuffer):
        buffers.append(pb.raw())
        return False  # out-of-band

    sink = io.BytesIO()
    p = _Pickler(sink, protocol=5, buffer_callback=callback)
    p.contained_refs = []
    p.borrow_tokens = []
    p.device_snapshot = device_snapshot
    p.dump(value)
    return SerializedObject(sink.getvalue(), buffers,
                            contained=p.contained_refs,
                            borrow_tokens=p.borrow_tokens)


def deserialize(obj: SerializedObject) -> Any:
    if obj.meta == _BYTES_META:
        return bytes(obj.buffers[0])
    if obj.meta == _BYTEARRAY_META:
        return bytearray(obj.buffers[0])
    return pickle.loads(obj.meta, buffers=[pickle.PickleBuffer(b) for b in obj.buffers])


def dumps(value: Any) -> bytes:
    return serialize(value).to_bytes()


def loads(data: bytes) -> Any:
    return deserialize(SerializedObject.from_view(memoryview(data)))


def loads_view(view: memoryview) -> Any:
    """Deserialize from a BORROWED view without retaining it: the result
    owns its memory, so the caller may release/reuse the backing storage
    (a shm ring slot) immediately after. The common meta-only frame (no
    out-of-band buffers — e.g. serve request dicts) costs zero buffer
    copies; frames with out-of-band buffers (numpy) pay exactly one copy
    per buffer — half the memcpy pair of the staging-buffer read path."""
    obj = SerializedObject.from_view(view)
    if obj.meta == _BYTES_META:
        return bytes(obj.buffers[0])
    if obj.meta == _BYTEARRAY_META:
        return bytearray(obj.buffers[0])
    if obj.buffers:
        obj = SerializedObject(
            obj.meta, [memoryview(bytes(b)) for b in obj.buffers])
    return deserialize(obj)
