"""Head-side anomaly watchdogs over the merged workload telemetry.

A periodic pass on the head (zero extra RPCs — it reads what the gossip
plane and the metrics pusher already delivered) flags:

- **slow_pull** — object pulls whose duration landed above
  ``workload_slow_pull_s`` (delta-counted from the merged
  ``object_pull_seconds`` histograms, so each slow pull is flagged once);
- **train_straggler** — a gang member whose EWMA step time exceeds
  ``workload_straggler_factor`` x its gang's median (per-run grouping of
  the gossiped train-worker rows);
- **slo_route** — a serve route whose estimated p99 latency (from the
  merged ``serve_request_seconds`` buckets) exceeds ``serve_p99_slo_s``;
- **serve_shedding** — a route whose admission control kept shedding
  (``serve_shed_total`` deltas positive across consecutive passes): one
  shedding pass is a burst absorber doing its job; sustained shedding is
  capacity starvation the autoscaler/operator should see;
- **hotpath_regression** — drift on the compiled planes' golden signals
  (``hotpath_drift`` > 0): a ring whose stall ratio (stall seconds per
  wall second, writer+reader, delta-judged between passes) or a compiled
  chain whose gossiped p99 lands ``hotpath_drift``x above its own
  rolling EWMA baseline, plus a per-rank fused-step phase straggler
  (one rank's timed ``train_phase`` step far above the gang median, the
  slowest-vs-median phase named for attribution). Baselines freeze
  while a key is regressed so a sustained regression cannot launder
  itself into the baseline.

Anomalies land in the flight-recorder event stream
(``kind="workload_anomaly"``, visible in ``state.list_lease_events()``
and ``GET /api/workloads``) and bump
``workload_anomalies_total{kind}`` — the live-signal substrate
cluster-view-aware routing and spillback debugging route on.

`scan` is pure (telemetry in, anomalies + carried state out) so the
policies are unit-testable without a cluster.
"""

from __future__ import annotations

import statistics
from typing import Dict, List, Optional, Tuple

# a repeating condition (stuck straggler, persistently slow route) is
# re-flagged at most this often — the event stream stays readable
REFLAG_INTERVAL_S = 30.0
# workload rows older than this are a dead process's last breath, not
# live load — never judged
FRESH_S = 30.0


# a route's p99 is judged over the observations since the PREVIOUS pass
# (cumulative-since-process-start buckets would keep a recovered route
# flagging forever); windows with too few samples are skipped
MIN_WINDOW_SAMPLES = 20

# admission-control shedding must persist for this many consecutive
# passes before it's flagged (a single-pass shed burst is the bounded
# queue absorbing a spike, not an anomaly)
SHED_SUSTAIN_PASSES = 2


def _count_above(series: dict, threshold: float) -> int:
    """Observations provably above `threshold`: sum of buckets whose
    LOWER edge is >= threshold (conservative — a bucket straddling the
    threshold is not counted; the overflow bucket's lower edge is the
    last boundary)."""
    hist = series.get("histogram")
    bounds = series.get("boundaries")
    if not hist or not bounds:
        return 0
    above = 0
    for i, c in enumerate(hist["buckets"]):
        lower = bounds[i - 1] if i > 0 else 0.0
        if lower >= threshold:
            above += c
    return above


def _merge_buckets(series_list: List[dict]) -> Dict[object, int]:
    """Merge histogram series into {boundary: count, "count": total};
    overflow observations land under the "count" total only (their
    boundary is unbounded)."""
    merged: Dict[object, int] = {"count": 0}
    for s in series_list:
        hist = s.get("histogram")
        bounds = s.get("boundaries")
        if not hist or not bounds:
            continue
        for i, c in enumerate(hist["buckets"]):
            if i < len(bounds):
                merged[bounds[i]] = merged.get(bounds[i], 0) + c
        merged["count"] += hist["count"]
    return merged


def _p99_from_buckets(buckets: Dict[object, int]) -> Optional[float]:
    """Upper-bound p99: the boundary at which cumulative count reaches
    99% of the total (total includes overflow, so an overflow-heavy
    window reports the largest boundary — a floor, "worse than this")."""
    total = buckets.get("count", 0)
    bounds = sorted(b for b in buckets if b != "count")
    if total <= 0 or not bounds:
        return None
    target = 0.99 * total
    acc = 0
    for b in bounds:
        acc += buckets[b]
        if acc >= target:
            return b
    return bounds[-1]


def estimate_p99(series_list: List[dict]) -> Optional[float]:
    """Upper-bound p99 from merged histogram buckets."""
    return _p99_from_buckets(_merge_buckets(series_list))


def scan(workload_rows: List[dict],
         families: Dict[str, List[Tuple[str, dict]]],
         now: float, *, slow_pull_s: float, straggler_factor: float,
         p99_slo_s: float, hotpath_drift: float = 0.0,
         state: Optional[dict] = None
         ) -> Tuple[List[dict], dict]:
    """One watchdog pass.

    `workload_rows`: merged `__workloads__` rows ({kind, key, stats, ts,
    proc}); `families`: {metric_name: [(proc, series_dict), ...]} from
    the merged metric snapshots; `state`: the previous pass's carry
    (slow-pull high-water counts, re-flag timestamps).
    """
    state = dict(state or {})
    # a fresh state (new head, incl. post-restart) baselines the
    # cumulative counters silently on its first pass: worker histograms
    # survive the head, its high-water carry does not — flagging the
    # whole history as "new" would bury the post-recovery event stream
    primed = bool(state.get("primed"))
    state["primed"] = True
    seen: Dict = dict(state.get("slow_pull_seen") or {})
    last_flag: Dict = dict(state.get("last_flag") or {})
    anomalies: List[dict] = []

    def flag(key, anomaly: dict) -> None:
        if now - last_flag.get(key, 0.0) < REFLAG_INTERVAL_S:
            return
        last_flag[key] = now
        anomalies.append(anomaly)

    # ---- slow pulls (delta-counted per series, no re-flag needed)
    for proc, s in families.get("object_pull_seconds", ()):
        above = _count_above(s, slow_pull_s)
        skey = (proc, tuple(sorted((s.get("tags") or {}).items())))
        prev = seen.get(skey, 0)
        if above > prev and primed:
            anomalies.append({
                "anomaly": "slow_pull", "proc": proc,
                "role": (s.get("tags") or {}).get("role"),
                "count": above - prev, "threshold_s": slow_pull_s})
        if above:
            seen[skey] = above

    # ---- train-step stragglers (per-gang outliers)
    gangs: Dict[str, List[dict]] = {}
    for row in workload_rows:
        if row.get("kind") != "train_worker":
            continue
        if now - row.get("ts", 0) > FRESH_S:
            continue
        stats = row.get("stats") or {}
        gangs.setdefault(str(stats.get("run", "train")), []).append(stats)
    for run, members in gangs.items():
        steps = [m.get("ewma_step_s") for m in members
                 if m.get("ewma_step_s")]
        if len(steps) < 2:
            continue
        # median_low: in an even-sized gang the interpolated median is
        # dragged toward the straggler itself (a 2-worker gang could
        # never flag); the low median compares against the healthy half
        med = statistics.median_low(steps)
        if med <= 0:
            continue
        for m in members:
            ewma = m.get("ewma_step_s") or 0.0
            if ewma > straggler_factor * med:
                flag(("straggler", run, m.get("rank")), {
                    "anomaly": "train_straggler", "run": run,
                    "rank": m.get("rank"), "ewma_step_s": round(ewma, 4),
                    "gang_median_s": round(med, 4)})

    # ---- p99-over-SLO routes, judged over THIS pass's window (bucket
    # deltas vs the previous pass — cumulative counts would keep a
    # long-recovered route flagging forever)
    prev_routes: Dict = dict(state.get("route_hist") or {})
    new_routes: Dict = {}
    if p99_slo_s > 0:
        by_route: Dict[str, List[dict]] = {}
        for _proc, s in families.get("serve_request_seconds", ()):
            route = (s.get("tags") or {}).get("route", "?")
            by_route.setdefault(route, []).append(s)
        for route, series in by_route.items():
            merged = _merge_buckets(series)
            new_routes[route] = merged
            prev = prev_routes.get(route)
            if prev is None:
                continue  # baseline pass for a newly seen route
            # clamp negatives: a replica restart resets its counters
            window = {b: max(c - prev.get(b, 0), 0)
                      for b, c in merged.items()}
            if window.get("count", 0) < MIN_WINDOW_SAMPLES:
                continue
            p99 = _p99_from_buckets(window)
            if p99 is not None and p99 > p99_slo_s:
                flag(("slo_route", route), {
                    "anomaly": "slo_route", "route": route,
                    "p99_s": p99, "slo_s": p99_slo_s,
                    "window_requests": window["count"]})
    state["route_hist"] = new_routes

    # ---- sustained load shedding (proxy admission control): judged on
    # serve_shed_total deltas per route, summed across processes and shed
    # reasons; flagged only after SHED_SUSTAIN_PASSES consecutive passes
    # with fresh sheds (a replica restart's counter reset reads as a
    # non-positive delta and clears the streak)
    prev_shed: Dict = dict(state.get("shed_seen") or {})
    streaks: Dict = dict(state.get("shed_streak") or {})
    shed_totals: Dict[str, float] = {}
    for _proc, s in families.get("serve_shed_total", ()):
        route = (s.get("tags") or {}).get("route", "?")
        shed_totals[route] = shed_totals.get(route, 0.0) + (
            s.get("value") or 0.0)
    for route, total in shed_totals.items():
        if route not in prev_shed:
            streaks[route] = 0        # baseline pass for a new route
        elif total - prev_shed[route] > 0:
            streaks[route] = streaks.get(route, 0) + 1
            if streaks[route] >= SHED_SUSTAIN_PASSES:
                flag(("serve_shedding", route), {
                    "anomaly": "serve_shedding", "route": route,
                    "shed_in_window": int(total - prev_shed[route]),
                    "sustained_passes": streaks[route]})
        else:
            streaks[route] = 0
    state["shed_seen"] = shed_totals
    state["shed_streak"] = {k: v for k, v in streaks.items()
                            if k in shed_totals}

    # ---- hot-path regression watch (compiled planes): each golden
    # signal is judged against its OWN rolling EWMA baseline — absolute
    # thresholds can't cover a 4-lane ring and a 2-stage LLM chain with
    # one number. The baseline warms over 3 samples, then freezes while
    # the key is regressed (updating it would absorb the regression and
    # silence the very next pass).
    if hotpath_drift > 0:
        base: Dict = dict(state.get("hotpath_base") or {})
        fresh_keys = set()

        def drift_check(bkey, value, floor, detail):
            fresh_keys.add(bkey)
            b = base.get(bkey)
            if b is None:
                base[bkey] = {"ewma": value, "n": 1}
                return
            if b["n"] >= 3 and value > max(floor, hotpath_drift * b["ewma"]):
                flag(("hotpath", bkey), {
                    "anomaly": "hotpath_regression",
                    "value": round(value, 6),
                    "baseline": round(b["ewma"], 6),
                    "drift": hotpath_drift, **detail})
                return
            b["ewma"] = 0.8 * b["ewma"] + 0.2 * value
            b["n"] += 1

        # ring stall ratio: stall seconds accrued per wall second since
        # the previous pass (cumulative counters delta'd per ring key);
        # the 0.05 floor keeps an all-idle ring's noise unflaggable
        prev_stall: Dict = dict(state.get("hotpath_stall") or {})
        new_stall: Dict = {}
        for row in workload_rows:
            if now - row.get("ts", 0) > FRESH_S:
                continue
            stats = row.get("stats") or {}
            key = str(row.get("key", "?"))
            if row.get("kind") == "hotpath":
                cum = ((stats.get("writer_stall_s") or 0.0)
                       + (stats.get("reader_stall_s") or 0.0))
                prev = prev_stall.get(key)
                new_stall[key] = (cum, row.get("ts", now))
                if prev is None:
                    continue
                dt = row.get("ts", now) - prev[1]
                if dt <= 0:
                    continue
                drift_check(("ring", key), max(cum - prev[0], 0.0) / dt,
                            0.05, {"metric": "ring_stall_ratio",
                                   "plane": stats.get("plane"),
                                   "key": key})
            elif row.get("kind") == "serve_chain":
                p99 = stats.get("p99_s")
                if p99:
                    drift_check(("chain_p99", key), float(p99), 0.0,
                                {"metric": "chain_p99_s", "chain": key})
        state["hotpath_stall"] = new_stall

        # fused-step phase stragglers: timed-step rows gossiped per rank
        # (key "run:rank"); one rank far above the gang's low median is
        # flagged with its slowest-vs-median phase named, so "rank 3 is
        # slow" arrives as "rank 3's inter-host allreduce is slow"
        runs: Dict[str, List[dict]] = {}
        for row in workload_rows:
            if row.get("kind") != "train_phase":
                continue
            if now - row.get("ts", 0) > FRESH_S:
                continue
            run = str(row.get("key", "?")).rsplit(":", 1)[0]
            runs.setdefault(run, []).append(row.get("stats") or {})
        for run, members in runs.items():
            steps = [m.get("step_s") for m in members if m.get("step_s")]
            if len(steps) < 2:
                continue
            med = statistics.median_low(steps)
            if med <= 0:
                continue
            phase_names = sorted({k for m in members for k in m
                                  if k.endswith("_s") and k != "step_s"})
            med_phase = {p: statistics.median_low(
                [m.get(p) or 0.0 for m in members]) for p in phase_names}
            for m in members:
                step = m.get("step_s") or 0.0
                if step > straggler_factor * med:
                    worst = max(phase_names, default=None,
                                key=lambda p: (m.get(p) or 0.0)
                                - med_phase[p])
                    flag(("phase_straggler", run, m.get("rank")), {
                        "anomaly": "hotpath_regression",
                        "metric": "train_phase_step_s", "run": run,
                        "rank": m.get("rank"), "step_s": round(step, 4),
                        "gang_median_s": round(med, 4),
                        "phase": worst[:-2] if worst else None})
        state["hotpath_base"] = {k: v for k, v in base.items()
                                 if k in fresh_keys}

    # prune the carry so a long-lived head doesn't accumulate state for
    # every process/run/route that ever existed: slow-pull high-waters
    # die with their process's snapshot, re-flag stamps age out once
    # they can no longer suppress anything
    live_procs = {proc for series in families.values()
                  for proc, _ in series}
    state["slow_pull_seen"] = {k: v for k, v in seen.items()
                               if k[0] in live_procs}
    state["last_flag"] = {k: v for k, v in last_flag.items()
                          if now - v < 2 * REFLAG_INTERVAL_S}
    return anomalies, state
