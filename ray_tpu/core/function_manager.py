"""Ship function/class definitions once, load lazily on workers.

Parity with the reference's FunctionActorManager
(`python/ray/_private/function_manager.py:58`): definitions are exported to
the head KV keyed by content hash; executing workers fetch + cache. Uses
cloudpickle so closures/lambdas work.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict

import cloudpickle

FUNCTION_NS = "fn"


class FunctionManager:
    def __init__(self, client):
        self.client = client
        self._exported: Dict[bytes, bytes] = {}   # key -> blob (local cache)
        self._loaded: Dict[bytes, Any] = {}

    def export(self, obj: Any) -> bytes:
        blob = cloudpickle.dumps(obj, protocol=5)
        key = hashlib.sha256(blob).digest()[:16]
        if key not in self._exported:
            suspect = getattr(self.client, "_head_suspect", None)
            if suspect is not None and suspect():
                # head unreachable/paused: a blocking KV export would
                # stall the very submission the peer mesh exists to keep
                # alive. Cache locally (headless dispatch ships the blob
                # inside the spec) and fire the export as a push — it is
                # buffered/dropped now and `resync()` re-pushes every
                # cached def on reconnect anyway.
                self._exported[key] = blob
                try:
                    self.client.head_push("kv_put", ns=FUNCTION_NS,
                                          key=key, value=blob,
                                          overwrite=False)
                except Exception:
                    pass
            else:
                self.client.kv_put(FUNCTION_NS, key, blob, overwrite=False)
                self._exported[key] = blob
        return key

    def resync(self) -> None:
        """Re-export every cached definition (head-restart recovery: a
        def exported after the last snapshot died with the old head, and
        in-flight/replayed tasks still reference it by hash)."""
        for key, blob in list(self._exported.items()):
            try:
                self.client.head_push("kv_put", ns=FUNCTION_NS, key=key,
                                      value=blob, overwrite=False)
            except Exception:
                pass

    def blob(self, key: bytes):
        """Locally cached serialized definition, or None — the submitter
        attaches this to specs dispatched while the head is unreachable
        so ANY worker can execute them without a head KV fetch (headless
        cold-path dispatch must not stall on function delivery)."""
        return self._exported.get(key)

    def load(self, key: bytes, blob: bytes = None) -> Any:
        if key in self._loaded:
            return self._loaded[key]
        if blob is not None and key not in self._exported:
            # definition rode the spec (headless dispatch): adopt it —
            # the content hash is the key, so a forged/corrupt blob
            # cannot impersonate a different function silently
            if hashlib.sha256(blob).digest()[:16] == key:
                self._exported[key] = blob
        blob = self._exported.get(key)
        if blob is None:
            import time as _time

            blob = self.client.kv_get(FUNCTION_NS, key)
            recovering = getattr(self.client, "head_recovering", None)
            if blob is None and recovering is not None and recovering():
                # a miss inside the head-restart recovery window (we rode
                # a reconnect, or we are a fresh process on a young head)
                # is probably transient: the restored head predates this
                # def and its exporter re-pushes on reconnect — poll
                # briefly. A miss with no restart in sight fails fast
                # (no 5 s stall for genuinely missing defs).
                deadline = _time.monotonic() + 5.0
                while blob is None and _time.monotonic() < deadline:
                    _time.sleep(0.2)
                    blob = self.client.kv_get(FUNCTION_NS, key)
            if blob is None:
                raise RuntimeError(f"function def {key.hex()} not found in KV")
        obj = cloudpickle.loads(blob)
        self._loaded[key] = obj
        return obj
