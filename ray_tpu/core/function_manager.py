"""Ship function/class definitions once, load lazily on workers.

Parity with the reference's FunctionActorManager
(`python/ray/_private/function_manager.py:58`): definitions are exported to
the head KV keyed by content hash; executing workers fetch + cache. Uses
cloudpickle so closures/lambdas work.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict

import cloudpickle

FUNCTION_NS = "fn"


class FunctionManager:
    def __init__(self, client):
        self.client = client
        self._exported: Dict[bytes, bytes] = {}   # key -> blob (local cache)
        self._loaded: Dict[bytes, Any] = {}

    def export(self, obj: Any) -> bytes:
        blob = cloudpickle.dumps(obj, protocol=5)
        key = hashlib.sha256(blob).digest()[:16]
        if key not in self._exported:
            self.client.kv_put(FUNCTION_NS, key, blob, overwrite=False)
            self._exported[key] = blob
        return key

    def load(self, key: bytes) -> Any:
        if key in self._loaded:
            return self._loaded[key]
        blob = self._exported.get(key)
        if blob is None:
            blob = self.client.kv_get(FUNCTION_NS, key)
            if blob is None:
                raise RuntimeError(f"function def {key.hex()} not found in KV")
        obj = cloudpickle.loads(blob)
        self._loaded[key] = obj
        return obj
