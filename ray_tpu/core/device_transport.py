"""Device-object data plane: shm-staged snapshots with zero-copy reads.

Replaces the host-pickle round trip for cross-process `get()` of device
objects (`tensor_transport="device"`). Parity target: the reference's
accelerator tensor channel
(`python/ray/experimental/channel/torch_tensor_accelerator_channel.py`) —
metadata rides the control plane, bulk tensor bytes ride a data plane the
consumer maps without copies.

Design (TPU-native): PJRT HBM buffers are process-local, so every
cross-process move requires exactly one D2H DMA on the owner and (for a
device consumer) one H2D DMA on the consumer. Everything between those
two DMAs is zero-copy:

- the owner stages each `jax.Array` leaf STRAIGHT into the node's shm
  arena (out-of-band pickle5 buffers + `write_into`, no intermediate
  bytes, no pickle of the array data);
- a same-node consumer maps the shm segment and reconstructs numpy views
  onto it (true zero-copy for host consumers; a device consumer feeds the
  view to `jax.device_put`, which DMAs shm→HBM directly);
- a cross-node consumer pulls the snapshot through the existing chunked
  windowed transfer (`object_transfer.pull_object`) — 4 MiB chunks, so a
  multi-GB fetch no longer monopolizes the owner's event loop with one
  giant frame;
- jax leaves are tagged at serialization so the consumer rematerializes
  them on ITS devices (`_remat_leaf`), while plain numpy stays numpy.

The snapshot is cached on the owner keyed by the device object id and
freed together with it, so repeated consumers pay one D2H total.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Optional

from ray_tpu.core.ids import ObjectID
from ray_tpu.core.store import ObjectMeta

_tls = threading.local()


def snapshot_oid(device_oid: ObjectID) -> ObjectID:
    """Deterministic snapshot id: retries/races on the same device object
    stage to the same id, and any node can derive it without the owner."""
    return ObjectID(hashlib.blake2b(
        device_oid.binary() + b":snap", digest_size=16).digest())


def _remat_leaf(arr):
    """Unpickle hook for a staged jax leaf: inside a rematerialize()
    context the host view is DMA'd onto the consumer's default device;
    outside (plain host read) it stays a zero-copy numpy view."""
    if getattr(_tls, "remat", False):
        import jax

        return jax.device_put(arr)
    return arr


class IciLeaf:
    """Placeholder for a jax leaf in a device-object skeleton shipped over
    the control plane while the array itself rides the gang's ICI mesh
    (pair-mesh ppermute send/recv)."""

    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index

    def __reduce__(self):
        return (IciLeaf, (self.index,))


class rematerialize_context:
    def __enter__(self):
        _tls.remat = True
        return self

    def __exit__(self, *exc):
        _tls.remat = False
        return False


def stage_snapshot(client, device_oid: ObjectID, value: Any) -> ObjectMeta:
    """Owner-side: write a host snapshot of `value` into the node shm
    store (one D2H DMA per leaf, no pickle of array bytes). Runs in an
    executor thread — never on the owner's event loop."""
    from ray_tpu.core import serialization

    ser = serialization.serialize(value, device_snapshot=True)
    oid = snapshot_oid(device_oid)
    meta = client.store.put_serialized(oid, ser)
    meta.node_id = client.node_id
    meta.owner = client.worker_id
    return meta


def load_snapshot(value_bytes) -> Any:
    """Consumer-side: deserialize a pulled/mapped snapshot, placing jax
    leaves on this process's devices."""
    from ray_tpu.core import serialization

    with rematerialize_context():
        return serialization.deserialize(value_bytes)
