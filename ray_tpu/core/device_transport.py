"""Device-object data plane: shm-staged snapshots with zero-copy reads.

Replaces the host-pickle round trip for cross-process `get()` of device
objects (`tensor_transport="device"`). Parity target: the reference's
accelerator tensor channel
(`python/ray/experimental/channel/torch_tensor_accelerator_channel.py`) —
metadata rides the control plane, bulk tensor bytes ride a data plane the
consumer maps without copies.

Design (TPU-native): PJRT HBM buffers are process-local, so every
cross-process move requires exactly one D2H DMA on the owner and (for a
device consumer) one H2D DMA on the consumer. Everything between those
two DMAs is zero-copy:

- the owner stages each `jax.Array` leaf STRAIGHT into the node's shm
  arena (out-of-band pickle5 buffers + `write_into`, no intermediate
  bytes, no pickle of the array data);
- a same-node consumer maps the shm segment and reconstructs numpy views
  onto it (true zero-copy for host consumers; a device consumer feeds the
  view to `jax.device_put`, which DMAs shm→HBM directly);
- a cross-node consumer pulls the snapshot through the existing chunked
  windowed transfer (`object_transfer.pull_object`) — 4 MiB chunks, so a
  multi-GB fetch no longer monopolizes the owner's event loop with one
  giant frame;
- jax leaves are tagged at serialization so the consumer rematerializes
  them on ITS devices (`_remat_leaf`), while plain numpy stays numpy.

The snapshot is cached on the owner keyed by the device object id and
freed together with it, so repeated consumers pay one D2H total.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Optional

from ray_tpu.core.ids import ObjectID
from ray_tpu.core.store import ObjectMeta

_tls = threading.local()


def snapshot_oid(device_oid: ObjectID) -> ObjectID:
    """Deterministic snapshot id: retries/races on the same device object
    stage to the same id, and any node can derive it without the owner."""
    return ObjectID(hashlib.blake2b(
        device_oid.binary() + b":snap", digest_size=16).digest())


def _remat_leaf(arr):
    """Unpickle hook for a staged jax leaf: inside a rematerialize()
    context the host view becomes a jax.Array on the consumer's default
    device; outside (plain host read) it stays a zero-copy numpy view.

    The rematerialization path is host-copy-free on the consumer end:
    on CPU backends the mapped shm view is ADOPTED via DLPack (the jax
    array aliases the pulled segment's pages — zero copies end to end);
    on accelerator backends `device_put` issues the one unavoidable
    shm→HBM DMA straight from the mapped view. Combined with the owner
    staging straight into shm (one D2H) and the chunked pull writing
    straight into the consumer node's shm, a cross-node device handoff
    costs exactly one D2H and one H2D — the seed north star's DLPack
    path."""
    if getattr(_tls, "remat", False):
        import jax

        from ray_tpu.core import config as _config

        if _config.get("device_dlpack"):
            try:
                # XLA:CPU adopts a DLPack capsule without copying only
                # when the buffer is 64-byte aligned (shm mappings are
                # page-aligned, so staged leaves usually qualify); the
                # capsule's deleter keeps the exporting numpy view — and
                # with it the shm mapping — alive. Aliasing the SHARED
                # snapshot pages is safe against donate_argnums because
                # buffer donation is not implemented on the CPU backend
                # (donated inputs are left untouched — verified on this
                # jax); adoption is gated to cpu above for exactly that
                # reason, so accelerator backends always go through the
                # copying device_put DMA below.
                if (jax.default_backend() == "cpu"
                        and arr.ctypes.data % 64 == 0):
                    return jax.dlpack.from_dlpack(arr)
            except Exception:
                pass  # exotic dtype/layout: fall back to the DMA path
        return jax.device_put(arr)
    return arr


class IciLeaf:
    """Placeholder for a jax leaf in a device-object skeleton shipped over
    the control plane while the array itself rides the gang's ICI mesh
    (pair-mesh ppermute send/recv)."""

    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index

    def __reduce__(self):
        return (IciLeaf, (self.index,))


class rematerialize_context:
    def __enter__(self):
        _tls.remat = True
        return self

    def __exit__(self, *exc):
        _tls.remat = False
        return False


def stage_snapshot(client, device_oid: ObjectID, value: Any) -> ObjectMeta:
    """Owner-side: write a host snapshot of `value` into the node shm
    store (one D2H DMA per leaf, no pickle of array bytes). Runs in an
    executor thread — never on the owner's event loop."""
    from ray_tpu.core import serialization

    ser = serialization.serialize(value, device_snapshot=True)
    oid = snapshot_oid(device_oid)
    meta = client.store.put_serialized(oid, ser)
    meta.node_id = client.node_id
    meta.owner = client.worker_id
    return meta


def load_snapshot(value_bytes) -> Any:
    """Consumer-side: deserialize a pulled/mapped snapshot, placing jax
    leaves on this process's devices."""
    from ray_tpu.core import serialization

    with rematerialize_context():
        return serialization.deserialize(value_bytes)
