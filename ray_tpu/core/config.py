"""Central config registry: every tunable in ONE table.

Parity: `src/ray/common/ray_config_def.h` (the reference's 222-flag
X-macro table) + `RayConfig` introspection. Before this module, ~40
`RAY_TPU_*` env vars were read ad hoc across ~25 files — no single list,
no introspection, no way to ask a running cluster what it's tuned to.

- `config.get("name")` — typed value: explicit override → env var →
  default. Call-time reads, so tests that set env vars keep working.
- `config.dump()` — every flag with value + where it came from
  (`ray-tpu config` CLI, `/api/config` dashboard, state API).
- **negotiated flags** adopt the HEAD's value at registration (shipped
  in the `register_worker` reply): a process whose environment differs
  from the head's must not silently diverge on semantics the whole
  cluster shares. Precedence for negotiated flags is override > head >
  env > default (the head beats local env, an explicit in-process
  `set()` beats everything); non-negotiated flags skip the head tier.
  `refcount` pioneered this in r3; the mechanism is now general.

Adding a flag = one table row; reading env directly for a tunable is a
review error. NOT flags (deliberately): per-process identity the parent
hands each child it spawns — RAY_TPU_{HEAD_PORT,SESSION,NODE_ID,LOG_TAG,
VENV_KEY,JAX_COORDINATOR,JAX_NUM_PROCESSES,JAX_PROCESS_ID,NODE_IP} and
the GKE-preset TPU_* facts. Those are arguments, not tunables: two
processes on one host legitimately hold different values, so a shared
registry would be wrong.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class Flag:
    name: str           # python-side name (snake_case)
    env: str            # environment variable
    type: type          # bool | int | float | str
    default: Any
    doc: str
    negotiated: bool = False  # cluster-wide: clients adopt the head's value


def _b(v: str) -> bool:
    return v not in ("0", "false", "False", "")


FLAGS: List[Flag] = [
    # ----------------------------------------------------- object lifetime
    Flag("refcount", "RAY_TPU_REFCOUNT", bool, True,
         "Distributed reference counting drives object eviction "
         "(free() optional). Reference: ReferenceCounter.", negotiated=True),
    Flag("evict_grace_s", "RAY_TPU_EVICT_GRACE_S", float, 0.0,
         "Grace before evicting an interest-free object; 0 = fully "
         "explicit lifetime (borrow pins).", negotiated=True),
    Flag("refcount_flush_s", "RAY_TPU_REFCOUNT_FLUSH_S", float, 0.1,
         "Batching window for ref transitions pushed to the head."),
    Flag("lineage_cap", "RAY_TPU_LINEAGE_CAP", int, 10_000,
         "Max reconstructable-task lineage entries at the head."),
    Flag("lineage_bytes", "RAY_TPU_LINEAGE_BYTES", int, 256 << 20,
         "Byte cap for lineage specs (inline args pin memory)."),
    # ------------------------------------------------------- object store
    Flag("object_store_bytes", "RAY_TPU_OBJECT_STORE_BYTES", int, 0,
         "Node object-store capacity; 0 = 30% of RAM capped by /dev/shm."),
    Flag("store_isolation", "RAY_TPU_STORE_ISOLATION", bool, False,
         "Per-node store namespaces on one machine (forces real "
         "cross-node transfers in tests)."),
    Flag("store_namespace", "RAY_TPU_STORE_NAMESPACE", str, "",
         "Explicit store namespace (else derived from node id)."),
    Flag("disable_native_store", "RAY_TPU_DISABLE_NATIVE_STORE", bool, False,
         "Skip the C++ arena store even if built."),
    Flag("pull_cache_bytes", "RAY_TPU_PULL_CACHE_BYTES", int, 1 << 30,
         "Per-process LRU cache of cross-node pulled objects."),
    # -------------------------------------------------------- data plane
    Flag("transfer_chunk_bytes", "RAY_TPU_TRANSFER_CHUNK_BYTES", int, 4 << 20,
         "Chunk size for cross-node object pulls."),
    Flag("transfer_window", "RAY_TPU_TRANSFER_WINDOW", int, 4,
         "In-flight chunks per pull (windowed transfer)."),
    Flag("transfer_server_reads", "RAY_TPU_TRANSFER_SERVER_READS", int, 8,
         "Concurrent chunk reads served per data server."),
    Flag("transfer_chunk_retries", "RAY_TPU_TRANSFER_CHUNK_RETRIES", int, 4,
         "Per-chunk retry budget inside one pull attempt (rides the "
         "chaos plane: injected drops/delays on the data edge are "
         "absorbed here before multi-source failover kicks in)."),
    Flag("transfer_retry_backoff_s", "RAY_TPU_TRANSFER_RETRY_BACKOFF_S",
         float, 0.05, "Base backoff between chunk retries (doubles per "
         "attempt, capped at 1s)."),
    Flag("object_directory", "RAY_TPU_OBJECT_DIRECTORY", bool, True,
         "Gossip object locations on the cluster_view plane so daemons "
         "and drivers resolve objects peer-to-peer; the head's "
         "locate_object becomes the cold-miss fallback.", negotiated=True),
    Flag("node_pull_manager", "RAY_TPU_NODE_PULL_MANAGER", bool, True,
         "Workers route remote-object pulls through their node daemon's "
         "pull manager so each object crosses the network once per node.",
         negotiated=True),
    Flag("replica_cache_bytes", "RAY_TPU_REPLICA_CACHE_BYTES", int, 1 << 30,
         "Node-daemon LRU cache of pulled object replicas (advertised "
         "in the gossiped object directory as pull sources)."),
    Flag("device_dlpack", "RAY_TPU_DEVICE_DLPACK", bool, True,
         "Rematerialize pulled device-object leaves via DLPack "
         "(zero-copy adoption of the mapped shm view on CPU backends; "
         "falls back to device_put)."),
    Flag("ici_fetch_timeout_s", "RAY_TPU_ICI_FETCH_TIMEOUT_S", float, 60.0,
         "Bound on a gang-ICI device fetch before the consumer surfaces "
         "ObjectLostError (a dead peer poisons the pair collective)."),
    # ----------------------------------------------------------- runtime
    Flag("head_host", "RAY_TPU_HEAD_HOST", str, "127.0.0.1",
         "Head host for spawned workers."),
    Flag("bind_host", "RAY_TPU_BIND_HOST", str, "127.0.0.1",
         "Bind address for every server (head/data/direct/proxy); set "
         "0.0.0.0 to accept off-box connections."),
    Flag("address", "RAY_TPU_ADDRESS", str, "",
         "Default cluster address for init()/CLI."),
    Flag("lease_idle_s", "RAY_TPU_LEASE_IDLE_S", float, 1.0,
         "Idle time before a leased worker returns to the pool."),
    # -------------------------------------------- two-level scheduling
    Flag("view_broadcast_s", "RAY_TPU_VIEW_BROADCAST_S", float, 0.25,
         "Head cadence for pushing the compacted cluster resource view "
         "to node daemons and drivers (reference ray_syncer broadcast)."),
    Flag("gossip_debounce_s", "RAY_TPU_GOSSIP_DEBOUNCE_S", float, 0.05,
         "Node-daemon debounce for resource-view deltas pushed to the "
         "head on local pool changes."),
    Flag("pool_idle_s", "RAY_TPU_POOL_IDLE_S", float, 5.0,
         "Idle time before a node daemon returns a pooled lease worker "
         "(and its resource carve-out) to the head."),
    Flag("node_local_sched", "RAY_TPU_NODE_LOCAL_SCHED", bool, True,
         "Clients route lease requests to node-daemon schedulers via the "
         "cached cluster view; off = every lease goes through the head.",
         negotiated=True),
    Flag("peer_spill_attempts", "RAY_TPU_PEER_SPILL_ATTEMPTS", int, 2,
         "On a local-pool miss a node daemon refers the client to up to "
         "this many peer daemons whose gossiped pools show warm idle "
         "workers (epoch-stamped peer grants; the head becomes the last "
         "resort). 0 disables daemon-to-daemon spillback.",
         negotiated=True),
    Flag("pool_acquire_timeout_s", "RAY_TPU_POOL_ACQUIRE_TIMEOUT_S",
         float, 8.0,
         "Daemon-side bound on the head pool_acquire carve-out RPC; a "
         "paused/hung head must fail over to peer referral or client "
         "spill instead of stalling the grant forever."),
    Flag("lease_park_max", "RAY_TPU_LEASE_PARK_MAX", int, 256,
         "Per-shape bound on cold-path tasks parked in the client's "
         "local dispatch queue while the head is unreachable (drained "
         "through daemon/peer-granted leases; overflow falls back to "
         "the head path)."),
    Flag("view_shards", "RAY_TPU_VIEW_SHARDS", int, 0,
         "Shard the cluster_view broadcast: interest-scoped subscribers "
         "(node daemons register interest='auto') receive only the "
         "node-set shards they route against plus a compact digest for "
         "spillback candidate selection, instead of the full node list "
         "(head-side flag; 0/1 = full-fanout broadcasts)."),
    Flag("view_digest_k", "RAY_TPU_VIEW_DIGEST_K", int, 16,
         "Spillback-candidate rows carried in the sharded-view digest "
         "(top idle-pool nodes cluster-wide)."),
    Flag("view_digest_refresh_s", "RAY_TPU_VIEW_DIGEST_REFRESH_S",
         float, 2.0,
         "Cadence for refreshing a scoped subscriber's digest when none "
         "of its interest shards changed (keeps spillback candidate "
         "idle counts honest without full-fanout broadcasts)."),
    Flag("reconnect_timeout_s", "RAY_TPU_RECONNECT_TIMEOUT_S", float, 30.0,
         "Window for clients to reconnect to a restarted head; 0 = die "
         "on disconnect."),
    Flag("runtime_env_cache_bytes", "RAY_TPU_RUNTIME_ENV_CACHE_BYTES",
         int, 2 << 30, "Head-side cap for cached runtime_env packages."),
    Flag("client_proxy_max_clients", "RAY_TPU_CLIENT_PROXY_MAX_CLIENTS",
         int, 16, "Concurrent remote drivers the client proxy will host; "
         "each costs a full driver process on the head node."),
    Flag("testing_rpc_failure", "RAY_TPU_TESTING_RPC_FAILURE", str, "",
         "Chaos injection: 'method:prob,...' (reference rpc_chaos)."),
    Flag("chaos", "RAY_TPU_CHAOS", str, "",
         "Deterministic fault plan: comma-separated rules "
         "'kind:target[:k=v...]' with kinds drop|delay|dup|partition|kill,"
         " triggers n=/every=/p=, windows after=/for=, plan-wide seed=N "
         "(README 'Failure model'); faults surface as "
         "chaos_injected_total{method,kind}."),
    Flag("node_reconnect_timeout_s", "RAY_TPU_NODE_RECONNECT_TIMEOUT_S",
         float, 60.0,
         "Window for a node daemon to reconnect to a restarted/partitioned"
         " head while serving warm leases from its existing pools and "
         "queueing gossip; 0 = die on head disconnect (pre-epoch "
         "behavior)."),
    # ------------------------------------------------------------- memory
    # ------------------------------------------------------------- health
    Flag("health_check_interval_s", "RAY_TPU_HEALTH_CHECK_INTERVAL_S",
         float, 5.0, "Liveness-probe cadence for workers/node daemons; "
         "0 disables probing (reference gcs_health_check_manager)."),
    Flag("health_check_timeout_s", "RAY_TPU_HEALTH_CHECK_TIMEOUT_S",
         float, 5.0, "Per-probe reply deadline."),
    Flag("health_check_misses", "RAY_TPU_HEALTH_CHECK_MISSES", int, 3,
         "Consecutive missed probes before a hung-but-connected process "
         "is declared dead (its socket is closed, triggering the normal "
         "failure path: actor restart, lease revoke, task retry)."),
    Flag("memory_monitor", "RAY_TPU_MEMORY_MONITOR", bool, True,
         "OOM monitor kills the newest task when node memory crosses "
         "the threshold."),
    Flag("memory_usage_threshold", "RAY_TPU_MEMORY_USAGE_THRESHOLD",
         float, 0.95, "Fraction of node memory that triggers the killer."),
    Flag("memory_monitor_interval_s", "RAY_TPU_MEMORY_MONITOR_INTERVAL_S",
         float, 1.0, "Monitor poll interval."),
    Flag("meminfo_path", "RAY_TPU_MEMINFO_PATH", str, "/proc/meminfo",
         "Meminfo source (tests point this at a fixture)."),
    # ------------------------------------------------------------ logging
    Flag("log_to_driver", "RAY_TPU_LOG_TO_DRIVER", bool, True,
         "Stream worker prints to the submitting driver's terminal."),
    # ------------------------------------------------------ observability
    Flag("tracing", "RAY_TPU_TRACING", bool, False,
         "OpenTelemetry-style span export."),
    Flag("tracing_buffer_spans", "RAY_TPU_TRACING_BUFFER_SPANS", int, 10_000,
         "In-process finished-span buffer cap; overflow drops the oldest "
         "half (reference span-processor queue bound)."),
    Flag("metrics_push_interval_s", "RAY_TPU_METRICS_PUSH_INTERVAL_S",
         float, 2.0, "Worker metrics push cadence."),
    Flag("rpc_metrics", "RAY_TPU_RPC_METRICS", bool, True,
         "Control-plane flight recorder: per-method RPC counters and "
         "latency histograms recorded through the protocol interposer "
         "in every process (head/daemon/driver/worker)."),
    Flag("flight_recorder_events", "RAY_TPU_FLIGHT_RECORDER_EVENTS", int, 512,
         "Per-node-daemon ring buffer of lease-lifecycle/gossip events "
         "piggybacked on resource_view_delta gossip."),
    Flag("flight_recorder_head_events", "RAY_TPU_FLIGHT_RECORDER_HEAD_EVENTS",
         int, 5000, "Head-side merged lease-event buffer (state API "
         "list_lease_events) and driver-side scheduling-phase buffer."),
    Flag("tracing_head_spans", "RAY_TPU_TRACING_HEAD_SPANS", int, 20_000,
         "Head-side buffer of finished spans pushed by every process "
         "(workload flight recorder); timeline(format='chrome') merges "
         "them into one cross-process trace."),
    Flag("workload_watchdog_interval_s", "RAY_TPU_WORKLOAD_WATCHDOG_INTERVAL_S",
         float, 5.0, "Head-side anomaly pass cadence over the merged "
         "workload telemetry (0 disables)."),
    Flag("workload_slow_pull_s", "RAY_TPU_WORKLOAD_SLOW_PULL_S", float, 5.0,
         "Object pulls slower than this flag a slow_pull anomaly."),
    Flag("workload_straggler_factor", "RAY_TPU_WORKLOAD_STRAGGLER_FACTOR",
         float, 2.0, "A train worker whose EWMA step time exceeds this "
         "multiple of its gang's median is flagged a straggler."),
    Flag("serve_p99_slo_s", "RAY_TPU_SERVE_P99_SLO_S", float, 0.0,
         "Route-level p99 latency SLO for the workload watchdog "
         "(0 disables the slo_route anomaly)."),
    Flag("serve_live_signal_refresh_s", "RAY_TPU_SERVE_LIVE_SIGNAL_REFRESH_S",
         float, 1.0, "Serve routers/autoscaler re-pull the merged "
         "gossiped replica-load rows (state.list_serve_stats) at most "
         "this often (0 disables live-signal consumption; routing falls "
         "back to local in-flight counts)."),
    Flag("serve_live_signal_max_age_s", "RAY_TPU_SERVE_LIVE_SIGNAL_MAX_AGE_S",
         float, 5.0, "Gossiped replica-load rows older than this are "
         "ignored by live-signal routing and admission control (local "
         "in-flight counts take over)."),
    Flag("tracing_compiled_sample_n", "RAY_TPU_TRACING_COMPILED_SAMPLE_N",
         int, 16, "Sample 1-in-N compiled-plane submissions for span "
         "capture when tracing is on (carriers ride the ring entries; "
         "0 disables compiled-path tracing entirely). Sampling keeps "
         "the zero-RPC contract and compiled p99 intact."),
    Flag("ring_telemetry_interval_s", "RAY_TPU_RING_TELEMETRY_INTERVAL_S",
         float, 1.0, "Cadence of lock-free shm-ring header snapshots "
         "(occupancy + writer/reader stall attribution) published per "
         "compiled chain / pipeline lane (0 disables ring telemetry)."),
    Flag("workload_hotpath_drift", "RAY_TPU_WORKLOAD_HOTPATH_DRIFT",
         float, 1.5, "hotpath_regression threshold: a hot-path golden "
         "signal (compiled p99, ring stall ratio, fused-step phase "
         "time) exceeding this multiple of its rolling baseline is "
         "flagged by the workload watchdog (0 disables)."),
    # --------------------------------------------------------------- TPU
    Flag("num_chips", "RAY_TPU_NUM_CHIPS", int, -1,
         "Override TPU chip autodetection (-1 = autodetect)."),
    Flag("pod_type", "RAY_TPU_POD_TYPE", str, "",
         "Override slice/pod type (else GKE env / GCE metadata)."),
    Flag("slice_name", "RAY_TPU_SLICE_NAME", str, "",
         "Override slice name (else TPU_NAME / GCE metadata)."),
    Flag("worker_id", "RAY_TPU_WORKER_ID", str, "",
         "Override TPU pod worker index."),
    Flag("gce_metadata_endpoint", "RAY_TPU_GCE_METADATA_ENDPOINT", str, "",
         "Override the GCE metadata server (tests use a local mock)."),
    # --------------------------------------------------------------- data
    Flag("data_memory_budget_bytes", "RAY_TPU_DATA_MEMORY_BUDGET_BYTES",
         int, 256 << 20,
         "Streaming executor in-flight byte budget (adaptive window)."),
    Flag("data_store_highwater", "RAY_TPU_DATA_STORE_HIGHWATER", float, 0.85,
         "Gossiped object-store pressure (used/capacity, any node) above "
         "which the streaming executor stops admitting NEW pipeline "
         "inputs — stages keep draining, so pressure falls instead of "
         "OOMing the store. 0 disables the signal."),
    Flag("data_input_retries", "RAY_TPU_DATA_INPUT_RETRIES", int, 3,
         "Per-(stage, partition) retries of a pipeline consumer task "
         "whose input block went lost (ObjectLostError result); each "
         "retry rides lineage reconstruction of the lost input."),
    Flag("data_prefetch", "RAY_TPU_DATA_PREFETCH", bool, True,
         "Push-side prefetch: stage a completed block into the consuming "
         "stage's node store before its task dispatches (overlaps the "
         "pull with queue wait; the node PullManager dedups)."),
    Flag("data_eager_release", "RAY_TPU_DATA_EAGER_RELEASE", bool, True,
         "Release consumed intermediate blocks' lineage entries when a "
         "partition's final output is consumed, so a long pipeline's "
         "store footprint stays bounded by the in-flight window."),
    # -------------------------------------------------------------- train
    Flag("torch_backend", "RAY_TPU_TORCH_BACKEND", str, "gloo",
         "torch.distributed backend for TorchTrainer."),
    Flag("torch_timeout_s", "RAY_TPU_TORCH_TIMEOUT_S", float, 120.0,
         "torch.distributed init timeout."),
    # ------------------------------------------------------------ testing
    Flag("testing_ici_drop_send", "RAY_TPU_TESTING_ICI_DROP_SEND", bool,
         False, "Chaos: drop ICI device-object sends (transfer tests)."),
    Flag("head_profile", "RAY_TPU_HEAD_PROFILE", str, "",
         "Write a cProfile of the head event loop to this path on "
         "SIGUSR1/exit."),
    Flag("spill_dir", "RAY_TPU_SPILL_DIR", str, "",
         "Object-spill directory; may be an fsspec URI (s3://, gs://) "
         "for remote spill storage."),
    Flag("usage_stats", "RAY_TPU_USAGE_STATS", bool, False,
         "Periodic usage-stats reporting (JSON lines under the state "
         "dir by default; reference usage_lib — opt-IN here)."),
]

_BY_NAME: Dict[str, Flag] = {f.name: f for f in FLAGS}
_BY_ENV: Dict[str, Flag] = {f.env: f for f in FLAGS}


class Config:
    """Process-wide view: overrides > env > head-negotiated > default."""

    def __init__(self) -> None:
        self._overrides: Dict[str, Any] = {}
        self._head_values: Dict[str, Any] = {}

    def _parse(self, flag: Flag, raw: str) -> Any:
        if flag.type is bool:
            return _b(raw)
        try:
            return flag.type(raw)
        except (TypeError, ValueError):
            return flag.default

    def get(self, name: str) -> Any:
        flag = _BY_NAME[name]
        if name in self._overrides:
            return self._overrides[name]
        if flag.negotiated and name in self._head_values:
            return self._head_values[name]  # head beats local env
        raw = os.environ.get(flag.env)
        if raw is not None and raw != "":
            return self._parse(flag, raw)
        return flag.default

    def source(self, name: str) -> str:
        flag = _BY_NAME[name]
        if name in self._overrides:
            return "override"
        if flag.negotiated and name in self._head_values:
            return "head"
        raw = os.environ.get(flag.env)
        if raw is not None and raw != "":
            return "env"
        return "default"

    def set(self, name: str, value: Any) -> None:
        if name not in _BY_NAME:
            raise KeyError(f"unknown config flag {name!r}")
        self._overrides[name] = value

    # ----------------------------------------------- cluster distribution
    def negotiated_snapshot(self) -> Dict[str, Any]:
        """The head's values for every negotiated flag — shipped to each
        process in the register_worker reply."""
        return {f.name: self.get(f.name) for f in FLAGS if f.negotiated}

    def adopt_head(self, values: Optional[Dict[str, Any]]) -> None:
        """Client side: record the head's negotiated values. get() ranks
        them above local env (never above an explicit set() override),
        and source() reports them as "head" — provenance stays honest."""
        if not values:
            return
        self._head_values.update(values)

    # ------------------------------------------------------ introspection
    def dump(self) -> List[dict]:
        return [{"name": f.name, "env": f.env,
                 "type": f.type.__name__,
                 "value": self.get(f.name), "default": f.default,
                 "source": self.source(f.name),
                 "negotiated": f.negotiated, "doc": f.doc}
                for f in FLAGS]


GLOBAL = Config()


def get(name: str) -> Any:
    return GLOBAL.get(name)


def dump() -> List[dict]:
    return GLOBAL.dump()
