"""Job manager: runs submitted entrypoints as drivers on the cluster.

Parity: `python/ray/dashboard/modules/job/job_manager.py` — each submitted
job is a supervisor-managed driver subprocess with RAY_TPU_ADDRESS set so
`init()` joins this cluster; status transitions PENDING→RUNNING→
SUCCEEDED/FAILED/STOPPED; logs captured per job.
"""

from __future__ import annotations

import asyncio
import os
import signal
import time
import uuid
from typing import Dict, Optional

from ray_tpu.utils.platform import STATE_DIR


class JobInfo:
    def __init__(self, job_id: str, entrypoint: str, metadata: Optional[dict]):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.metadata = metadata or {}
        self.status = "PENDING"
        self.message = ""
        self.start_time = time.time()
        self.end_time: Optional[float] = None
        self.proc: Optional[asyncio.subprocess.Process] = None
        self.log_path: Optional[str] = None

    def view(self) -> dict:
        return {"job_id": self.job_id, "entrypoint": self.entrypoint,
                "status": self.status, "message": self.message,
                "metadata": self.metadata, "start_time": self.start_time,
                "end_time": self.end_time, "log_path": self.log_path}


class JobManager:
    def __init__(self, session: str, head_port: int):
        self.session = session
        self.head_port = head_port
        self.jobs: Dict[str, JobInfo] = {}
        self.log_dir = os.path.join(STATE_DIR, session, "logs")
        os.makedirs(self.log_dir, exist_ok=True)

    async def submit(self, entrypoint: str, *, metadata: Optional[dict] = None,
                     env: Optional[dict] = None,
                     working_dir: Optional[str] = None,
                     job_id: Optional[str] = None) -> str:
        job_id = job_id or f"rtpu-{uuid.uuid4().hex[:10]}"
        if job_id in self.jobs:
            raise ValueError(f"job {job_id!r} already exists")
        info = JobInfo(job_id, entrypoint, metadata)
        info.log_path = os.path.join(self.log_dir, f"job-{job_id}.log")
        self.jobs[job_id] = info
        child_env = dict(os.environ)
        from ray_tpu.core.resources import strip_device_env

        child_env = strip_device_env(child_env)
        child_env["RAY_TPU_ADDRESS"] = f"127.0.0.1:{self.head_port}"
        child_env["RAY_TPU_JOB_ID"] = job_id
        child_env.update(env or {})
        logf = open(info.log_path, "wb")
        try:
            info.proc = await asyncio.create_subprocess_shell(
                entrypoint, stdout=logf, stderr=asyncio.subprocess.STDOUT,
                cwd=working_dir or None, env=child_env,
                start_new_session=True)
        except Exception as e:
            info.status = "FAILED"
            info.message = f"failed to start: {e!r}"
            info.end_time = time.time()
            logf.close()
            return job_id
        info.status = "RUNNING"
        asyncio.ensure_future(self._watch(info, logf))
        return job_id

    async def _watch(self, info: JobInfo, logf) -> None:
        rc = await info.proc.wait()
        logf.close()
        info.end_time = time.time()
        if info.status == "STOPPED":
            return
        info.status = "SUCCEEDED" if rc == 0 else "FAILED"
        info.message = f"exit code {rc}"

    def stop(self, job_id: str) -> bool:
        info = self.jobs.get(job_id)
        if info is None or info.proc is None or info.status != "RUNNING":
            return False
        info.status = "STOPPED"
        info.message = "stopped by user"
        try:
            os.killpg(os.getpgid(info.proc.pid), signal.SIGTERM)
        except Exception:
            try:
                info.proc.terminate()
            except Exception:
                pass
        return True

    def get(self, job_id: str) -> Optional[dict]:
        info = self.jobs.get(job_id)
        return info.view() if info else None

    def list(self) -> list:
        return [i.view() for i in self.jobs.values()]

    def logs(self, job_id: str) -> str:
        info = self.jobs.get(job_id)
        if info is None or not info.log_path or not os.path.exists(info.log_path):
            return ""
        with open(info.log_path, "rb") as f:
            return f.read().decode(errors="replace")
