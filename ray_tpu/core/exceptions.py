"""User-visible error types (capability parity with ray.exceptions)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base for all framework errors."""


class TaskError(RayTpuError):
    """A task/actor method raised. Carries the remote traceback; re-raised at
    every `get` on the result (and propagated through dependent tasks)."""

    def __init__(self, cause_repr: str, traceback_str: str = ""):
        super().__init__(f"task raised {cause_repr}\n{traceback_str}")
        self.cause_repr = cause_repr
        self.traceback_str = traceback_str


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died (OOM-killed, segfault, kill -9)."""


class ActorDiedError(RayTpuError):
    """The actor is dead (crashed with no restarts left, or killed)."""


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (restarting)."""


class ObjectLostError(RayTpuError):
    """Object data is gone and cannot be recovered (owner died)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get(timeout=...)` expired."""


class RuntimeEnvSetupError(RayTpuError):
    """Preparing a worker's runtime environment failed."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled via ray_tpu.cancel (reference TaskCancelledError)."""
