"""Head process entry (`python -m ray_tpu.core.head_main`).

Prints `RAY_TPU_HEAD_PORT=<port>` on stdout once serving, then runs until
killed — the counterpart of `gcs_server` + head-node raylet bring-up
(`python/ray/_private/node.py:1340 start_head_processes`).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

from ray_tpu.core import config as _config
from ray_tpu.core.gcs import Head


async def amain(args) -> None:
    from ray_tpu.core.protocol import enable_eager_tasks

    enable_eager_tasks(asyncio.get_running_loop())
    # flight recorder from process birth: node registrations and the
    # head's own outbound RPCs (spawn_worker, health probes) are counted
    # from the first connection (idempotent with Head.start's install).
    # The head's registry is scraped in-process by the dashboard — no
    # pusher thread needed (there is no CoreClient to push through).
    from ray_tpu.core import flight_recorder
    from ray_tpu.util import metrics as _metrics

    _metrics.disable_pusher()
    flight_recorder.install("head")
    if args.restore:
        # a SIGKILLed predecessor leaves its shm arena behind; object data
        # died with its owner processes, so clear it before re-creating
        import glob

        # two segment name schemes: rtpu_arena_{session[:16]} and
        # per-object rtpu_{session[:8]}_... — the 8-char prefix
        # matches both
        for seg in glob.glob(f"/dev/shm/rtpu_*{args.session[:8]}*"):
            try:
                os.unlink(seg)
            except OSError:
                pass
    head = Head(session=args.session, num_cpus=args.num_cpus,
                resources=json.loads(args.resources) if args.resources else None,
                num_tpu_chips=args.num_tpu_chips,
                object_store_bytes=args.object_store_bytes,
                max_workers=args.max_workers,
                labels=json.loads(args.labels) if args.labels else None)
    port = await head.start(port=args.port)
    restored = head.restore_snapshot() if args.restore else False
    if args.enable_snapshots:
        asyncio.ensure_future(head._snapshot_loop())
    if _config.get("memory_monitor"):
        from ray_tpu.core.memory_monitor import MemoryMonitor

        asyncio.ensure_future(MemoryMonitor(head).run())
    from ray_tpu.util.usage_stats import start_usage_stats_heartbeat

    start_usage_stats_heartbeat(args.session)  # no-op unless opted in
    # the head-port line must come first: init() parses it from stdout
    print(f"RAY_TPU_HEAD_PORT={port}", flush=True)
    if args.restore:
        print(f"RAY_TPU_RESTORED={int(restored)}", flush=True)
    ports = {"port": port}
    if not args.no_dashboard:
        try:
            from ray_tpu.dashboard import start_dashboard

            dport = await start_dashboard(head, port=args.dashboard_port)
            print(f"RAY_TPU_DASHBOARD_PORT={dport}", flush=True)
            ports["dashboard_port"] = dport
        except Exception as e:  # dashboard is best-effort, never blocks boot
            print(f"RAY_TPU_DASHBOARD_ERROR={e!r}", file=sys.stderr, flush=True)
    if not args.no_client_proxy:
        try:
            from ray_tpu.client_proxy.server import ClientProxyServer

            # same bind policy as the head/data servers: localhost unless
            # the operator opts into external exposure via RAY_TPU_BIND_HOST
            # (any connecting client gets a full driver — RCE surface)
            cps = ClientProxyServer("127.0.0.1", port)
            cp_port = await cps.start(
                host=_config.get("bind_host"),
                port=args.client_proxy_port)
            head.client_proxy_port = cp_port
            print(f"RAY_TPU_CLIENT_PROXY_PORT={cp_port}", flush=True)
            ports["client_proxy_port"] = cp_port
        except Exception as e:  # remote-driver ingress is best-effort
            print(f"RAY_TPU_CLIENT_PROXY_ERROR={e!r}", file=sys.stderr,
                  flush=True)
    if args.port_file:
        # atomic write so pollers never read a partial file; lets the CLI
        # spawn the head fully detached (stdout→devnull, no inherited pipe)
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(ports, f)
        os.replace(tmp, args.port_file)
    try:
        await asyncio.Event().wait()
    finally:
        await head.stop()


def main() -> None:
    prof_path = _config.get("head_profile")
    if prof_path:
        import cProfile
        import signal as _signal

        prof = cProfile.Profile()
        prof.enable()

        def _dump(_sig, _frm):
            # disable→dump→enable: create_stats() alone permanently stops
            # collection, making repeated snapshots silently stale
            prof.disable()
            prof.dump_stats(prof_path)
            prof.enable()

        _signal.signal(_signal.SIGUSR1, _dump)
    p = argparse.ArgumentParser()
    p.add_argument("--session", required=True)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpu-chips", type=int, default=None)
    p.add_argument("--resources", type=str, default=None)
    p.add_argument("--object-store-bytes", type=int, default=-1)
    p.add_argument("--max-workers", type=int, default=None)
    p.add_argument("--labels", type=str, default=None)
    p.add_argument("--no-dashboard", action="store_true")
    p.add_argument("--port-file", type=str, default=None)
    p.add_argument("--enable-snapshots", action="store_true",
                   help="persist control-plane state for head restart")
    p.add_argument("--restore", action="store_true",
                   help="restore session state from a prior head snapshot")
    p.add_argument("--dashboard-port", type=int, default=0)
    p.add_argument("--no-client-proxy", action="store_true")
    p.add_argument("--client-proxy-port", type=int, default=0)
    args = p.parse_args()
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
