"""CoreClient: the per-process runtime embedded in drivers and workers.

Capability-equivalent of the reference's core worker
(`src/ray/core_worker/core_worker.h:168`) Python-side: task submission,
object put/get/wait, actor calls over direct worker<->worker connections,
blocked/unblocked notifications to the scheduler. The asyncio loop runs in a
background thread; the public API is synchronous (like `ray.get`).
"""

from __future__ import annotations

import asyncio
import concurrent.futures as _cf
import functools
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from collections import OrderedDict, deque

from ray_tpu.core import config as _config
from ray_tpu.core import object_transfer, protocol, refcount, serialization
from ray_tpu.core.exceptions import (ActorDiedError, GetTimeoutError,
                                     ObjectLostError, RayTpuError,
                                     WorkerCrashedError)
from ray_tpu.core.function_manager import FunctionManager
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.store import INLINE_THRESHOLD, ObjectMeta, SharedMemoryStore
from ray_tpu.core.serialization import SerializedObject
from ray_tpu.util import tracing as _tracing

ARGS_INLINE_LIMIT = 512 * 1024  # args bigger than this go through the store


class _Lease:
    """A worker granted to this client for direct task pushes. `via` is
    the granting node daemon's scheduler address (two-level path) or None
    when the head granted it — releases route back to the granter."""

    __slots__ = ("worker_id", "addr", "inflight", "last_used", "dead", "via",
                 "acquire_mode")

    def __init__(self, worker_id: WorkerID, addr: Tuple[str, int],
                 via: Optional[Tuple[str, int]] = None):
        self.worker_id = worker_id
        self.addr = addr
        self.inflight = 0
        self.last_used = time.monotonic()
        self.dead = False
        self.via = via
        self.acquire_mode = None  # flight recorder: local|spillback|head


class CoreClient:
    def __init__(self, head_host: str, head_port: int, session: str,
                 is_driver: bool, handlers: Optional[dict] = None):
        self.head_host, self.head_port = head_host, head_port
        self.session = session
        self.is_driver = is_driver
        self.worker_id = WorkerID.generate()
        # capacity enforcement/spill is the head's job; client stores only
        # create/attach segments
        self.store = SharedMemoryStore(session, capacity_bytes=1 << 62)
        self.local_metas: Dict[ObjectID, ObjectMeta] = {}
        self._registered: set = set()     # object ids known to head
        self.fn_manager = FunctionManager(self)
        from ray_tpu.core.device_store import DeviceObjectStore

        self.device_store = DeviceObjectStore()
        self._extra_handlers = dict(handlers or {})
        # head liveness probes (answered on the client's loop thread, so a
        # blocked user thread doesn't read as dead)
        self._extra_handlers.setdefault("health_ping", self._on_health_ping)
        self._extra_handlers.setdefault("pubsub", self._on_pubsub)
        # head→process push when the directory drops one of our device
        # objects (refcount reached zero)
        self._extra_handlers.setdefault("free_device_object",
                                        self._on_free_device_object)
        self._extra_handlers.setdefault("evicted_object",
                                        self._on_evicted_object)
        self._extra_handlers.setdefault("lease_revoke",
                                        self._on_lease_revoke_msg)
        # cooperative stack dump (the reference dashboard's py-spy
        # reporter, without needing ptrace): every process answers with
        # the live stacks of all its threads
        self._extra_handlers.setdefault("dump_stacks", self._on_dump_stacks)
        if is_driver:
            # streamed worker-log lines (task/actor prints) land at the
            # submitting terminal by default (reference print_logs)
            self._extra_handlers.setdefault("log_lines", self._on_log_lines)
        self._direct: Dict[Tuple[str, int], protocol.Connection] = {}
        self._actor_addr_cache: Dict[ActorID, Tuple[str, int]] = {}
        # compiled-DAG channels hosted by THIS process (created via the
        # dag_chan_create direct RPC); plus the serving-side read pool
        self._dag_channels: Dict[str, Any] = {}
        self._dag_read_pool = None
        # user pubsub subscriptions: channel -> [callback]
        self._pubsub_callbacks: Dict[str, list] = {}
        # post-reconnect hooks (pool_reconcile pattern for client-held
        # state): after a successful head reconnect each callback runs
        # once so publishers re-announce state the restarted head lost
        # (e.g. prefix-store pin tables). Fired on the loop thread —
        # callbacks must be non-blocking (pushes, not round trips).
        self._reconnect_callbacks: list = []
        self.loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(target=self._run_loop, daemon=True,
                                             name="ray_tpu-client-loop")
        self.conn: Optional[protocol.Connection] = None
        self.direct_server: Optional[protocol.Server] = None
        self.direct_port: Optional[int] = None
        self.node_info: dict = {}
        self.current_actor_id: Optional[ActorID] = None  # set when hosting an actor
        # in-flight actor calls: return ObjectID -> concurrent Future of reply
        self._pending_calls: Dict[ObjectID, Any] = {}
        self._pending_lock = threading.Lock()
        self._actor_order_locks: Dict[ActorID, asyncio.Lock] = {}
        # per-actor count of live fallback sends (loop-confined): while
        # nonzero, fast-path sends must queue behind them for order
        self._fallbacks_pending: Dict[ActorID, int] = {}
        self._started = threading.Event()
        self._blocked_depth = 0
        self._blocked_lock = threading.Lock()
        self.node_id: Optional[NodeID] = None
        # head-restart survival (reference GCS-client reconnect): bounded
        # reconnect window; 0 restores die-on-disconnect behavior.
        # last_reconnect_ts lets recovery-aware paths (fn_manager.load)
        # treat misses right after a restart as transient.
        self._reconnect_s = _config.get("reconnect_timeout_s")
        self.last_reconnect_ts = 0.0
        self._register_ts = 0.0  # when node_info (head_uptime_s) was taken
        self._closing = False
        self._connected = threading.Event()
        self._connected.set()
        # head-scheduled submissions not yet observed complete, keyed by
        # first return id: a restarted head lost its queue, so these are
        # replayed on reconnect (client-side re-queue; bounded FIFO)
        self._inflight_specs: "OrderedDict[ObjectID, dict]" = OrderedDict()
        self._inflight_lock = threading.Lock()
        # cross-node pull machinery (loop-confined): data-server conns,
        # in-flight pull dedup, LRU-bounded cache of pulled copies
        self._data_conns: Dict[Tuple[str, int], protocol.Connection] = {}
        self._pull_tasks: Dict[ObjectID, asyncio.Task] = {}
        # owner-side staged host snapshots of device objects + in-flight
        # staging dedup (freed with the device object)
        self._device_snapshots: Dict[ObjectID, ObjectMeta] = {}
        self._staging: Dict[ObjectID, asyncio.Future] = {}
        # worker leases for direct task pushes (reference
        # NormalTaskSubmitter lease reuse): shape key -> _Lease
        self._leases: Dict[tuple, "_Lease"] = {}
        self._draining: list = []  # revoked leases with in-flight pushes
        self._lease_acquiring: set = set()
        self._lease_lock = threading.Lock()
        self._lease_idle_s = _config.get("lease_idle_s")
        self._lease_reaper_started = False
        # two-level scheduling: head-pushed cluster resource view + cached
        # connections to node-daemon schedulers; grants via a daemon never
        # touch the head (stats observable for tests/diagnostics)
        from ray_tpu.core.resource_view import ClusterView

        self.cluster_view = ClusterView()
        # gossiped object directory: location announcements piggybacked on
        # cluster_view pushes — a warm get() of a remote object resolves
        # meta + serving node from cache, zero head RPCs
        # (core/object_directory.py)
        from ray_tpu.core.object_directory import ObjectDirectory

        self.object_dir = ObjectDirectory()
        # metas of copies the LOCAL node's pull manager fetched for us
        # (daemon/head data server `pull_object`): the node owns the
        # replica's lifetime, so these are plain pointers, never freed by
        # this process (unlike _pulled, whose copies are ours to unlink)
        self._daemon_pulled: "OrderedDict[ObjectID, ObjectMeta]" = OrderedDict()
        data_port = os.environ.get("RAY_TPU_NODE_DATA_PORT")
        self._node_data_addr = (("127.0.0.1", int(data_port))
                                if data_port else None)
        self._sched_conns: Dict[Tuple[str, int], protocol.Connection] = {}
        self.lease_stats = {"daemon_grants": 0, "head_grants": 0,
                            "spills": 0, "peer_grants": 0}
        # headless resilience: cold-path tasks park in per-shape local
        # dispatch queues while the head is unreachable/suspect and drain
        # through daemon/peer-granted leases — the head stops being a
        # required hop on the cold task path. `_head_suspect_until` is
        # armed when a head lease RPC times out with the connection still
        # "open" (a paused head keeps TCP alive).
        self._lease_parked: Dict[tuple, deque] = {}
        self._lease_parked_ts: Dict[tuple, float] = {}
        self._parked_exec_tasks: set = set()
        self._head_suspect_until = 0.0
        # epoch fencing: the cluster epoch observed from the head
        # (registration reply + cluster_view pushes); lease traffic to
        # node-daemon schedulers is tagged with it, and a daemon that has
        # reconciled with a newer head refuses the stale-epoch grant
        self.cluster_epoch = 0
        # flight recorder, driver side: scheduling-phase events for traced
        # tasks (submit → lease-acquire[mode] → dispatch → run) consumed by
        # ray_tpu.timeline(); recorded only while tracing is enabled, so
        # the untraced hot path pays one boolean check
        self.sched_events: "deque[dict]" = deque(
            maxlen=_config.get("flight_recorder_head_events"))
        self._pull_sem: Optional[asyncio.Semaphore] = None
        self._pulled: "OrderedDict[ObjectID, ObjectMeta]" = OrderedDict()
        self._pulled_lock = threading.Lock()  # loop inserts, user threads free
        self._pulled_bytes = 0
        self._pull_cache_cap = _config.get("pull_cache_bytes")
        self.on_disconnect = None
        # invoked synchronously inside the start coroutine, right after the
        # head acks registration and before any pushed task handler can run
        self.on_registered = None
        # batched loop handoff: every call_soon_threadsafe pays a self-pipe
        # write to wake the loop; a pipelined burst (2000 actor calls) paid
        # it 2000 times. One queue + one scheduled drain per wakeup keeps
        # submission order (single FIFO) while collapsing the syscalls.
        self._loop_calls: deque = deque()
        self._loop_calls_lock = threading.Lock()
        self._loop_calls_scheduled = False

    # ----------------------------------------------------------- lifecycle
    def _run_loop(self):
        asyncio.set_event_loop(self.loop)
        protocol.enable_eager_tasks(self.loop)
        self.loop.run_forever()

    async def _on_free_device_object(self, object_id):
        oid = ObjectID(object_id)
        self.device_store.pop(oid)
        snap = self._device_snapshots.pop(oid, None)
        if snap is not None:
            try:
                self.store.free(snap)  # staged host copy dies with the value
            except Exception:
                pass
        return True

    async def _on_health_ping(self):
        return True

    # ------------------------------------------- compiled-DAG channel plane
    # Reference: remote-reader mutable objects
    # (`python/ray/experimental/channel/shared_memory_channel.py`,
    # `src/ray/core_worker/experimental_mutable_object_provider.cc`) — a
    # channel lives in its WRITER's process; cross-node readers read
    # through these RPCs on the writer process's direct server.

    async def _on_dag_chan_create(self, name, capacity, num_readers,
                                  num_slots=1):
        from ray_tpu.dag.channel import Channel

        if name not in self._dag_channels:
            ch = Channel(name=name, capacity=capacity,
                         num_readers=num_readers, num_slots=num_slots)
            ch._rlock = threading.Lock()
            self._dag_channels[name] = ch
        return True

    async def _on_dag_chan_read(self, name, last_seq, max_wait):
        from ray_tpu.dag.channel import Channel, ChannelClosedError

        ch = self._dag_channels.get(name)
        if ch is None:
            # a reader of a channel another local process created (the
            # driver co-located with a worker): serve from an attachment
            try:
                ch = Channel.attach(name)
            except Exception:
                return {"closed": True}
            ch._rlock = threading.Lock()
            self._dag_channels[name] = ch
        if self._dag_read_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._dag_read_pool = ThreadPoolExecutor(
                max_workers=16, thread_name_prefix="dag-read")

        def blocking():
            # reads share the channel's scratch buffer — serialize them
            with ch._rlock:
                try:
                    seq, data = ch.read_raw(last_seq, timeout=max_wait)
                    return {"seq": seq, "data": data}
                except TimeoutError:
                    return {"seq": last_seq, "data": None}
                except ChannelClosedError:
                    return {"closed": True}

        return await asyncio.get_running_loop().run_in_executor(
            self._dag_read_pool, blocking)

    async def _on_dag_chan_close(self, name, unlink):
        ch = self._dag_channels.pop(name, None)
        if ch is not None:
            # shutdown first: wakes any read blocked in the pool (new
            # ops see closed); the munmap-ing close then runs under the
            # read lock OFF the event loop, so it can never pull the
            # mapping out from under an in-flight blocking() read
            ch.shutdown()

            def _close():
                with ch._rlock:
                    ch.close(unlink=unlink)

            if self._dag_read_pool is not None:
                self._dag_read_pool.submit(_close)
            else:
                _close()
        return True

    async def _on_pubsub(self, channel, msg):
        """Head pubsub fan-in. actor_state transitions poison stale direct
        connections: when the head declares an actor's worker dead while
        its SOCKET is still open (hung process reaped by health checks),
        in-flight direct calls would otherwise wait on a frozen peer
        forever — closing the connection fails them into the resend path,
        which re-resolves the restarted actor's address (reference:
        ActorTaskSubmitter's GCS actor-state subscription)."""
        if channel == "cluster_view":
            self.cluster_view.adopt(msg)
            self.cluster_epoch = msg.get("epoch", self.cluster_epoch)
            self.object_dir.apply(msg.get("objects"))
        if channel == "actor_state" and msg.get("state") in ("RESTARTING",
                                                             "DEAD"):
            aid = ActorID(msg["actor_id"])
            addr = self._actor_addr_cache.pop(aid, None)
            if addr is not None:
                conn = self._direct.pop(addr, None)
                if conn is not None and not conn.closed:
                    asyncio.ensure_future(conn.close())
        # snapshot: subscribers add/remove from other threads (the train
        # controller's death watch); mutating the live list mid-iteration
        # would skip a neighbor's callback for this event
        for cb in list(self._pubsub_callbacks.get(channel, ())):
            try:
                cb(msg)
            except Exception:
                pass   # a user callback must never break the loop
        return True

    def subscribe_channel(self, channel: str, callback) -> None:
        """Public pubsub: `callback(msg_dict)` for every event the head
        publishes on `channel` (node_state / actor_state / object_state;
        reference `src/ray/pubsub/` channels). Callbacks run on the
        client's loop thread — hand off, don't block."""
        # empty list counts as first too: unsubscribe_channel leaves the
        # key behind, and a restarted head has no subscriber table — a
        # re-arm after disarm must re-issue the subscribe RPC (it is
        # idempotent head-side)
        first = not self._pubsub_callbacks.get(channel)
        self._pubsub_callbacks.setdefault(channel, []).append(callback)
        if first and channel != "actor_state":   # actor_state: always subbed
            self._wait_connected()
            self._call(self.conn.request("subscribe", channel=channel))

    def unsubscribe_channel(self, channel: str, callback) -> None:
        """Drop a `subscribe_channel` callback. The head-side channel
        subscription stays (it is per-connection and cheap); only the
        local fan-out entry is removed — callers that re-arm per worker
        group (the train controller's death watch) don't accumulate
        dead callbacks across restarts."""
        cbs = self._pubsub_callbacks.get(channel)
        if cbs and callback in cbs:
            cbs.remove(callback)

    async def _on_dump_stacks(self):
        """Formatted stacks of every thread in this process (reference:
        dashboard reporter's py-spy dump, done cooperatively)."""
        import traceback

        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        out = []
        for ident, frame in frames.items():
            out.append(f"--- thread {names.get(ident, '?')} ({ident})")
            out.extend(l.rstrip() for l in traceback.format_stack(frame))
        return "\n".join(out)

    async def _on_log_lines(self, entries):
        """Head-streamed worker log lines: print at this driver."""
        from ray_tpu.core import worker_logs

        worker_logs.print_driver_entries(entries)
        return True

    def _note_complete(self, oid: ObjectID) -> None:
        """A task's result meta was observed: its spec no longer needs
        head-restart replay."""
        if self._inflight_specs:
            with self._inflight_lock:
                self._inflight_specs.pop(oid, None)

    async def _on_evicted_object(self, meta):
        """Head evicted an object we own: drop our mapping, accounting and
        caches (auto-eviction must clean the producer like manual free())."""
        oid = meta.object_id
        self._note_complete(oid)
        self.local_metas.pop(oid, None)
        self._registered.discard(oid)
        pulled = self._drop_pulled(oid)
        for m in (pulled, meta):
            if m is None:
                continue
            try:
                self.store.free(m)
            except Exception:
                pass
        return True

    async def _on_fetch_device_object(self, object_id):
        """Another process wants a device object we own: stage a host
        snapshot into node shm (once, in an executor thread — a multi-GB
        D2H must not stall this loop) and reply with its tiny meta. The
        consumer maps the shm directly (same node) or pulls it through
        the chunked data plane (cross node) — the bulk bytes never ride
        this control connection (reference: accelerator tensor channel,
        torch_tensor_accelerator_channel.py)."""
        oid = ObjectID(object_id)
        try:
            value = self.device_store.get(oid)
        except KeyError:
            raise FileNotFoundError(f"device object {oid} not here") from None
        meta = self._device_snapshots.get(oid)
        if meta is None:
            from ray_tpu.core import device_transport

            task = self._staging.get(oid)
            if task is None:  # concurrent fetchers share one D2H
                task = asyncio.ensure_future(
                    asyncio.get_running_loop().run_in_executor(
                        None, device_transport.stage_snapshot,
                        self, oid, value))
                self._staging[oid] = task
                task.add_done_callback(
                    lambda t, o=oid: self._staging.pop(o, None))
            meta = await asyncio.shield(task)
            if not self.device_store.contains(oid):
                # freed while we were staging: the free handler saw no
                # snapshot entry, so the snapshot must be released here or
                # the shm leaks. Exactly ONE of the concurrent fetchers
                # sharing this staging task may free it — the check-and-set
                # is race-free because every waiter resumes on this loop.
                if not getattr(task, "_orphan_freed", False):
                    task._orphan_freed = True
                    try:
                        self.store.free(meta)
                    except Exception as e:
                        print(f"[ray_tpu] freeing orphan snapshot of "
                              f"{oid.hex()[:12]} failed: {e!r}",
                              file=sys.stderr, flush=True)
                raise FileNotFoundError(f"device object {oid} freed")
            self._device_snapshots[oid] = meta
        return {"meta": meta}

    async def _on_fetch_device_ici(self, object_id, group_name, dst_rank):
        """Gang-member fetch: a peer of one of our xla-multihost groups
        wants this device object. Ship the pytree skeleton over this
        control connection and every jax leaf over the gang's device mesh
        (pair-mesh ppermute — ICI on TPU), never touching host pickle for
        the array bytes."""
        oid = ObjectID(object_id)
        try:
            value = self.device_store.get(oid)
        except KeyError:
            raise FileNotFoundError(f"device object {oid} not here") from None
        from ray_tpu.util.collective import collective as col

        group = col._groups.get(group_name)
        if group is None or getattr(group, "backend_name", "") != "xla-multihost":
            return None  # consumer falls back to the shm snapshot path
        import jax

        from ray_tpu.core import device_transport as dt

        leaves, treedef = jax.tree_util.tree_flatten(value)
        descs, skeleton_leaves, dev_leaves = [], [], []
        for leaf in leaves:
            if isinstance(leaf, jax.Array):
                descs.append({"shape": tuple(leaf.shape),
                              "dtype": str(leaf.dtype)})
                skeleton_leaves.append(dt.IciLeaf(len(descs) - 1))
                dev_leaves.append(leaf)
            else:
                skeleton_leaves.append(leaf)
        skeleton = jax.tree_util.tree_unflatten(treedef, skeleton_leaves)

        def _send_all():
            if _config.get("testing_ici_drop_send"):
                return  # chaos hook: reply sent, transfer never happens
            for leaf in dev_leaves:
                group.send_device(leaf, dst_rank)

        # sends run concurrently with the consumer's recvs (each pair-mesh
        # program blocks until both peers join); never on this loop. A
        # failed send leaves the consumer blocked in its recv — inherent
        # to collective p2p (NCCL parity); at minimum the failure must be
        # loud on the owner, not a silently dropped Future.
        fut = asyncio.get_running_loop().run_in_executor(None, _send_all)

        def _log_failure(f):
            exc = f.exception()
            if exc is not None:
                print(f"[ray_tpu] ICI send of {oid.hex()[:12]} to rank "
                      f"{dst_rank} failed: {exc!r}", file=sys.stderr,
                      flush=True)

        fut.add_done_callback(_log_failure)
        return {"skeleton": serialization.dumps(skeleton), "descs": descs}

    def _try_ici_fetch(self, meta: ObjectMeta) -> Optional[Any]:
        """Device-plane get() between gang members: when the owner and we
        are both members of one xla-multihost group, leaves ride the ICI
        mesh instead of a host-staged snapshot. Returns None when the
        route does not apply (caller falls back)."""
        if meta.owner is None:
            return None
        from ray_tpu.util.collective import collective as col
        from ray_tpu.util.collective import xla_multihost as xmh

        mine = {name: g for name, g in list(col._groups.items())
                if getattr(g, "backend_name", "") == "xla-multihost"}
        if not mine:
            return None
        info = xmh.lookup_membership(self, meta.owner.hex())
        if not info or info.get("group") not in mine:
            return None
        group = mine[info["group"]]
        src = info["rank"]
        if src == group.rank:
            return None
        rep = self._call(self._direct_owner_request(
            meta, "fetch_device_ici", object_id=meta.object_id.binary(),
            group_name=info["group"], dst_rank=group.rank))
        if rep is None:
            return None
        import jax

        from ray_tpu.core import device_transport as dt

        def _recv_all():
            return [group.recv_device(tuple(d["shape"]), d["dtype"], src)
                    for d in rep["descs"]]

        # a pair-mesh recv blocks until the peer joins — a peer that died
        # between its reply and its send would hang this get() forever
        # (NCCL-parity). Bound it with a DAEMON thread: on timeout the
        # consumer surfaces ObjectLostError while the recv thread stays
        # parked on the dead collective (the group is poisoned, as a dead
        # NCCL communicator would be) — daemon, so a parked thread never
        # blocks interpreter exit (ThreadPoolExecutor's atexit join would).
        timeout_s = _config.get("ici_fetch_timeout_s")
        box: dict = {}
        done = threading.Event()

        def _runner():
            try:
                box["v"] = _recv_all()
            except BaseException as e:  # noqa: BLE001 - marshalled to caller
                box["e"] = e
            finally:
                done.set()

        threading.Thread(target=_runner, daemon=True,
                         name="ici-recv").start()
        if not done.wait(timeout_s):
            raise ObjectLostError(
                f"device object {meta.object_id}: gang peer rank {src} "
                f"never entered the ICI transfer within {timeout_s}s "
                f"(owner crashed mid-handoff?); group "
                f"{info['group']!r} may be poisoned")
        if "e" in box:
            raise box["e"]
        received = box["v"]
        skeleton = serialization.loads(bytes(rep["skeleton"]))
        return jax.tree_util.tree_map(
            lambda x: received[x.index] if isinstance(x, dt.IciLeaf) else x,
            skeleton,
            is_leaf=lambda x: isinstance(x, dt.IciLeaf))

    def start(self, direct_handlers: Optional[dict] = None) -> None:
        direct_handlers = dict(direct_handlers or {})
        direct_handlers.setdefault("fetch_device_object",
                                   self._on_fetch_device_object)
        direct_handlers.setdefault("fetch_device_ici",
                                   self._on_fetch_device_ici)
        # compiled-DAG channel plane (process-level, independent of the
        # actor executor — teardown works even while an exec loop runs)
        direct_handlers.setdefault("dag_chan_create", self._on_dag_chan_create)
        direct_handlers.setdefault("dag_chan_read", self._on_dag_chan_read)
        direct_handlers.setdefault("dag_chan_close", self._on_dag_chan_close)
        # tracker active BEFORE the loop can dispatch anything: a task or
        # actor __init__ processed during registration may construct
        # ObjectRefs, and every one of them must be counted (else the head
        # never records this process as a holder and evicts early)
        self.ref_tracker = refcount.RefTracker(self)
        refcount.activate(self.ref_tracker)
        from ray_tpu.core import flight_recorder

        flight_recorder.install("driver" if self.is_driver else "worker")
        self._loop_thread.start()
        fut = asyncio.run_coroutine_threadsafe(
            self._start_async(direct_handlers or {}), self.loop)
        fut.result(timeout=30)
        # refcounting on/off is the HEAD's setting, distributed at
        # registration — per-process env vars can't diverge into a head
        # that evicts objects a non-reporting process still holds
        self.ref_tracker.set_enabled(self.node_info.get("refcount", True))
        self._started.set()

    async def _start_async(self, direct_handlers: dict) -> None:
        self.direct_server = protocol.Server(direct_handlers, name="direct")
        self.direct_port = await self.direct_server.start(
            host=_config.get("bind_host"))
        self.conn = await protocol.connect(self.head_host, self.head_port,
                                           handlers=self._extra_handlers,
                                           name="head")
        self.conn.on_close = lambda c: self._handle_head_loss()
        node_id_hex = os.environ.get("RAY_TPU_NODE_ID")
        self.node_info = await self.conn.request(
            "register_worker", worker_id=self.worker_id.binary(), pid=os.getpid(),
            port=self.direct_port, is_driver=self.is_driver,
            node_id=bytes.fromhex(node_id_hex) if node_id_hex else None,
            log_tag=os.environ.get("RAY_TPU_LOG_TAG"),
            venv_key=os.environ.get("RAY_TPU_VENV_KEY"))
        # actor failover needs to hear about restarts it can't observe via
        # its own sockets (hung-worker reaping) — fire-and-forget so
        # registration latency doesn't grow. cluster_view feeds the local
        # feasible-node cache for two-level lease routing.
        asyncio.ensure_future(self.conn.request("subscribe",
                                                channel="actor_state"))
        asyncio.ensure_future(self.conn.request("subscribe",
                                                channel="cluster_view"))
        self.node_id = NodeID(self.node_info["node_id"])
        self.cluster_epoch = self.node_info.get("epoch", 0)
        self._register_ts = time.monotonic()
        # negotiated flags: the head's values are authoritative for
        # cluster-shared semantics (config.py registry)
        _config.GLOBAL.adopt_head(self.node_info.get("config"))
        if (self.store.isolated and not self.store.namespace
                and not _config.get("store_namespace")):
            # isolation mode: our namespace is our node's — knowable only
            # after registration (no objects have been stored yet)
            self.store = SharedMemoryStore(
                self.session, capacity_bytes=1 << 62,
                namespace=self.node_id.hex()[:8])
        if self.on_registered is not None:
            self.on_registered(self.node_info)
        if self.is_driver:
            # minimal runtime-env: ship the driver's import roots so workers
            # can resolve by-reference pickles of driver-local modules (the
            # reference solves this with runtime_env working_dir packaging)
            import json as _json
            import sys as _sys

            await self.conn.request(
                "kv_put", ns="cluster", key=b"driver_sys_path",
                value=_json.dumps(
                    [p for p in _sys.path if p]).encode(), overwrite=True)

    def _handle_head_loss(self):
        # Reconnect-with-backoff (reference retryable_grpc_client + GCS
        # client reconnect semantics): a restarted head gets this process
        # back — re-register, replay directory entries and ref holds —
        # instead of the whole cluster's clients dying with it.
        if self._closing or self._reconnect_s <= 0:
            if self.on_disconnect:
                self.on_disconnect()
            return
        if not self._connected.is_set():
            return  # a reconnect loop is already running
        self._connected.clear()
        asyncio.ensure_future(self._reconnect_loop())

    async def _reconnect_loop(self) -> None:
        deadline = time.monotonic() + self._reconnect_s
        delay = 0.2
        while not self._closing and time.monotonic() < deadline:
            try:
                conn = await protocol.connect(self.head_host, self.head_port,
                                              handlers=self._extra_handlers,
                                              name="head")
            except OSError:
                await asyncio.sleep(delay)
                delay = min(delay * 1.6, 2.0)
                continue
            node_id_hex = os.environ.get("RAY_TPU_NODE_ID")
            try:
                info = await conn.request(
                    "register_worker", worker_id=self.worker_id.binary(),
                    pid=os.getpid(), port=self.direct_port,
                    is_driver=self.is_driver,
                    node_id=(bytes.fromhex(node_id_hex)
                             if node_id_hex else None),
                    log_tag=os.environ.get("RAY_TPU_LOG_TAG"),
                    venv_key=os.environ.get("RAY_TPU_VENV_KEY"),
                    # a restarted head parks reconnecting workers until
                    # their node daemon's reconciliation handshake claims
                    # or disowns them (double-grant fence)
                    reconnect=True)
            except Exception:
                try:
                    await conn.close()
                except Exception:
                    pass
                await asyncio.sleep(delay)
                continue
            self.conn = conn
            self.node_info = info
            self.node_id = NodeID(info["node_id"])
            self.cluster_epoch = info.get("epoch", self.cluster_epoch)
            self._register_ts = time.monotonic()
            conn.on_close = lambda c: self._handle_head_loss()
            _config.GLOBAL.adopt_head(info.get("config"))
            # the restarted head has no subscriber table: re-subscribe —
            # including every channel live pubsub callbacks still watch
            # (the train controller's death watch rides node_state; losing
            # it across a head restart would silently downgrade death
            # detection to poll timeouts)
            channels = {"actor_state", "cluster_view"}
            channels.update(ch for ch, cbs in self._pubsub_callbacks.items()
                            if cbs)
            for ch in channels:
                asyncio.ensure_future(conn.request("subscribe", channel=ch))
            # enablement is the head's setting; the restarted head may
            # differ and a non-reporting client would see early evictions
            self.ref_tracker.set_enabled(info.get("refcount", True))
            # the restarted head lost our directory entries and holds:
            # replay every meta we registered, then re-announce live refs
            for oid in list(self._registered):
                meta = self.local_metas.get(oid)
                if meta is not None:
                    try:
                        conn.push("put_meta", meta=meta)
                    except Exception:
                        pass
            self.ref_tracker.resync()
            # function/class defs exported after the head's last snapshot
            # died with it; replayed tasks reference them by hash
            self.fn_manager.resync()
            self.last_reconnect_ts = time.monotonic()
            if self.is_driver:
                import json as _json
                import sys as _sys

                try:
                    await conn.request(
                        "kv_put", ns="cluster", key=b"driver_sys_path",
                        value=_json.dumps(
                            [p for p in _sys.path if p]).encode(),
                        overwrite=True)
                except Exception:
                    pass
            # leased workers likely died with the head; mark dead so the
            # next submit fails over through the (new) head
            with self._lease_lock:
                for lease in self._leases.values():
                    lease.dead = True
            # client-side task re-queue: the restarted head has no task
            # queue, and a push can die in the old socket's buffer — so
            # every submission not yet observed complete is replayed
            # (at-least-once for retryable tasks, like lease failover;
            # max_retries=0 tasks surface an error instead of re-running)
            with self._inflight_lock:
                pending = list(self._inflight_specs.items())
            for rid0, spec in pending:
                if rid0 in self.local_metas:
                    with self._inflight_lock:
                        self._inflight_specs.pop(rid0, None)
                    continue
                if spec.get("options", {}).get("max_retries", 3):
                    sp = dict(spec)
                    sp["failover"] = True  # skip the dup holder add
                    try:
                        conn.push("submit_task", spec=sp)
                    except Exception:
                        pass
                else:
                    err = WorkerCrashedError(
                        "head restarted while a max_retries=0 task was in "
                        "flight; it may or may not have run")
                    try:
                        self.store_result(rid0, err, register=True,
                                          is_error=True)
                    except Exception:
                        pass
                    with self._inflight_lock:
                        self._inflight_specs.pop(rid0, None)
            self._connected.set()
            for cb in list(self._reconnect_callbacks):
                try:
                    cb()
                except Exception:
                    pass
            return
        self._connected.set()  # unblock waiters into their errors
        if self.on_disconnect:
            self.on_disconnect()

    def add_reconnect_callback(self, cb) -> None:
        """Run `cb()` after every successful head reconnect (loop
        thread; must not block). Used by publishers whose head-side
        state is rebuilt from client truth — the prefix store re-pushes
        its pin-table bindings the way pool_reconcile re-reports pools."""
        if cb not in self._reconnect_callbacks:
            self._reconnect_callbacks.append(cb)

    def remove_reconnect_callback(self, cb) -> None:
        if cb in self._reconnect_callbacks:
            self._reconnect_callbacks.remove(cb)

    def head_recovering(self) -> bool:
        """True inside the window where a restarted head may still be
        re-learning state from reconnecting processes — misses (e.g. a
        function def) are plausibly transient and worth a brief poll."""
        if self.last_reconnect_ts and (
                time.monotonic() - self.last_reconnect_ts < 30.0):
            return True
        age = self.node_info.get("head_uptime_s")
        if age is None or not self._register_ts:
            return False
        # a FRESH process (never reconnected) registered to a young head:
        # e.g. a worker spawned right after a restart, whose driver's
        # re-exports may still be in flight
        return age + (time.monotonic() - self._register_ts) < 60.0

    def _wait_connected(self) -> None:
        """Block a sync API call while a reconnect is in progress (bounded
        by the reconnect window) so callers see a brief stall, not an
        immediate ConnectionLost, across a head restart."""
        if not self._connected.is_set():
            self._connected.wait(timeout=self._reconnect_s + 5)

    def shutdown(self) -> None:
        # final metrics flush BEFORE the connection closes: a short-lived
        # worker/driver otherwise silently loses its last
        # <metrics_push_interval_s of counter increments
        try:
            from ray_tpu.util import metrics as _m

            _m.flush(wait=True)
        except Exception:
            pass
        self._closing = True
        refcount.activate(None)

        async def _close():
            if self.conn:
                await self.conn.close()
            for c in self._direct.values():
                await c.close()
            for c in self._data_conns.values():
                await c.close()
            for c in self._sched_conns.values():
                await c.close()
            if self.direct_server:
                await self.direct_server.stop()

        try:
            asyncio.run_coroutine_threadsafe(_close(), self.loop).result(timeout=5)
        except Exception:
            pass
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._loop_thread.join(timeout=5)

    # ---------------------------------------------------------------- sync
    def _call(self, coro, timeout=None):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout=timeout)

    def _loop_call_soon(self, fn, *args) -> None:
        """Thread-safe loop handoff with coalesced wakeups: enqueued
        callables run on the loop in enqueue order; only the first one
        after an idle period pays the self-pipe wakeup."""
        with self._loop_calls_lock:
            self._loop_calls.append((fn, args))
            if self._loop_calls_scheduled:
                return
            self._loop_calls_scheduled = True
        try:
            self.loop.call_soon_threadsafe(self._drain_loop_calls)
        except RuntimeError:
            # loop stopped/closed mid-shutdown: reset the flag so later
            # callers raise here too instead of parking behind a drain
            # that will never run (head_request would block forever)
            with self._loop_calls_lock:
                self._loop_calls_scheduled = False
            raise

    def _drain_loop_calls(self) -> None:
        while True:
            with self._loop_calls_lock:
                if not self._loop_calls:
                    self._loop_calls_scheduled = False
                    return
                batch = list(self._loop_calls)
                self._loop_calls.clear()
            for fn, args in batch:
                try:
                    fn(*args)
                except Exception as e:
                    print(f"[ray_tpu] loop call {fn} failed: {e!r}",
                          file=sys.stderr, flush=True)

    def head_request(self, method: str, **kwargs) -> Any:
        """Blocking head RPC without per-call coroutine/Task overhead:
        the request is written by a plain loop callback and the reply
        future chains straight into a concurrent future (the same trick
        as _fast_actor_send — Task creation was a measurable slice of
        every control-plane round trip).

        Rides a head restart: a ConnectionLost inside the reconnect
        window retries on the re-established connection instead of
        surfacing into callers (a worker fetching a function blob
        mid-outage would otherwise poison its task's result with an
        infrastructure error the retry machinery never sees)."""
        deadline = time.monotonic() + max(self._reconnect_s, 0.0) + 5.0
        while True:
            self._wait_connected()
            cfut: _cf.Future = _cf.Future()
            conn = self.conn  # bind now: a reconnect must not swap mid-flight

            def _send(conn=conn, cfut=cfut):
                try:
                    fut = conn.request_future(method, **kwargs)
                except Exception as e:
                    if not cfut.cancelled():
                        cfut.set_exception(e)
                    return

                def _done(f):
                    if cfut.cancelled():
                        return
                    if f.cancelled():
                        cfut.cancel()
                    elif f.exception() is not None:
                        cfut.set_exception(f.exception())
                    else:
                        cfut.set_result(f.result())

                fut.add_done_callback(_done)

            self._loop_call_soon(_send)
            try:
                return cfut.result()
            except protocol.ConnectionLost:
                if (self._closing or self._reconnect_s <= 0
                        or time.monotonic() >= deadline
                        # a ConnectionLost while the conn is still open is
                        # synthetic (chaos injection): surface it — only a
                        # genuinely dead head rides the reconnect
                        or not conn.closed):
                    raise
                time.sleep(0.1)  # _handle_head_loss swaps self.conn

    def direct_request(self, addr, method: str, **kwargs) -> Any:
        """Synchronous RPC to another process's direct server (connection
        cached/shared with the actor-call path)."""
        self._wait_connected()

        async def go():
            addr_t = (addr[0], int(addr[1]))
            conn = self._direct.get(addr_t)
            if conn is None or conn.closed:
                conn = await protocol.connect(*addr_t,
                                              name=f"direct-{addr_t[1]}")
                self._direct[addr_t] = conn
            return await conn.request(method, **kwargs)

        return self._call(go())

    # ------------------------------------------------------------- objects
    def put(self, value: Any, owner: Optional[str] = None) -> ObjectRef:
        oid = ObjectID.generate()
        ser = serialization.serialize(value)
        meta = self.store.put_serialized(oid, ser)
        meta.node_id = self.node_id
        meta.owner = self.worker_id
        meta.contained = [o.binary() for o in ser.contained] or None
        self.local_metas[oid] = meta
        self._register_meta(meta)
        return ObjectRef(oid)

    def put_device(self, value: Any) -> ObjectRef:
        """Store a device-resident value (jax.Array or pytree) in THIS
        process's device store; only the meta travels. Same-process get()
        returns the living object zero-copy; cross-process get() fetches a
        host snapshot from us (reference RDT GPUObjectStore design)."""
        from ray_tpu.core import device_store as ds

        oid = ObjectID.generate()
        size = self.device_store.put(oid, value)
        meta = ObjectMeta(oid, size, "device")
        meta.node_id = self.node_id
        meta.owner = self.worker_id
        meta.inline = None
        # record on the meta whether top-level is a jax.Array so consumers
        # re-materialize on their device without asking us again
        meta.segment = "jax" if ds.is_device_value(value) else None
        self.local_metas[oid] = meta
        self._register_meta(meta)
        return ObjectRef(oid)

    def store_device_result(self, oid: ObjectID, value: Any) -> ObjectMeta:
        """Actor-method result kept on device (tensor_transport option).

        Registered with the head (unlike plain actor replies): the head's
        refcount-driven free is what releases the value from our device
        store — without it, every device result would pin HBM for the
        actor's lifetime."""
        from ray_tpu.core import device_store as ds

        size = self.device_store.put(oid, value)
        meta = ObjectMeta(oid, size, "device")
        meta.node_id = self.node_id
        meta.owner = self.worker_id
        meta.segment = "jax" if ds.is_device_value(value) else None
        self.local_metas[oid] = meta
        # non-blocking registration: this runs on the loop for async actor
        # methods, where a blocking request would deadlock; the consumer
        # gets the meta from the reply, the head entry only drives lifetime
        self._registered.add(oid)
        self.head_push("put_meta", meta=meta)
        return meta

    def _get_device_value(self, meta: ObjectMeta) -> Any:
        """Resolve a kind=='device' meta: living value when we own it;
        between gang members, leaves ride the ICI mesh; otherwise a
        shm-snapshot read (zero-copy map same-node, chunked pull
        cross-node)."""
        oid = meta.object_id
        if self.device_store.contains(oid):
            return self.device_store.get(oid)
        ici = self._try_ici_fetch(meta)
        if ici is not None:
            return ici
        from ray_tpu.core import device_transport

        snap = self._call(self._fetch_device_async(meta))["meta"]
        return device_transport.load_snapshot(self.read_serialized(snap))

    async def _direct_owner_request(self, meta: ObjectMeta, method: str,
                                    **kwargs):
        """RPC straight to the owning process's direct server."""
        addr = await self.conn.request("worker_address",
                                       worker_id=meta.owner.binary())
        if addr is None:
            raise ObjectLostError(
                f"device object {meta.object_id} lost: owner process gone")
        host, port = addr
        conn = self._data_conns.get((host, port))
        if conn is None or conn.closed:
            conn = await protocol.connect(host, port, name=f"dev-{port}")
            self._data_conns[(host, port)] = conn
        return await conn.request(method, **kwargs)

    async def _fetch_device_async(self, meta: ObjectMeta):
        """Ask the owner to stage its snapshot; returns {"meta": snapshot
        meta} — bytes travel separately over the data plane."""
        return await self._direct_owner_request(
            meta, "fetch_device_object", object_id=meta.object_id.binary())

    def put_serialized(self, ser: SerializedObject, error: bool = False,
                       register: bool = True) -> ObjectMeta:
        oid = ObjectID.generate()
        meta = self.store.put_serialized(oid, ser)
        meta.error = error
        meta.node_id = self.node_id
        meta.owner = self.worker_id
        meta.contained = [o.binary() for o in ser.contained] or None
        self.local_metas[oid] = meta
        if register:
            self._register_meta(meta)
        return meta

    def store_result(self, oid: ObjectID, value: Any, register: bool,
                     is_error: bool = False,
                     via_head: bool = False) -> ObjectMeta:
        """`via_head=True` promises the meta reaches the head on another
        channel (e.g. generator_yield seals it) — skip the extra push."""
        ser = serialization.serialize(value)
        meta = self.store.put_serialized(oid, ser)
        meta.error = is_error
        # node-stamped so a cross-node consumer of an UNregistered meta
        # (direct actor reply) can still find our node's data server
        meta.node_id = self.node_id
        meta.owner = self.worker_id
        meta.contained = [o.binary() for o in ser.contained] or None
        self.local_metas[oid] = meta
        if register:
            self._register_meta(meta)
        elif not via_head and (meta.contained or meta.kind != "inline"):
            # Two cases where a direct-reply result MUST still reach the
            # head. Embedded refs: the containment pin is what keeps the
            # inner objects alive once the producer drops its own refs.
            # Non-inline payloads: the bytes live in node storage (shm
            # arena / spill), and only a head directory entry lets the
            # consumer's eventual ref-drop free them — unregistered, the
            # dec writes a tombstone and the arena bytes leak forever.
            # Non-blocking push — this path runs on the loop for async
            # actor methods.
            self._registered.add(oid)
            self.head_push("put_meta", meta=meta)
        return meta

    def head_push(self, method: str, **kwargs) -> None:
        """Fire-and-forget message to the head, thread-safe. FIFO with
        every other message this client sends (incl. submit pushes), so
        registration-before-submit ordering is preserved without paying a
        blocking round trip."""
        self._loop_call_soon(
            functools.partial(self.conn.push, method, **kwargs))

    def _register_meta(self, meta: ObjectMeta) -> None:
        if meta.object_id in self._registered:
            return
        self._registered.add(meta.object_id)
        # push, not request: consumers that race ahead block in the head's
        # get_meta until this lands (same-connection FIFO per process)
        self.head_push("put_meta", meta=meta)

    def ensure_registered(self, ref: ObjectRef) -> None:
        if ref.id not in self.local_metas:
            # passing an in-flight actor-call result onward: join it first so
            # the head learns the object before anyone depends on it
            self._resolve_pending_call(ref.id)
        meta = self.local_metas.get(ref.id)
        if meta is not None and ref.id not in self._registered:
            self._registered.add(ref.id)
            self.head_request("put_meta", meta=meta)  # rides a head restart

    def adopt_meta(self, meta: ObjectMeta) -> ObjectRef:
        """Record a meta received from a direct actor reply."""
        self.local_metas[meta.object_id] = meta
        return ObjectRef(meta.object_id)

    def read_serialized(self, meta: ObjectMeta) -> SerializedObject:
        """Serialized bytes of `meta`, pulling from the owner node when the
        object isn't local (sync; called from user threads)."""
        try:
            return self.store.get_serialized(meta)
        except FileNotFoundError:
            pass
        # retry: a resolved cached copy can be evicted by a concurrent
        # pull's cache trim between resolve and read — re-resolve re-pulls
        for attempt in range(3):
            local = self._call(self._resolve_readable(meta))
            try:
                return self.store.get_serialized(local)
            except FileNotFoundError:
                self._drop_pulled(meta.object_id)
        raise ObjectLostError(f"object {meta.object_id} vanished during read")

    async def read_serialized_async(self, meta: ObjectMeta) -> SerializedObject:
        """Event-loop-safe variant (sync one would deadlock on the loop)."""
        try:
            return self.store.get_serialized(meta)
        except FileNotFoundError:
            pass
        for attempt in range(3):
            local = await self._resolve_readable(meta)
            try:
                return self.store.get_serialized(local)
            except FileNotFoundError:
                self._drop_pulled(meta.object_id)
        raise ObjectLostError(f"object {meta.object_id} vanished during read")

    def _drop_pulled(self, oid: ObjectID):
        """Forget a pulled copy; returns its meta (caller frees storage).
        Node-pulled pointers are dropped too so a retry re-resolves
        through the node pull manager (which re-pulls if it evicted)."""
        self._daemon_pulled.pop(oid, None)
        with self._pulled_lock:
            stale = self._pulled.pop(oid, None)
            if stale is not None:
                self._pulled_bytes -= stale.size
        return stale

    async def _resolve_readable(self, meta: ObjectMeta) -> ObjectMeta:
        """Produce a locally-readable meta for an object we can't read:
        stale meta (spilled/moved) or an object living on another node.
        Runs on the loop; concurrent requests for one object share a pull."""
        oid = meta.object_id
        task = self._pull_tasks.get(oid)
        if task is None:
            task = asyncio.ensure_future(self._locate_or_pull(meta))
            self._pull_tasks[oid] = task
            task.add_done_callback(
                lambda t, o=oid: self._pull_tasks.pop(o, None))
        return await asyncio.shield(task)

    def _probe_readable(self, meta: ObjectMeta) -> bool:
        try:
            view, rel = self.store.get_raw(meta, 0, 0)
            view.release()
            if rel is not None:
                rel()
            return True
        except (FileNotFoundError, OSError):
            return False

    def _dep_metas(self, deps: list) -> list:
        """Metas of a task's non-inline deps that this process already
        holds (e.g. results of lease tasks it submitted) — shipped with
        the spec so the executing worker skips the per-dep get_meta."""
        from ray_tpu.core.object_directory import PULLABLE_KINDS

        out = []
        for dep in deps:
            m = self.local_metas.get(ObjectID(dep))
            if m is not None and m.kind in PULLABLE_KINDS and not m.error:
                out.append(m)
        return out

    def lease_data_addr(self, fn_key: bytes, options: dict):
        """Data-server address of the node the current lease for this
        task shape lives on, or None — the push-side prefetch target for
        a pipeline stage's pending inputs. Resolved entirely from cache:
        the lease's granting-daemon sched address matched against the
        gossiped view entries."""
        shape = self._lease_shape(fn_key, options)
        with self._lease_lock:
            lease = self._leases.get(shape)
            via = None if lease is None or lease.dead else lease.via
        if via is None:
            return None
        via = tuple(via)
        for e in self.cluster_view.entries.values():
            sched = e.get("sched_addr")
            if sched is not None and tuple(sched) == via:
                addr = e.get("data_addr")
                return tuple(addr) if addr else None
        return None

    def prefetch_object(self, ref, addr) -> bool:
        """Fire-and-forget: ask the data server at `addr` (the node a
        consuming task will run on) to pull `ref`'s object into its node
        store ahead of dispatch, so the task's dependency fetch finds the
        bytes already local. The node PullManager's in-flight dedup
        merges this with the real fetch if they race. Best-effort by
        design — a lost prefetch only costs the overlap."""
        meta = self.local_metas.get(ref.id) if hasattr(ref, "id") else ref
        from ray_tpu.core.object_directory import PULLABLE_KINDS

        if (meta is None or meta.kind not in PULLABLE_KINDS or meta.error
                or addr is None):
            return False
        if meta.node_id is not None and self.cluster_view.data_addr_of(
                meta.node_id.hex()) == tuple(addr):
            return False  # already home: nothing to stage

        async def _go():
            key = tuple(addr)
            try:
                conn = self._data_conns.get(key)
                if conn is None or conn.closed:
                    conn = await protocol.connect(key[0], key[1],
                                                  name=f"data-{key[1]}")
                    self._data_conns[key] = conn
                await asyncio.wait_for(
                    conn.request("pull_object", meta=meta, sources=None),
                    timeout=120 + meta.size / (4 << 20))
            except Exception:
                pass  # prefetch is advisory; the dispatch-time pull wins

        try:
            asyncio.run_coroutine_threadsafe(_go(), self.loop)
        except Exception:
            return False
        return True

    def _sources_from_view(self, meta: ObjectMeta) -> list:
        """Candidate data-server addresses resolved ENTIRELY from cache:
        the gossiped object directory's locations (primary first, then
        advertised replicas) mapped through the cluster view's data_addr
        entries — the warm path that keeps remote get() head-RPC-free."""
        from ray_tpu.core.object_directory import resolve_addrs

        return resolve_addrs(self.object_dir, meta,
                             self.cluster_view.data_addr_of, self.head_host)

    async def _pull_via_node(self, meta: ObjectMeta,
                             sources: list) -> Optional[ObjectMeta]:
        """Ask the LOCAL node's pull manager (daemon, or the head's for
        head-node workers) to fetch the object into the node store: two
        workers on one node pulling the same remote object then cost one
        network crossing, not two. Returns None when no local manager is
        configured or the node-level pull failed (caller falls back to a
        direct pull)."""
        if self._node_data_addr is None \
                or not _config.get("node_pull_manager"):
            return None
        key = self._node_data_addr
        conn = self._data_conns.get(key)
        try:
            if conn is None or conn.closed:
                conn = await protocol.connect(key[0], key[1],
                                              name=f"data-{key[1]}")
                self._data_conns[key] = conn
            # size-aware bound: a multi-GB pull must not be abandoned at a
            # fixed wall time (the daemon would keep pulling while we
            # redundantly re-pull direct); assume a conservative 4 MiB/s
            # floor on top of a fixed grace. The trace carrier rides the
            # RPC so the daemon's pull span parents to the consuming
            # task's context.
            trace = _tracing.inject_context()
            with _tracing.start_span(
                    "object_pull",
                    attributes={"ray_tpu.op": "object_pull",
                                "object_id": meta.object_id.hex()[:16],
                                "size": meta.size, "via": "node"}):
                local = await asyncio.wait_for(
                    conn.request("pull_object", meta=meta, sources=sources,
                                 **({"trace": trace} if trace else {})),
                    timeout=120 + meta.size / (4 << 20))
        except (protocol.RpcError, OSError, asyncio.TimeoutError):
            return None
        if local is None or not self._probe_readable(local):
            return None
        self._daemon_pulled[local.object_id] = local
        while len(self._daemon_pulled) > 4096:  # metas only; node owns data
            self._daemon_pulled.popitem(last=False)
        return local

    async def _pull_from_cache(self, oid: ObjectID) -> Optional[ObjectMeta]:
        """One warm resolution attempt entirely from cache: gossiped
        directory meta + cluster-view addresses (node pull manager first,
        then direct pulls with replica failover). None when the cache
        cannot resolve the object — never a head RPC."""
        node_local = self._daemon_pulled.get(oid)
        if node_local is not None and self._probe_readable(node_local):
            return node_local
        fresh = self.object_dir.lookup_meta(oid)
        if fresh is None:
            return None
        self.local_metas[oid] = fresh
        if self._probe_readable(fresh):
            return fresh
        sources = self._sources_from_view(fresh)
        if sources or fresh.node_id is not None:
            local = await self._pull_via_node(fresh, sources)
            if local is not None:
                return local
        for addr in sources:
            try:
                return await self._pull_from(addr, fresh)
            except (protocol.RpcError, OSError, FileNotFoundError):
                continue
        return None

    async def _locate_or_pull(self, meta: ObjectMeta) -> ObjectMeta:
        oid = meta.object_id
        with self._pulled_lock:
            cached = self._pulled.get(oid)
            if cached is not None:
                self._pulled.move_to_end(oid)
        if cached is not None:
            return cached
        node_local = self._daemon_pulled.get(oid)
        if node_local is not None:
            if self._probe_readable(node_local):
                return node_local
            self._daemon_pulled.pop(oid, None)
        # warm path: fresh meta + serving nodes from the cached gossiped
        # directory, data addresses from the cached cluster view — no
        # head round trips at all
        fresh = self.object_dir.lookup_meta(oid)
        if fresh is not None:
            meta = fresh
            self.local_metas[oid] = fresh
            if self._probe_readable(fresh):
                return fresh  # e.g. retargeted spill file we can read
        sources = self._sources_from_view(meta)
        if sources or meta.node_id is not None:
            local = await self._pull_via_node(meta, sources)
            if local is not None:
                return local
        for addr in sources:  # direct pull with replica failover
            try:
                return await self._pull_from(addr, meta)
            except (protocol.RpcError, OSError, FileNotFoundError):
                continue  # node lost / object moved: next source or head
        if (not sources and meta.node_id is not None
                and meta.kind in ("shm", "arena", "spilled")
                and not self._head_suspect()):
            # meta names its node but the cached view doesn't know that
            # node's data server yet (cold driver): one head lookup
            try:
                addr = await asyncio.wait_for(
                    self.conn.request("node_data_addr",
                                      node_id=meta.node_id.binary()),
                    timeout=10.0)
            except (protocol.RpcError, OSError, asyncio.TimeoutError):
                addr = None
            if addr is not None:
                try:
                    return await self._pull_from(tuple(addr), meta)
                except (protocol.RpcError, OSError, FileNotFoundError):
                    pass
        # cold miss / all cached routes failed: the head directory is the
        # fallback — refreshed meta + every advertised source. The head
        # may be unreachable (outage) or unresponsive (paused), and this
        # shared pull task can be JOINED by get()s issued after the
        # gossiped directory learned the object — so between bounded head
        # attempts, re-consult the cached directory and serve from it the
        # moment it resolves: a cold miss must never block a now-warm hit
        # behind a head retry loop.
        # the deadline budgets FAILED attempts against a trusted head; a
        # suspect head (paused/reconnecting) pushes it out instead — a
        # transient control-plane outage must stall this get(), like the
        # unbounded request it replaces, not surface a spurious
        # ObjectLostError for an object that is merely unresolvable from
        # cache. A hard cap (reconnect window + slack) still bounds the
        # truly-dead-head case.
        deadline = time.monotonic() + 30.0
        hard_deadline = time.monotonic() + max(
            float(_config.get("reconnect_timeout_s")), 0.0) + 60.0
        last_exc: Optional[BaseException] = None
        while True:
            local = await self._pull_from_cache(oid)
            if local is not None:
                return local
            rep = None
            if not self._head_suspect():
                try:
                    # client-side bound outlasts the server-side get_meta
                    # wait, so it only fires against a head that stopped
                    # answering entirely (paused/hung)
                    rep = await asyncio.wait_for(
                        self.conn.request("locate_object",
                                          object_id=oid.binary(),
                                          timeout=30),
                        timeout=40.0)
                    break
                except (protocol.RpcError, OSError,
                        asyncio.TimeoutError) as e:
                    last_exc = e
            else:
                deadline = max(deadline, time.monotonic() + 10.0)
            if time.monotonic() >= min(deadline, hard_deadline):
                raise ObjectLostError(
                    f"object {oid} unresolvable: head unreachable and the "
                    f"cached directory has no serving copy "
                    f"({last_exc!r})") from last_exc
            await asyncio.sleep(0.2)
        if rep is None:
            raise ObjectLostError(f"object {oid} is gone")
        fresh = rep["meta"]
        self.local_metas[oid] = fresh
        if self._probe_readable(fresh):
            return fresh
        head_sources = [tuple(s) for s in (rep.get("sources")
                        or ([rep["data_addr"]] if rep.get("data_addr")
                            else []))]
        last_exc = None
        for addr in head_sources:
            try:
                return await self._pull_from(addr, fresh)
            except (protocol.RpcError, OSError, FileNotFoundError) as e:
                last_exc = e
        if last_exc is not None:
            raise ObjectLostError(
                f"object {oid} unreachable on {head_sources}: "
                f"{last_exc!r}") from last_exc
        raise ObjectLostError(f"object {oid} has no reachable location")

    async def _pull_from(self, addr, meta: ObjectMeta) -> ObjectMeta:
        host, port = addr
        if host is None:
            host = self.head_host  # head-node objects: reuse our head route
        key = (host, port)
        conn = self._data_conns.get(key)
        if conn is None or conn.closed:
            conn = await protocol.connect(host, port, name=f"data-{port}")
            self._data_conns[key] = conn
        if self._pull_sem is None:
            self._pull_sem = asyncio.Semaphore(int(os.environ.get(
                "RAY_TPU_MAX_CONCURRENT_PULLS", "4")))
        role = "driver" if self.is_driver else "worker"
        t0 = time.perf_counter()
        with _tracing.start_span(
                "object_pull",
                attributes={"ray_tpu.op": "object_pull",
                            "object_id": meta.object_id.hex()[:16],
                            "size": meta.size, "via": "direct"}):
            async with self._pull_sem:  # pull admission control
                local = await object_transfer.pull_object(
                    conn, meta, self.store, role=role)
        m = object_transfer._get_metrics()
        m["bytes"].inc(local.size, tags={"role": role})
        m["pulls"].inc(tags={"role": role})
        m["seconds"].observe(time.perf_counter() - t0, tags={"role": role})
        self._note_pulled(local)
        return local

    def _note_pulled(self, local: ObjectMeta) -> None:
        """LRU cache of pulled copies, bounded by RAY_TPU_PULL_CACHE_BYTES —
        evicted copies are unlinked (they are ours, unlike canonical
        objects, which only their owner node frees)."""
        evicted = []
        with self._pulled_lock:
            old = self._pulled.pop(local.object_id, None)
            if old is not None:
                self._pulled_bytes -= old.size
            self._pulled[local.object_id] = local
            self._pulled_bytes += local.size
            while (self._pulled_bytes > self._pull_cache_cap
                   and len(self._pulled) > 1):
                _, evict = self._pulled.popitem(last=False)
                self._pulled_bytes -= evict.size
                evicted.append(evict)
        for evict in evicted:
            try:
                self.store.free(evict)
            except Exception:
                pass

    def _read_value(self, meta: ObjectMeta) -> Any:
        if meta.kind == "device":
            return self._get_device_value(meta)
        return serialization.deserialize(self.read_serialized(meta))

    async def _read_value_async(self, meta: ObjectMeta) -> Any:
        if meta.kind == "device":
            oid = meta.object_id
            if self.device_store.contains(oid):
                return self.device_store.get(oid)
            from ray_tpu.core import device_transport

            snap = (await self._fetch_device_async(meta))["meta"]
            return device_transport.load_snapshot(
                await self.read_serialized_async(snap))
        return serialization.deserialize(
            await self.read_serialized_async(meta))

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        self._set_blocked(True)
        try:
            for ref in refs:
                meta = self.local_metas.get(ref.id)
                if meta is None:
                    remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
                    if self._resolve_pending_call(ref.id, timeout=remaining):
                        meta = self.local_metas[ref.id]
                    else:
                        # gossiped directory first: a sealed remote object
                        # we never held a meta for resolves from cache —
                        # the head only sees genuinely cold misses
                        meta = self.object_dir.lookup_meta(ref.id)
                        if meta is None:
                            meta = self.head_request(
                                "get_meta", object_id=ref.id.binary(),
                                timeout=remaining)
                    if meta is None:
                        raise GetTimeoutError(f"get timed out on {ref}")
                    self.local_metas[ref.id] = meta
                self._note_complete(ref.id)
                value = self._read_value(meta)
                if meta.error or isinstance(value, RayTpuError):
                    raise value
                out.append(value)
            return out
        finally:
            self._set_blocked(False)

    async def get_async(self, refs: Sequence[ObjectRef]) -> Any:
        out = []
        for ref in refs:
            meta = self.local_metas.get(ref.id)
            if meta is None:
                with self._pending_lock:
                    cfut = self._pending_calls.get(ref.id)
                if cfut is not None:
                    meta = (await asyncio.wrap_future(cfut))["meta"]
                    with self._pending_lock:
                        self._pending_calls.pop(ref.id, None)
                if cfut is None or meta is None:
                    # no pending call, or a lease failover resubmitted the
                    # task through the head: cached gossiped directory
                    # first, head get_meta as the cold-miss fallback
                    meta = self.object_dir.lookup_meta(ref.id)
                    if meta is None:
                        meta = await self.conn.request(
                            "get_meta", object_id=ref.id.binary(),
                            timeout=None)
                self.local_metas[ref.id] = meta
            self._note_complete(ref.id)
            value = await self._read_value_async(meta)
            if meta.error or isinstance(value, RayTpuError):
                raise value
            out.append(value)
        return out[0] if len(out) == 1 else out

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        num_returns = min(num_returns, len(refs))
        deadline = None if timeout is None else time.monotonic() + timeout
        ready_set: set = set()

        def check_local(r: ObjectRef) -> bool:
            if r.id in self.local_metas:
                return True
            with self._pending_lock:
                cfut = self._pending_calls.get(r.id)
            if cfut is None or not cfut.done():
                return False
            # a finished-but-failed call counts as ready (get surfaces it);
            # a lease failover (None meta) is NOT ready — the resubmitted
            # task resolves through the head directory instead
            try:
                if cfut.result()["meta"] is None:
                    with self._pending_lock:
                        self._pending_calls.pop(r.id, None)
                    return False
            except BaseException:
                pass
            return True

        # Event-driven (r3 VERDICT weak #6: the old loop polled the head
        # every 50 ms whenever actor calls were in flight): BOTH readiness
        # sources — in-flight actor-call futures and a head-side
        # wait_objects — wake one shared event. The head request runs in
        # bounded chunks so an abandoned server-side wait never lingers
        # unboundedly after we return.
        wake = threading.Event()
        hooked: set = set()
        head_errors = 0  # consecutive wait_objects failures

        def _hook(f):
            if id(f) not in hooked:
                hooked.add(id(f))
                f.add_done_callback(lambda _f: wake.set())

        while True:
            ready_set.update(r for r in refs if check_local(r))
            if len(ready_set) >= num_returns:
                break
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                break
            wake.clear()
            head_refs = []
            for r in refs:
                if r in ready_set:
                    continue
                with self._pending_lock:
                    cfut = self._pending_calls.get(r.id)
                if cfut is not None and not cfut.done():
                    _hook(cfut)
                else:
                    head_refs.append(r)
            if head_refs:
                step = 2.0 if remaining is None else min(2.0, remaining)
                hfut = asyncio.run_coroutine_threadsafe(
                    self.conn.request(
                        "wait_objects",
                        object_ids=[r.id.binary() for r in head_refs],
                        num_returns=num_returns - len(ready_set),
                        timeout=step), self.loop)
                hfut.add_done_callback(lambda _f: wake.set())
                wake.wait(step + 1.0)
                if hfut.done():
                    try:
                        ready_set.update(head_refs[i] for i in hfut.result())
                        head_errors = 0
                    except (protocol.ConnectionLost, protocol.RpcError,
                            OSError):
                        # transient during a head-restart window: stall
                        # until reconnected; persistent failure must
                        # RAISE, not spin at network rate forever
                        self._wait_connected()
                        head_errors += 1
                        if (head_errors >= 3
                                or self.conn is None or self.conn.closed):
                            raise
                    except Exception:
                        head_errors += 1
                        if head_errors >= 3:
                            raise
                else:
                    # an actor call woke us first: stop the head wait (the
                    # late reply lands on a cancelled future, a no-op)
                    hfut.cancel()
            else:
                wake.wait(remaining)
        ready = [r for r in refs if r in ready_set][:num_returns]
        ready_final = set(ready)
        return ready, [r for r in refs if r not in ready_final]

    def _is_pending_call(self, oid: ObjectID) -> bool:
        with self._pending_lock:
            cfut = self._pending_calls.get(oid)
        return cfut is not None and not cfut.done()

    def add_done_callback(self, ref: ObjectRef, cb) -> None:
        """Invoke cb() once the in-flight actor call behind `ref` completes
        (immediately if already resolved). Client-side routing bookkeeping
        (Serve router) relies on this."""
        with self._pending_lock:
            cfut = self._pending_calls.get(ref.id)
        if cfut is None:
            cb()
        else:
            cfut.add_done_callback(lambda f: cb())

    def free(self, refs: Sequence[ObjectRef]) -> None:
        for r in refs:
            with self._pending_lock:
                self._pending_calls.pop(r.id, None)
            meta = self.local_metas.pop(r.id, None)
            self._registered.discard(r.id)
            if meta is not None:
                self.store.release(meta)  # drop our mapping; head unlinks
            pulled = self._drop_pulled(r.id)
            if pulled is not None:
                try:
                    self.store.free(pulled)  # our cached copy: unlink it
                except Exception:
                    pass
        self._call(self.conn.request(
            "free_objects", object_ids=[r.id.binary() for r in refs]))

    def _set_blocked(self, value: bool) -> None:
        if self.is_driver or self.conn is None:
            return
        with self._blocked_lock:
            self._blocked_depth += 1 if value else -1
            depth = self._blocked_depth
        if (value and depth == 1) or (not value and depth == 0):
            # push, not round trip: the head's handler is fire-and-forget
            # (flip the flag, release the CPU, kick the scheduler) and
            # pushes keep same-connection FIFO ordering — waiting for the
            # ack bought nothing but two head round trips on EVERY
            # worker-side blocking get (warm paths must stay head-free)
            try:
                self.head_push("blocked", value=value)
            except Exception:
                pass

    # --------------------------------------------------------------- tasks
    _empty_payload_bytes: Optional[bytes] = None

    def build_args_payload(self, args: tuple, kwargs: dict):
        """Top-level ObjectRef args become deps (resolved at execution, like
        the reference); refs NESTED anywhere in the arguments are collected
        during pickling and pinned as deps too; everything ships
        serialized."""
        if not args and not kwargs:
            # zero-arg calls (the actor-call hot path) serialize to the
            # same constant bytes every time — skip the pickler entirely
            blob = CoreClient._empty_payload_bytes
            if blob is None:
                blob = CoreClient._empty_payload_bytes = \
                    serialization.serialize(((), {})).to_bytes()
            return {"inline": blob}, [], []
        deps = []
        seen = set()
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, ObjectRef):
                self.ensure_registered(a)
                deps.append(a.id.binary())
                seen.add(a.id)
        ser = serialization.serialize((args, kwargs))
        for oid in ser.contained:
            if oid not in seen:
                seen.add(oid)
                self.ensure_registered(ObjectRef(oid))
                deps.append(oid.binary())
        if ser.total_bytes <= ARGS_INLINE_LIMIT:
            return {"inline": ser.to_bytes()}, deps, ser.borrow_tokens
        meta = self.put_serialized(ser)
        return {"meta": meta}, deps, ser.borrow_tokens

    def release_borrows(self, tokens) -> None:
        """Sender-side release of borrow pins for a payload that will
        provably never be deserialized (terminally failed call). Idempotent
        against a racing receiver commit."""
        for oid, token in tokens or []:
            self.ref_tracker.borrow_commit(oid, token)

    # ------------------------------------------------------------- leases
    @staticmethod
    def _sched_tracing() -> bool:
        return _tracing.is_enabled()

    def _sched_event(self, phase: str, *, task_id=None, name=None, mode=None,
                     t0=None, t1=None, **detail) -> None:
        """Record one scheduling-phase event (flight recorder, driver
        side). Only called behind a _sched_tracing() check."""
        self.sched_events.append({
            "phase": phase,
            "task_id": task_id.hex() if hasattr(task_id, "hex") else task_id,
            "name": name, "mode": mode, "t0": t0, "t1": t1, **detail})

    @staticmethod
    def _lease_shape(fn_key: bytes, options: dict) -> tuple:
        res = options.get("resources") or {"CPU": 1}
        sel = options.get("label_selector")
        sel_key = (tuple(sorted(
            (k, tuple(v) if isinstance(v, (list, tuple, set)) else str(v))
            for k, v in sel.items())) if sel else None)
        return (fn_key, tuple(sorted(res.items())), sel_key)

    @staticmethod
    def _lease_eligible(options: dict, num_returns) -> bool:
        """Direct pushes cover the common shapes (label selectors
        included — grants are selector-checked by the granting scheduler);
        anything needing the head's placement machinery (PGs, streaming,
        runtime envs) takes the scheduled path."""
        return (num_returns == 1
                and options.get("num_returns") != "streaming"
                and not options.get("placement_group")
                and not options.get("runtime_env")
                and options.get("scheduling_strategy", "hybrid") == "hybrid")

    def _pick_lease_node(self, options: dict) -> Optional[dict]:
        """Feasible-node selection against the head-pushed cluster view:
        a node-daemon scheduler that can grant without the head."""
        if not _config.get("node_local_sched") or not self.cluster_view.entries:
            return None
        return self.cluster_view.select_node(
            options.get("resources") or {"CPU": 1},
            options.get("label_selector"))

    def _on_sched_conn_close(self, addr: Tuple[str, int]) -> None:
        """The granting daemon's scheduler connection died: every lease it
        granted is void THERE (the daemon reclaims on disconnect), so it
        must die HERE too — otherwise the daemon re-grants the worker to
        another client while we keep pushing to it (double lease)."""
        with self._lease_lock:
            for shape, lease in list(self._leases.items()):
                if lease.via == addr:
                    lease.dead = True
                    del self._leases[shape]

    async def _daemon_lease_grant(self, entry: dict, options: dict,
                                  referred=None) -> Optional[dict]:
        """Ask the chosen node daemon for a lease; None = spill to head
        (infeasible there, stale view, or the daemon is unreachable).
        A reply carrying "peers" is a peer referral — the daemon's pool
        missed but its cached view names peer daemons with warm idle
        workers; the caller completes the grant there. `referred` marks
        a request that IS such a completion (the peer grants warm-pool
        only, never cascading)."""
        addr = tuple(entry["sched_addr"])
        conn = None
        try:
            conn = self._sched_conns.get(addr)
            if conn is None or conn.closed:
                conn = await protocol.connect(addr[0], addr[1],
                                              name=f"sched-{addr[1]}")
                conn.on_close = lambda c, a=addr: self._on_sched_conn_close(a)
                self._sched_conns[addr] = conn
                if conn.closed:  # closed before on_close was attached
                    self._on_sched_conn_close(addr)
                    return None
            rep = await asyncio.wait_for(
                conn.request(
                    "lease_grant",
                    resources=options.get("resources") or {"CPU": 1},
                    label_selector=options.get("label_selector"),
                    venv_key=(options.get("runtime_env") or {}).get("pip_key"),
                    epoch=self.cluster_epoch or None,
                    referred=referred),
                timeout=10.0)
        except asyncio.TimeoutError:
            # the daemon may still complete this grant after we give up —
            # the only way to reconcile without request ids is to close
            # the scheduler session: the daemon returns everything it
            # granted on it, and _on_sched_conn_close voids our side
            if conn is not None:
                self._sched_conns.pop(addr, None)
                asyncio.ensure_future(conn.close())
            return None
        except (protocol.RpcError, OSError):
            return None
        if not rep or rep.get("spill"):
            if rep and rep.get("peers") and not referred:
                return rep  # peer referral: caller follows it
            self.lease_stats["spills"] += 1
            return None
        return rep

    def _head_suspect(self) -> bool:
        """True while the head cannot be counted on to answer: the
        connection is down/re-forming, or a recent head RPC timed out
        with the socket still "open" (a SIGSTOPped head keeps TCP alive
        — liveness is judged by answers, not by the connection)."""
        return (not self._connected.is_set() or self.conn is None
                or self.conn.closed
                or time.monotonic() < self._head_suspect_until)

    def _only_pool_capacity(self, options: dict) -> bool:
        """True when the cached view says this shape can ONLY be served
        by warm daemon pools: no feasible node has ledger-free capacity.
        Pushing such a task onto the head queue would starve it until a
        pool release returns capacity (the pools hold the whole ledger),
        so the local dispatch queue + lease path is strictly better —
        the head could not have parallelized it anyway."""
        if not _config.get("node_local_sched") \
                or not self.cluster_view.entries:
            return False
        from ray_tpu.core.resource_view import fits, matches_labels

        res = options.get("resources") or {"CPU": 1}
        sel = options.get("label_selector")
        saw_pool = False
        for e in self.cluster_view.entries.values():
            if not matches_labels(e.get("labels") or {}, sel):
                continue
            if not fits(e.get("total") or {}, res):
                continue
            if fits(e.get("free") or {}, res):
                return False  # the head can dispatch this somewhere
            if e.get("idle_workers") and e.get("sched_addr"):
                saw_pool = True
        return saw_pool

    def _maybe_acquire_lease(self, shape: tuple, options: dict) -> None:
        """Fire-and-forget lease acquisition — never blocks a submit.

        Warm path: the cached cluster view names a feasible node daemon
        and the grant is node-local (zero head involvement). A daemon
        whose pool misses may answer with a peer REFERRAL — peer daemons
        whose gossiped pools show warm idle workers; the grant completes
        there (mode "peer", epoch-fenced by the peer) with zero head
        RPCs. Spillback to the head's acquire_lease only on label miss,
        infeasibility, or when the mesh has no warm capacity — and not
        at all while the head is suspect (parked cold tasks retry the
        mesh instead)."""
        with self._lease_lock:
            if shape in self._leases or shape in self._lease_acquiring:
                return
            self._lease_acquiring.add(shape)

        async def _acquire():
            traced = self._sched_tracing()
            t0 = time.time() if traced else 0.0
            mode = None
            acquired = False
            try:
                rep, via = None, None
                entry = self._pick_lease_node(options)
                if entry is not None:
                    rep = await self._daemon_lease_grant(entry, options)
                    if rep is not None and rep.get("peers"):
                        # peer referral: the chosen daemon's pool missed,
                        # but its cached view names warm peers — complete
                        # the grant there (one hop, no cascading)
                        referral, rep = rep, None
                        for p in referral["peers"]:
                            prep = await self._daemon_lease_grant(
                                {"sched_addr": p["sched_addr"]}, options,
                                referred=entry["node_id"])
                            if prep is not None and not prep.get("peers"):
                                rep = prep
                                via = tuple(p["sched_addr"])
                                self.lease_stats["daemon_grants"] += 1
                                self.lease_stats["peer_grants"] += 1
                                mode = "peer"
                                break
                    elif rep is not None:
                        via = tuple(entry["sched_addr"])
                        self.lease_stats["daemon_grants"] += 1
                        mode = "local"
                if rep is None:
                    # spillback: a daemon refused (stale view/labels/full)
                    # or no feasible view node existed — the head grants,
                    # unless it is suspect (closed, reconnecting, or
                    # recently unresponsive): then fail the attempt and
                    # let the parked-task retry loop re-try the mesh
                    mode = "spillback" if entry is not None else "head"
                    if not self._head_suspect():
                        try:
                            hfut = self.conn.request_future(
                                "acquire_lease", options=options)
                        except Exception:
                            hfut = None
                        try:
                            if hfut is not None:
                                rep = await asyncio.wait_for(
                                    asyncio.shield(hfut), timeout=15.0)
                        except (protocol.RpcError, OSError):
                            rep = None
                        except asyncio.TimeoutError:
                            # the socket is open but the head is not
                            # answering (paused/hung): reroute cold tasks
                            # through the peer mesh for a while. A LATE
                            # grant is handed straight back (the head
                            # debited a worker for a requester that gave
                            # up — releasing it is the leak fence).
                            rep = None
                            self._head_suspect_until = \
                                time.monotonic() + 10.0

                            def _late(f):
                                if f.cancelled() or f.exception():
                                    return
                                r = f.result()
                                if r:
                                    try:
                                        self.conn.push(
                                            "release_lease",
                                            worker_id=r["worker_id"])
                                    except Exception:
                                        pass

                            hfut.add_done_callback(_late)
                    if rep is not None:
                        self.lease_stats["head_grants"] += 1
                if rep is not None:
                    lease = _Lease(WorkerID(rep["worker_id"]),
                                   tuple(rep["addr"]), via=via)
                    if traced:
                        lease.acquire_mode = mode
                        with _tracing.start_span(
                                "lease_acquire",
                                attributes={"ray_tpu.op": "lease_acquire",
                                            "mode": mode}) as sp:
                            if sp is not None:
                                sp.start_ts = t0
                        self._sched_event(
                            "lease-acquire", mode=mode, t0=t0,
                            t1=time.time(),
                            worker=lease.worker_id.hex()[:12])
                    with self._lease_lock:
                        self._leases[shape] = lease
                    acquired = True
                    self._start_lease_reaper()
                elif traced:
                    self._sched_event("lease-acquire", mode=mode or "none",
                                      t0=t0, t1=time.time(), failed=True)
            finally:
                with self._lease_lock:
                    self._lease_acquiring.discard(shape)
            self._settle_parked(shape, options, acquired)

        asyncio.run_coroutine_threadsafe(_acquire(), self.loop)

    def _park_for_lease(self, shape: tuple, options: dict, spec: dict,
                        return_id: ObjectID):
        """Park a cold-path task in the local per-shape dispatch queue
        while the head is suspect: it dispatches through the daemon/peer
        lease once one lands instead of riding the head queue. Returns
        True (parked), False (queue full — caller falls back to the head
        path), or "retry" (a lease landed concurrently — caller submits
        through it)."""
        cap = int(_config.get("lease_park_max"))
        cfut: _cf.Future = _cf.Future()
        with self._lease_lock:
            lease = self._leases.get(shape)
            if lease is not None and not lease.dead:
                return "retry"
            q = self._lease_parked.setdefault(shape, deque())
            if len(q) >= cap:
                return False
            q.append((spec, cfut))
            self._lease_parked_ts.setdefault(shape, time.monotonic())
        with self._pending_lock:
            self._pending_calls[return_id] = cfut
        pins = [ObjectRef(ObjectID(b)) for b in spec["deps"]]

        def _on_done(f, _pins=pins):
            _pins.clear()
            try:
                meta = f.result()["meta"]
            except BaseException:
                return
            if meta is not None:
                self.local_metas[meta.object_id] = meta

        cfut.add_done_callback(_on_done)
        self._maybe_acquire_lease(shape, options)
        return True

    def _settle_parked(self, shape: tuple, options: dict,
                       acquired: bool) -> None:
        """After a lease acquisition attempt: drain this shape's parked
        tasks through the fresh lease, or — with no lease — re-try the
        mesh shortly while the head stays suspect, falling back to the
        head queue the moment it is trusted again. Runs on the loop."""
        items = []
        lease = None
        with self._lease_lock:
            q = self._lease_parked.get(shape)
            if not q:
                self._lease_parked.pop(shape, None)
                self._lease_parked_ts.pop(shape, None)
                return
            if acquired:
                lease = self._leases.get(shape)
                if lease is not None and not lease.dead:
                    items = list(q)
                    q.clear()
                    self._lease_parked.pop(shape, None)
                    self._lease_parked_ts.pop(shape, None)
                    lease.inflight += len(items)
                    lease.last_used = time.monotonic()
                else:
                    lease = None
        if lease is not None:
            for spec, cfut in items:
                task = asyncio.ensure_future(
                    self._lease_exec_async(lease, spec))
                # STRONG reference until done: asyncio tracks tasks
                # weakly, and a drained exec task whose only ref was this
                # loop variable was observed garbage-collected mid-flight
                # (its coroutine turned up "already awaited")
                self._parked_exec_tasks.add(task)
                task.add_done_callback(self._parked_exec_tasks.discard)

                def _chain(t, _cfut=cfut):
                    if _cfut.cancelled():
                        return
                    if t.cancelled():
                        _cfut.cancel()
                    elif t.exception() is not None:
                        _cfut.set_exception(t.exception())
                    else:
                        _cfut.set_result(t.result())

                task.add_done_callback(_chain)
            return
        parked_age = time.monotonic() - self._lease_parked_ts.get(
            shape, time.monotonic())
        if self._head_suspect() or (self._only_pool_capacity(options)
                                    and parked_age < 2.0):
            # no lease and no usable head queue (unreachable, or the
            # pools hold the whole ledger): keep the tasks parked and
            # re-try the mesh — the daemon pools / referral candidates
            # are re-read from the cached view each attempt, and a pool
            # release flips the view back to head-drainable. Pool-held
            # parking is age-bounded: a shape the pools can't actually
            # serve (wrong size/venv) must reach the HEAD queue, where
            # the pool_trim reclaim loop can free capacity for it —
            # parked tasks are invisible to that loop.
            self.loop.call_later(
                0.5, lambda: self._maybe_acquire_lease(shape, options))
            return
        # head is trusted again: the parked tasks take the classic head
        # path (push + at-least-once inflight tracking); their parked
        # futures resolve to the None-meta marker so get() falls through
        # to the head directory, exactly like a lease failover
        with self._lease_lock:
            q = self._lease_parked.pop(shape, None)
            self._lease_parked_ts.pop(shape, None)
            items = list(q) if q else []
        for spec, cfut in items:
            with self._inflight_lock:
                self._inflight_specs[ObjectID(spec["return_ids"][0])] = spec
                while len(self._inflight_specs) > 4096:
                    self._inflight_specs.popitem(last=False)
            try:
                self.conn.push("submit_task", spec=spec)
            except Exception:
                pass
            if not cfut.done():
                cfut.set_result({"meta": None})

    def _release_lease_now(self, lease: "_Lease") -> None:
        """Hand a lease back to whoever granted it (loop thread only)."""
        try:
            if lease.via is not None:
                conn = self._sched_conns.get(lease.via)
                if conn is not None and not conn.closed:
                    conn.push("lease_return",
                              worker_id=lease.worker_id.binary())
                # sched conn gone: the daemon reclaimed on disconnect
            else:
                self.conn.push("release_lease",
                               worker_id=lease.worker_id.binary())
        except Exception:
            pass

    def _start_lease_reaper(self) -> None:
        if self._lease_reaper_started:
            return
        self._lease_reaper_started = True

        def _reap():
            now = time.monotonic()
            dead = []
            with self._lease_lock:
                for shape, lease in list(self._leases.items()):
                    if (lease.dead or (lease.inflight == 0 and
                                       now - lease.last_used > self._lease_idle_s)):
                        dead.append((shape, lease))
                        del self._leases[shape]
            for shape, lease in dead:
                self._release_lease_now(lease)
            self.loop.call_later(max(self._lease_idle_s / 2, 0.25), _reap)

        self.loop.call_soon_threadsafe(
            lambda: self.loop.call_later(self._lease_idle_s, _reap))

    async def _on_lease_revoke_msg(self, worker_id):
        self._on_lease_revoke(worker_id)
        return True

    def _on_lease_revoke(self, worker_id: bytes) -> None:
        """Head wants the worker back. Stop submitting NOW, but only
        hand it back once in-flight pushes drain — releasing a busy
        worker would let the head queue new tasks behind ours, and if one
        of ours blocks on an object THOSE tasks produce, that's deadlock."""
        wid = WorkerID(worker_id)
        release_now = []
        with self._lease_lock:
            for shape, lease in list(self._leases.items()):
                if lease.worker_id == wid:
                    del self._leases[shape]
                    if lease.inflight == 0:
                        release_now.append(lease)
                    else:
                        lease.dead = True  # drain in _lease_exec_async
                        self._draining.append(lease)
        for lease in release_now:
            self._release_lease_now(lease)

    async def _lease_exec_async(self, lease: "_Lease", spec: dict):
        """Push one task to the leased worker; on a dead worker/lease the
        task is resubmitted through the head (same return ids — the head
        path seals them) and the pending-call resolves to a None meta so
        get() falls through to the head directory."""
        try:
            try:
                conn = self._direct.get(lease.addr)
                if conn is None or conn.closed:
                    conn = await protocol.connect(
                        *lease.addr, name=f"lease-{lease.addr[1]}")
                    self._direct[lease.addr] = conn
            except (ConnectionRefusedError, OSError):
                # connect-phase failure: the task was provably never sent,
                # so resubmitting through the head is safe for ANY retry
                # policy (no duplicate-execution risk)
                lease.dead = True
                spec["failover"] = True  # head skips the dup holder add
                self._track_failover(spec)
                self.conn.push("submit_task", spec=spec)
                return {"meta": None}
            if self._sched_tracing():
                t_dispatch = time.time()
                rep = await conn.request("lease_exec", spec=spec)
                t_reply = time.time()
                prof = rep.get("prof")
                opts = spec.get("options", {})
                tid = spec["task_id"]
                if prof:
                    # all phase timestamps stay in the DRIVER's clock: the
                    # worker reports only its run DURATION, anchored here
                    # to the reply arrival (cross-host wall clocks skew by
                    # NTP offsets, which would render out-of-order phases)
                    run_s = max(prof["end"] - prof["start"], 0.0)
                    t_run = max(t_reply - run_s, t_dispatch)
                    self._sched_event(
                        "dispatch", task_id=tid,
                        name=opts.get("name"), mode="lease",
                        t0=t_dispatch, t1=t_run,
                        worker=lease.worker_id.hex()[:12])
                    self._sched_event(
                        "run", task_id=tid, name=opts.get("name"),
                        mode="lease", t0=t_run, t1=t_reply,
                        worker=lease.worker_id.hex()[:12])
                else:
                    self._sched_event(
                        "dispatch", task_id=tid, name=opts.get("name"),
                        mode="lease", t0=t_dispatch, t1=t_reply,
                        worker=lease.worker_id.hex()[:12])
            else:
                rep = await conn.request("lease_exec", spec=spec)
            if rep.get("retired"):
                lease.dead = True
            return rep
        except (protocol.ConnectionLost, protocol.RpcError, OSError):
            lease.dead = True
            # The request was in flight: the worker may have executed the
            # task and only the reply was lost — resubmitting through the
            # head can run it twice, so the failover is gated on the
            # task's retry policy (reference NormalTaskSubmitter only
            # re-queues retryable tasks on worker death). Non-retryable
            # tasks surface a worker-died error.
            if spec.get("options", {}).get("max_retries", 3):
                spec["failover"] = True  # head skips the duplicate holder add
                self._track_failover(spec)
                self.conn.push("submit_task", spec=spec)
                return {"meta": None}
            rid = ObjectID(spec["return_ids"][0])
            # terminal failure: the head never sees this spec, so the
            # client must drop the borrow pins itself (idempotent vs a
            # racing worker commit)
            self.release_borrows(
                [(ObjectID(b), t) for b, t in spec.get("borrows", [])])
            err = WorkerCrashedError(
                f"leased worker {lease.worker_id.hex()[:12]} died executing "
                f"a task with max_retries=0; the task may or may not have "
                f"run")
            meta = self.store_result(rid, err, register=True, is_error=True)
            return {"meta": meta}
        finally:
            with self._lease_lock:
                # _try_lease_submit increments under this lock from user
                # threads; an unlocked decrement here can lose an update and
                # strand a positive count, leaking the leased worker
                lease.inflight -= 1
                lease.last_used = time.monotonic()
                release = (lease.dead and lease.inflight == 0
                           and lease in self._draining)
                if release:
                    # revoked mid-burst: last in-flight push done
                    self._draining.remove(lease)
            if release:
                self._release_lease_now(lease)

    def _track_failover(self, spec: dict) -> None:
        """Record a lease-failover resubmission for head-restart replay:
        the push may land in a dead head socket's buffer (the worker died
        WITH the head), and lease submits are not otherwise tracked — an
        untracked failover would lose the task forever."""
        with self._inflight_lock:
            self._inflight_specs[ObjectID(spec["return_ids"][0])] = spec
            while len(self._inflight_specs) > 4096:
                self._inflight_specs.popitem(last=False)

    def _try_lease_submit(self, fn_key, payload, deps, tokens, options,
                          task_id, return_id: ObjectID) -> bool:
        shape = self._lease_shape(fn_key, options)
        with self._lease_lock:
            lease = self._leases.get(shape)
            if lease is None or lease.dead:
                lease = None
            else:
                lease.inflight += 1
                lease.last_used = time.monotonic()
        if lease is None:
            self._maybe_acquire_lease(shape, options)
            return False
        spec = {"task_id": task_id, "fn_key": fn_key, "args": payload,
                "deps": deps, "return_ids": [return_id.binary()],
                "borrows": [(o.binary(), t) for o, t in tokens],
                "options": options}
        dep_metas = self._dep_metas(deps)
        if dep_metas:
            # ship the deps' metas with the push: the executing worker
            # resolves each block straight through its node PullManager
            # instead of round-tripping get_meta per dependency — the
            # warm inter-stage handoff of a data pipeline makes zero
            # head RPCs
            spec["dep_metas"] = dep_metas
        if options.get("lineage"):
            # out-of-band lineage registration: lease-path tasks never
            # reach the head's submit_task, so a data-stage task opts its
            # spec into the lineage ledger with one fire-and-forget push
            # (reconstruction re-runs it through the normal queue). The
            # recorded spec drops the borrow tokens (the live dispatch
            # below owns the handoff; a re-run must not re-commit them)
            # and the shipped dep metas (the head re-attaches FRESH ones
            # at reconstruction dispatch — recording these would pin
            # stale locations in the ledger).
            self.head_push(
                "record_lineage",
                spec={k: v for k, v in spec.items()
                      if k != "dep_metas"} | {"borrows": []})
        if self._head_suspect():
            # headless dispatch: the granted worker may never have run
            # this function, and its KV fetch would stall on the dead/
            # paused head — ship the definition with the spec
            blob = self.fn_manager.blob(fn_key)
            if blob is not None:
                spec["fn_blob"] = blob
        # caller-held pins keep deps alive until completion (the head is
        # not involved, so it cannot pin them — same as direct actor
        # calls); deps already includes the big-args payload object
        pins = [ObjectRef(ObjectID(b)) for b in deps]
        cfut = asyncio.run_coroutine_threadsafe(
            self._lease_exec_async(lease, spec), self.loop)
        with self._pending_lock:
            self._pending_calls[return_id] = cfut

        def _on_done(f, _pins=pins):
            _pins.clear()
            try:
                meta = f.result()["meta"]
            except BaseException:
                return
            if meta is not None:
                self.local_metas[meta.object_id] = meta

        cfut.add_done_callback(_on_done)
        return True

    def submit_task(self, fn_key: bytes, args: tuple, kwargs: dict,
                    options: dict, num_returns: int = 1) -> List[ObjectRef]:
        traced = self._sched_tracing()
        t_submit = time.time() if traced else 0.0
        payload, deps, tokens = self.build_args_payload(args, kwargs)
        if "meta" in payload:
            # the args payload object is itself pinned as a dep: the head
            # releases it at task completion, so big-args payloads stop
            # leaking and can't be evicted while the task is queued
            deps = deps + [payload["meta"].object_id.binary()]
        task_id = TaskID.generate()
        return_ids = [ObjectID.generate() for _ in range(num_returns)]
        if self._lease_eligible(options, num_returns):
            if self._try_lease_submit(fn_key, payload, deps, tokens,
                                      options, task_id, return_ids[0]):
                if traced:
                    self._sched_event("submit", task_id=task_id,
                                      name=options.get("name"), mode="lease",
                                      t0=t_submit, t1=time.time())
                return [ObjectRef(return_ids[0])]
            attempts = 0
            while (self._head_suspect()
                   or self._only_pool_capacity(options)) and attempts < 4:
                attempts += 1
                # cold path without a usable head queue: either the head
                # is unreachable (outage/pause), or every feasible node's
                # capacity lives in daemon pools (head-queueing would
                # starve until a pool release). Park the task in the
                # local per-shape dispatch queue; it drains through the
                # daemon/peer-granted lease once the acquisition lands
                spec = {"task_id": task_id, "fn_key": fn_key,
                        "args": payload, "deps": deps,
                        "return_ids": [return_ids[0].binary()],
                        "borrows": [(o.binary(), t) for o, t in tokens],
                        "options": options}
                blob = self.fn_manager.blob(fn_key)
                if blob is not None:
                    # definitions ride parked specs: the worker that
                    # eventually executes must not stall on a head KV
                    # fetch the outage makes impossible
                    spec["fn_blob"] = blob
                parked = self._park_for_lease(
                    self._lease_shape(fn_key, options), options, spec,
                    return_ids[0])
                if parked is True:
                    if traced:
                        self._sched_event(
                            "submit", task_id=task_id,
                            name=options.get("name"), mode="parked",
                            t0=t_submit, t1=time.time())
                    return [ObjectRef(return_ids[0])]
                if parked == "retry":
                    if self._try_lease_submit(fn_key, payload, deps,
                                              tokens, options, task_id,
                                              return_ids[0]):
                        return [ObjectRef(return_ids[0])]
                    continue
                break  # queue full: classic head path below
        spec = {"task_id": task_id, "fn_key": fn_key, "args": payload,
                "deps": deps, "return_ids": [o.binary() for o in return_ids],
                # head releases these if the task dies before any worker
                # deserializes the args (borrow pins must not leak)
                "borrows": [(o.binary(), t) for o, t in tokens],
                "options": options}
        # fire-and-forget: return ids are client-generated, so no reply is
        # needed — a blocking round trip here caps pipelined submission at
        # ~500 tasks/s; a push lets the socket batch thousands/s (head-side
        # submission failures seal error objects on the return ids)
        self._wait_connected()  # ride out a head restart, don't drop tasks
        if self.conn.closed:
            raise protocol.ConnectionLost("head connection closed")
        with self._inflight_lock:
            # retained until the result meta is observed; replayed to a
            # restarted head (which lost its queue AND any push that died
            # in the old socket's buffer)
            self._inflight_specs[return_ids[0]] = spec
            while len(self._inflight_specs) > 4096:
                self._inflight_specs.popitem(last=False)
        # bind the CURRENT conn: a reconnect between here and the loop
        # callback must not push into the dead connection object
        self._loop_call_soon(
            functools.partial(self.conn.push, "submit_task", spec=spec))
        if traced:
            self._sched_event("submit", task_id=task_id,
                              name=options.get("name"), mode="head",
                              t0=t_submit, t1=time.time())
        return [ObjectRef(o) for o in return_ids]

    # -------------------------------------------------------------- actors
    def create_actor(self, cls_key: bytes, args: tuple, kwargs: dict,
                     options: dict, methods: dict) -> ActorID:
        payload, deps, tokens = self.build_args_payload(args, kwargs)
        actor_id = ActorID.generate()
        spec = {"actor_id": actor_id.binary(), "cls_key": cls_key,
                "args": payload, "deps": deps, "options": options,
                "borrows": [(o.binary(), t) for o, t in tokens],
                "methods": methods}
        self._wait_connected()
        reply = self._call(self.conn.request("create_actor", spec=spec))
        return ActorID(reply["actor_id"])

    async def _actor_conn(self, actor_id: ActorID) -> protocol.Connection:
        addr = self._actor_addr_cache.get(actor_id)
        if addr is None:
            reply = await self.conn.request("get_actor_address",
                                            actor_id=actor_id.binary())
            if reply["state"] == "DEAD":
                raise ActorDiedError(reply.get("death_cause") or "actor died")
            addr = tuple(reply["address"])
            self._actor_addr_cache[actor_id] = addr
        conn = self._direct.get(addr)
        if conn is None or conn.closed:
            conn = await protocol.connect(addr[0], addr[1],
                                          name=f"actor-{addr[1]}")
            self._direct[addr] = conn
        return conn

    def _fast_actor_send(self, actor_id: ActorID, method: str, payload,
                         deps, return_id: bytes, group, cfut,
                         trace=None) -> None:
        """Loop-side send without coroutine overhead. Falls back to the
        retrying coroutine path on a cold/poisoned connection, and resends
        through it when a reply is lost to a dropped connection (the same
        at-least-once semantics the coroutine path has always had)."""
        if self._fallbacks_pending.get(actor_id):
            # a fallback send for this actor is still alive (created,
            # queued on, or inside its ordered section): overtaking it
            # would deliver calls out of program order — join the same
            # FIFO instead. The counter (not the lock state) is the
            # guard: a just-created fallback task holds no lock yet.
            self._fallback_actor_send(actor_id, method, payload, deps,
                                      return_id, group, cfut, trace)
            return
        addr = self._actor_addr_cache.get(actor_id)
        conn = self._direct.get(addr) if addr is not None else None
        if conn is None or conn.closed:
            self._fallback_actor_send(actor_id, method, payload, deps,
                                      return_id, group, cfut, trace)
            return
        try:
            kw = {"actor_id": actor_id.binary(), "method": method,
                  "args": payload, "deps": deps, "return_id": return_id,
                  "group": group}
            if trace is not None:
                kw["trace"] = trace
            fut = conn.request_future("actor_call", **kw)
        except Exception:
            self._fallback_actor_send(actor_id, method, payload, deps,
                                      return_id, group, cfut, trace)
            return

        def _done(f):
            exc = f.exception() if not f.cancelled() else None
            if isinstance(exc, (protocol.ConnectionLost,
                                ConnectionRefusedError, OSError)):
                # reply lost mid-flight: re-resolve + resend (actor may
                # have restarted elsewhere)
                self._actor_addr_cache.pop(actor_id, None)
                self._fallback_actor_send(actor_id, method, payload, deps,
                                          return_id, group, cfut, trace)
                return
            if cfut.cancelled():
                return
            if exc is not None:
                cfut.set_exception(exc)
            elif f.cancelled():
                cfut.cancel()
            else:
                cfut.set_result(f.result())

        fut.add_done_callback(_done)

    def _fallback_actor_send(self, actor_id, method, payload, deps,
                             return_id, group, cfut, trace=None) -> None:
        """Cold/failed path: run the full retrying coroutine, chain its
        outcome into the caller's concurrent future. The pending counter
        covers the task's whole lifetime (creation through completion) so
        the fast path can never slip between a fallback's creation and
        its lock acquisition (loop-confined, no lock needed)."""
        self._fallbacks_pending[actor_id] = \
            self._fallbacks_pending.get(actor_id, 0) + 1
        task = asyncio.ensure_future(self._call_actor_async(
            actor_id, method, payload, deps, return_id, group=group,
            trace=trace))

        def _chain(t):
            n = self._fallbacks_pending.get(actor_id, 1) - 1
            if n <= 0:
                self._fallbacks_pending.pop(actor_id, None)
            else:
                self._fallbacks_pending[actor_id] = n
            if cfut.cancelled():
                return
            if t.cancelled():
                cfut.cancel()
            elif t.exception() is not None:
                cfut.set_exception(t.exception())
            else:
                cfut.set_result(t.result())

        task.add_done_callback(_chain)

    async def _call_actor_async(self, actor_id: ActorID, method: str,
                                payload, deps, return_id: bytes,
                                retries: int = 30, group=None, trace=None):
        order_lock = self._actor_order_locks.setdefault(actor_id, asyncio.Lock())
        last_err = None
        for _ in range(retries):
            try:
                # hold the per-actor lock only across connect+send so calls
                # from this process reach the actor in program order while
                # replies stay pipelined (ActorTaskSubmitter seqno semantics,
                # reference task_submission/actor_task_submitter.h:70)
                async with order_lock:
                    conn = await self._actor_conn(actor_id)
                    kw = {"actor_id": actor_id.binary(), "method": method,
                          "args": payload, "deps": deps,
                          "return_id": return_id, "group": group}
                    if trace is not None:
                        kw["trace"] = trace
                    fut = conn.request_future("actor_call", **kw)
                return await fut
            except (protocol.ConnectionLost, ConnectionRefusedError, OSError) as e:
                last_err = e
                self._actor_addr_cache.pop(actor_id, None)
                await asyncio.sleep(0.1)
        raise ActorDiedError(f"actor unreachable: {last_err}")

    def call_actor(self, actor_id: ActorID, method: str, args: tuple,
                   kwargs: dict, group=None) -> ObjectRef:
        """Submit an actor call; returns immediately with the result ref.

        The reply (result meta) resolves in the background; `get`/`wait` on
        the ref join it via `_pending_calls`."""
        payload, deps, tokens = self.build_args_payload(args, kwargs)
        return_id = ObjectID.generate()
        # actor calls bypass the head, so the head can't pin their args:
        # hold ObjectRefs (our own local refcounts) for the deps and the
        # payload object until the reply lands
        pins = [ObjectRef(ObjectID(b)) for b in deps]
        if "meta" in payload:
            pins.append(ObjectRef(payload["meta"].object_id))
        # fast path: one plain loop callback per call. Creating a Task per
        # call (run_coroutine_threadsafe) was the single largest cost of
        # pipelined actor calls (~1/3 of the 264 us/call the r3 VERDICT
        # flagged); the coroutine machinery is only needed for connect /
        # retry, which _fast_actor_send falls back to.
        cfut = _cf.Future()
        # W3C context captured on the CALLING thread (the loop callback
        # below runs without this thread's contextvars): the receiving
        # actor opens a child execution span, so serve proxy -> replica ->
        # nested calls stay one trace (None when tracing is off)
        trace = _tracing.inject_context()
        self._loop_call_soon(
            self._fast_actor_send, actor_id, method, payload, deps,
            return_id.binary(), group, cfut, trace)
        with self._pending_lock:
            self._pending_calls[return_id] = cfut

        def _on_done(f, _pins=pins, _tokens=tokens):
            _pins.clear()  # release arg pins NOW — the future object (and
            # this callback's defaults) may outlive the call in
            # _pending_calls, so dropping the binding wouldn't free them
            try:
                meta = f.result()["meta"]
            except BaseException:
                # terminal failure: the payload will never be deserialized
                # anywhere — self-release its borrow pins (idempotent if an
                # earlier retry did deliver it before the actor died)
                self.release_borrows(_tokens)
                return  # surfaced when the ref is consumed
            self.local_metas[meta.object_id] = meta

        cfut.add_done_callback(_on_done)
        return ObjectRef(return_id)

    def _resolve_pending_call(self, oid: ObjectID,
                              timeout: Optional[float] = None) -> bool:
        """Join an in-flight actor call for `oid`. True if it was pending."""
        with self._pending_lock:
            cfut = self._pending_calls.get(oid)
        if cfut is None:
            return False
        try:
            meta = cfut.result(timeout=timeout)["meta"]
            if meta is None:
                # lease failover: the task was resubmitted through the
                # head — resolve via the head directory instead
                return False
            self.local_metas[meta.object_id] = meta
        except TimeoutError:
            raise GetTimeoutError(f"actor call {oid} not finished in time")
        finally:
            if cfut.done():
                with self._pending_lock:
                    self._pending_calls.pop(oid, None)
        return True

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self._wait_connected()
        self._call(self.conn.request("kill_actor", actor_id=actor_id.binary(),
                                     no_restart=no_restart))

    # ------------------------------------------------------------------ kv
    # via head_request: KV ops are idempotent and ride a head restart
    # (retry on the re-established connection) — a worker loading a
    # function blob mid-outage must stall briefly, not fail its task
    def kv_put(self, ns: str, key: bytes, value: bytes, overwrite=True) -> bool:
        return self.head_request("kv_put", ns=ns, key=key, value=value,
                                 overwrite=overwrite)

    def kv_get(self, ns: str, key: bytes) -> Optional[bytes]:
        return self.head_request("kv_get", ns=ns, key=key)

    def kv_del(self, ns: str, key: bytes) -> bool:
        return self.head_request("kv_del", ns=ns, key=key)

    def kv_keys(self, ns: str, prefix: bytes) -> list:
        return self.head_request("kv_keys", ns=ns, prefix=prefix)
