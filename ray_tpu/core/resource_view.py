"""Versioned cluster resource view — the `ray_syncer` equivalent.

Reference: `src/ray/common/ray_syncer/ray_syncer.h` — each node owns a
monotonically versioned snapshot of its local resource state and gossips
deltas; consumers keep a compacted cluster view and ignore stale versions.

Three parties share this module:

- the **head** (`gcs.py`) builds the authoritative compacted view: its own
  ledger supplies `free`/`total` per node, node-daemon deltas supply
  `idle_workers` (the daemon's warm lease pool) and `sched_addr`.  The view
  is broadcast (debounced) to node daemons and drivers.
- **node daemons** (`node_main.py`) gossip `{version, idle_workers,
  labels}` deltas to the head on change and cache the pushed cluster view.
- **clients** (`client.py`) cache the pushed view and use
  `select_node` for feasible-node lease routing: a lease request goes
  straight to the chosen node's daemon scheduler, touching the head only
  on infeasibility, version conflict (grant refused), or label miss.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def matches_labels(labels: Dict[str, str],
                   selector: Optional[dict]) -> bool:
    """Shared label-selector semantics (NodeInfo and view entries must
    agree, or client-side routing and head-side granting diverge)."""
    if not selector:
        return True
    for k, v in selector.items():
        have = labels.get(k)
        if isinstance(v, (list, tuple, set)):   # "in" semantics
            if have not in v:
                return False
        elif have != str(v):
            return False
    return True


def fits(free: Dict[str, float], resources: Dict[str, float]) -> bool:
    return all(free.get(r, 0) >= amt - 1e-9 for r, amt in resources.items())


def make_entry(node_id_hex: str, *, version: int, free: Dict[str, float],
               total: Dict[str, float], labels: Dict[str, str],
               idle_workers: int = 0, sched_addr=None,
               data_addr=None, is_head: bool = False) -> dict:
    # data_addr: the node's object data server — consumers of the gossiped
    # object directory resolve pull sources from the cached view instead
    # of asking the head (host None = "the head's host", substituted by
    # each consumer from its own route to the head)
    return {"node_id": node_id_hex, "version": version, "free": dict(free),
            "total": dict(total), "labels": dict(labels),
            "idle_workers": idle_workers, "sched_addr": sched_addr,
            "data_addr": data_addr, "is_head": is_head}


class ClusterView:
    """Compacted per-node view entries + a view-level version.

    `update` ignores regressions of a node's own version (a reconnecting
    daemon's stale delta must not rewind the view); every accepted change
    bumps the view version so consumers can detect staleness cheaply."""

    def __init__(self):
        self.entries: Dict[str, dict] = {}   # node_id hex -> entry
        self.version = 0
        # epoch fencing: the cluster epoch stamped into head-built
        # snapshots (0 until the first adopt); consumers tag lease/pool
        # traffic with it so stale-epoch ops are rejected after a head
        # restart instead of silently mutating the rebuilt ledger
        self.epoch = 0
        # flight-recorder gossip health: when this consumer last adopted a
        # head-pushed snapshot (monotonic; 0 = never) — `staleness_s()` is
        # the age of the cached view, gossiped back to the head as
        # per-node `gossip_lag_s`
        self.adopted_ts: float = 0.0
        # serve-replica live-load rows piggybacked on head snapshots
        # (changed-only, so absence in a snapshot means "unchanged");
        # None until the first row batch arrives — consumers
        # (serve/live_signals.py) distinguish "no serve plane yet" from
        # "idle serve plane" and fall back to the state API for the former
        self.serve_loads: Optional[list] = None

    def staleness_s(self) -> float:
        """Seconds since the last adopted snapshot; -1 = never adopted."""
        import time

        if not self.adopted_ts:
            return -1.0
        return time.monotonic() - self.adopted_ts

    def update(self, entry: dict) -> bool:
        cur = self.entries.get(entry["node_id"])
        if cur is not None and entry["version"] < cur["version"]:
            return False
        if cur == entry:
            return False
        self.entries[entry["node_id"]] = entry
        self.version += 1
        return True

    def remove(self, node_id_hex: str) -> bool:
        if self.entries.pop(node_id_hex, None) is None:
            return False
        self.version += 1
        return True

    def snapshot(self) -> dict:
        return {"version": self.version,
                "nodes": list(self.entries.values())}

    def adopt(self, snap: dict) -> None:
        """Replace wholesale with a pushed snapshot. Pushes ride one FIFO
        connection, so the latest received is the latest sent; the version
        is kept for diagnostics and conflict reporting."""
        import time

        self.entries = {e["node_id"]: e for e in snap.get("nodes", [])}
        self.version = snap.get("version", self.version)
        self.epoch = snap.get("epoch", self.epoch)
        wl = snap.get("workloads")
        if wl is not None:
            self.serve_loads = wl
        self.adopted_ts = time.monotonic()

    def data_addr_of(self, node_id_hex: str):
        """Cached data-server address of a node, or None — the gossiped
        object directory's companion lookup (zero head RPCs)."""
        e = self.entries.get(node_id_hex)
        addr = e.get("data_addr") if e else None
        return tuple(addr) if addr else None

    # ------------------------------------------------------------ routing
    def select_node(self, resources: Dict[str, float],
                    label_selector: Optional[dict] = None,
                    require_sched: bool = True,
                    exclude: Optional[str] = None) -> Optional[dict]:
        """Feasible-node selection against the cached view: a node whose
        labels match and that either has warm idle pool workers or free
        capacity for the ask. Prefers the warmest pool (most idle
        workers), breaking ties on free capacity — the reference's
        best-node-by-load flavor without a second RPC."""
        best, best_key = None, None
        for e in self.entries.values():
            if require_sched and not e.get("sched_addr"):
                continue
            if exclude is not None and e["node_id"] == exclude:
                continue
            if not matches_labels(e.get("labels") or {}, label_selector):
                continue
            warm = e.get("idle_workers", 0)
            if not warm and not fits(e.get("free") or {}, resources):
                continue
            if not fits(e.get("total") or {}, resources):
                continue
            key = (warm, sum((e.get("free") or {}).values()))
            if best_key is None or key > best_key:
                best, best_key = e, key
        return best
