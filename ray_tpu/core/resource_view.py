"""Versioned cluster resource view — the `ray_syncer` equivalent.

Reference: `src/ray/common/ray_syncer/ray_syncer.h` — each node owns a
monotonically versioned snapshot of its local resource state and gossips
deltas; consumers keep a compacted cluster view and ignore stale versions.

Three parties share this module:

- the **head** (`gcs.py`) builds the authoritative compacted view: its own
  ledger supplies `free`/`total` per node, node-daemon deltas supply
  `idle_workers` (the daemon's warm lease pool) and `sched_addr`.  The view
  is broadcast (debounced) to node daemons and drivers.
- **node daemons** (`node_main.py`) gossip `{version, idle_workers,
  labels}` deltas to the head on change and cache the pushed cluster view.
- **clients** (`client.py`) cache the pushed view and use
  `select_node` for feasible-node lease routing: a lease request goes
  straight to the chosen node's daemon scheduler, touching the head only
  on infeasibility, version conflict (grant refused), or label miss.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def shard_of(node_id_hex: str, nshards: int) -> int:
    """Stable shard assignment for a node id. Node ids are random, so the
    first 32 bits are already uniform — no extra hashing needed, and every
    party (head, daemons, drivers, tests) computes the same shard."""
    if nshards <= 1:
        return 0
    return int(node_id_hex[:8] or "0", 16) % nshards


def matches_labels(labels: Dict[str, str],
                   selector: Optional[dict]) -> bool:
    """Shared label-selector semantics (NodeInfo and view entries must
    agree, or client-side routing and head-side granting diverge)."""
    if not selector:
        return True
    for k, v in selector.items():
        have = labels.get(k)
        if isinstance(v, (list, tuple, set)):   # "in" semantics
            if have not in v:
                return False
        elif have != str(v):
            return False
    return True


def fits(free: Dict[str, float], resources: Dict[str, float]) -> bool:
    return all(free.get(r, 0) >= amt - 1e-9 for r, amt in resources.items())


def make_entry(node_id_hex: str, *, version: int, free: Dict[str, float],
               total: Dict[str, float], labels: Dict[str, str],
               idle_workers: int = 0, sched_addr=None,
               data_addr=None, is_head: bool = False,
               store_frac=None, pool_shapes=None) -> dict:
    # data_addr: the node's object data server — consumers of the gossiped
    # object directory resolve pull sources from the cached view instead
    # of asking the head (host None = "the head's host", substituted by
    # each consumer from its own route to the head).
    # store_frac: that store's used/capacity fraction (None = unknown) —
    # the data plane's live memory-pressure signal.
    # pool_shapes: per-shape composition of the node's warm lease pool,
    # [[shape-pairs, count], ...] (shape = sorted (resource, amount)
    # pairs, the daemon's exact _pool_take key). None = the daemon
    # gossips no composition (legacy) — referral quality unknown.
    return {"node_id": node_id_hex, "version": version, "free": dict(free),
            "total": dict(total), "labels": dict(labels),
            "idle_workers": idle_workers, "sched_addr": sched_addr,
            "data_addr": data_addr, "is_head": is_head,
            "store_frac": store_frac, "pool_shapes": pool_shapes}


def pool_shape_key(resources: Dict[str, float]) -> tuple:
    """Canonical pool-shape key for a resource ask — the same sorted
    (name, amount) pairs the daemon keys its warm pool by, normalized so
    int/float spellings of the same ask compare equal across the wire."""
    return tuple(sorted((str(k), float(v)) for k, v in resources.items()))


def has_matching_shape(pool_shapes, resources: Dict[str, float]):
    """Whether a gossiped pool composition holds a warm worker of EXACTLY
    the asked shape (pool-take matches exact shapes, so anything else is
    a dead referral). None = composition unknown (the peer gossips no
    shapes) — callers treat that as 'maybe'."""
    if pool_shapes is None:
        return None
    ask = pool_shape_key(dict(resources))
    for row in pool_shapes:
        try:
            shape, count = row[0], row[1]
        except (TypeError, IndexError, KeyError):
            continue
        if count and tuple(
                (str(k), float(v)) for k, v in shape) == ask:
            return True
    return False


class ClusterView:
    """Compacted per-node view entries + a view-level version.

    `update` ignores regressions of a node's own version (a reconnecting
    daemon's stale delta must not rewind the view); every accepted change
    bumps the view version so consumers can detect staleness cheaply."""

    def __init__(self):
        self.entries: Dict[str, dict] = {}   # node_id hex -> entry
        self.version = 0
        # epoch fencing: the cluster epoch stamped into head-built
        # snapshots (0 until the first adopt); consumers tag lease/pool
        # traffic with it so stale-epoch ops are rejected after a head
        # restart instead of silently mutating the rebuilt ledger
        self.epoch = 0
        # flight-recorder gossip health: when this consumer last adopted a
        # head-pushed snapshot (monotonic; 0 = never) — `staleness_s()` is
        # the age of the cached view, gossiped back to the head as
        # per-node `gossip_lag_s`
        self.adopted_ts: float = 0.0
        # serve-replica live-load rows piggybacked on head snapshots
        # (changed-only, so absence in a snapshot means "unchanged");
        # None until the first row batch arrives — consumers
        # (serve/live_signals.py) distinguish "no serve plane yet" from
        # "idle serve plane" and fall back to the state API for the former
        self.serve_loads: Optional[list] = None
        # interest-scoped view plane: when the head shards its broadcast,
        # a scoped subscriber holds full entries only for its interest
        # shards (versioned independently, so a stale shard payload can
        # never rewind another shard's entries) plus a compact digest of
        # the whole cluster for spillback candidate selection
        self.nshards = 0
        self.shard_vs: Dict[int, int] = {}
        self.digest: Optional[dict] = None

    def staleness_s(self) -> float:
        """Seconds since the last adopted snapshot; -1 = never adopted."""
        import time

        if not self.adopted_ts:
            return -1.0
        return time.monotonic() - self.adopted_ts

    def update(self, entry: dict) -> bool:
        cur = self.entries.get(entry["node_id"])
        if cur is not None and entry["version"] < cur["version"]:
            return False
        if cur == entry:
            return False
        self.entries[entry["node_id"]] = entry
        self.version += 1
        return True

    def remove(self, node_id_hex: str) -> bool:
        if self.entries.pop(node_id_hex, None) is None:
            return False
        self.version += 1
        return True

    def snapshot(self) -> dict:
        return {"version": self.version,
                "nodes": list(self.entries.values())}

    def adopt(self, snap: dict) -> None:
        """Replace wholesale with a pushed snapshot. Pushes ride one FIFO
        connection, so the latest received is the latest sent; the version
        is kept for diagnostics and conflict reporting."""
        import time

        self.entries = {e["node_id"]: e for e in snap.get("nodes", [])}
        self.version = snap.get("version", self.version)
        self.epoch = snap.get("epoch", self.epoch)
        # a wholesale snapshot supersedes any sharded history (e.g. the
        # head restarted with sharding off)
        self.nshards = 0
        self.shard_vs.clear()
        wl = snap.get("workloads")
        if wl is not None:
            self.serve_loads = wl
        self.adopted_ts = time.monotonic()

    def adopt_shards(self, snap: dict) -> None:
        """Apply a sharded, interest-scoped broadcast payload.

        Each shard blob is a SNAPSHOT of that shard's current entries at
        an independent per-shard version: a blob at or below the version
        already applied for ITS shard is dropped (a delayed or replayed
        push must never rewind one shard while another is current), and
        applying a blob replaces that shard's entries wholesale so node
        removals need no tombstones. An epoch change (head restart) or a
        reshard invalidates EVERY cached shard atomically — entries from
        the old epoch's shards must not survive into the new one."""
        import time

        epoch = snap.get("epoch", 0)
        nshards = snap.get("nshards", 0)
        if ((epoch and self.epoch and epoch != self.epoch)
                or (self.nshards and nshards != self.nshards)):
            self.entries.clear()
            self.shard_vs.clear()
            self.version += 1
        if nshards:
            self.nshards = nshards
        if epoch:
            self.epoch = epoch
        for blob in snap.get("shards") or ():
            sid, v = blob["sid"], blob["v"]
            if v <= self.shard_vs.get(sid, -1):
                continue  # stale shard payload: keep the newer entries
            for h in [h for h in self.entries
                      if shard_of(h, self.nshards) == sid]:
                del self.entries[h]
            for e in blob.get("nodes") or ():
                self.entries[e["node_id"]] = e
            self.shard_vs[sid] = v
            self.version += 1
        d = snap.get("digest")
        if d is not None:
            self.digest = d
        wl = snap.get("workloads")
        if wl is not None:
            self.serve_loads = wl
        self.adopted_ts = time.monotonic()

    def data_addr_of(self, node_id_hex: str):
        """Cached data-server address of a node, or None — the gossiped
        object directory's companion lookup (zero head RPCs)."""
        e = self.entries.get(node_id_hex)
        addr = e.get("data_addr") if e else None
        return tuple(addr) if addr else None

    def max_store_frac(self) -> float:
        """Highest gossiped object-store pressure (used/capacity) across
        the cached view entries; 0.0 when no node reports one. The data
        plane's zero-RPC backpressure signal: a producer consults this
        before admitting more blocks into the cluster."""
        frac = 0.0
        for e in self.entries.values():
            f = e.get("store_frac")
            if f is not None and f > frac:
                frac = f
        return frac

    # ------------------------------------------------------------ routing
    def select_node(self, resources: Dict[str, float],
                    label_selector: Optional[dict] = None,
                    require_sched: bool = True,
                    exclude: Optional[str] = None) -> Optional[dict]:
        """Feasible-node selection against the cached view: a node whose
        labels match and that either has warm idle pool workers or free
        capacity for the ask. Prefers the warmest pool (most idle
        workers), breaking ties on free capacity — the reference's
        best-node-by-load flavor without a second RPC."""
        best, best_key = None, None
        for e in self.entries.values():
            if require_sched and not e.get("sched_addr"):
                continue
            if exclude is not None and e["node_id"] == exclude:
                continue
            if not matches_labels(e.get("labels") or {}, label_selector):
                continue
            warm = e.get("idle_workers", 0)
            if not warm and not fits(e.get("free") or {}, resources):
                continue
            if not fits(e.get("total") or {}, resources):
                continue
            key = (warm, sum((e.get("free") or {}).values()))
            if best_key is None or key > best_key:
                best, best_key = e, key
        return best

    def spill_candidates(self, resources: Dict[str, float],
                         label_selector: Optional[dict] = None,
                         exclude: Optional[str] = None,
                         limit: int = 2) -> List[dict]:
        """Peer daemons a local-pool miss can spill to: nodes whose
        gossiped pools show warm idle workers, warmest first. Full view
        entries are checked against totals; digest candidate rows (nodes
        outside this consumer's interest shards) carry no totals, so only
        labels gate them — the peer's own pool-take decides the rest.

        Referral quality: peers that gossip pool composition
        (`pool_shapes`) and provably hold NO warm worker of the asked
        shape are dropped — pool-take matches exact shapes, so referring
        to them is a guaranteed cold refusal hop. Peers whose composition
        shows a match rank above peers that don't gossip shapes."""
        if limit <= 0:
            return []
        # full entries are authoritative where we hold them: a digest row
        # must never resurrect a node the entry disqualified
        seen = set(self.entries)
        rows = []

        def _consider(e, check_total: bool):
            if (not e.get("sched_addr") or not e.get("idle_workers")
                    or e["node_id"] == exclude):
                return
            if not matches_labels(e.get("labels") or {}, label_selector):
                return
            if check_total and not fits(e.get("total") or {}, resources):
                return
            match = has_matching_shape(e.get("pool_shapes"), resources)
            if match is False:
                return  # dead referral: warm pool holds no such shape
            rows.append({"node_id": e["node_id"],
                         "sched_addr": tuple(e["sched_addr"]),
                         "idle_workers": e.get("idle_workers", 0),
                         "shape_match": match})

        for e in self.entries.values():
            _consider(e, check_total=True)
        for d in (self.digest or {}).get("candidates") or ():
            if d["node_id"] not in seen:
                _consider(d, check_total=False)
        rows.sort(key=lambda r: (bool(r["shape_match"]),
                                 r["idle_workers"]), reverse=True)
        return rows[:limit]
