"""Per-session worker log capture + streaming.

Reference parity: `python/ray/_private/log_monitor.py` (file tailing,
batched publish to drivers) and the per-worker stdout/stderr redirection
configured at `python/ray/_private/node.py:1426-1427`. Re-shaped for this
runtime:

- every spawner (head, node daemon) redirects a worker's stdout/stderr at
  the **fd level** into `<STATE_DIR>/<session>/logs/worker-<tag>.{out,err}`
  — captures C-level writes and the final lines of a crashing process;
- a `LogMonitor` thread on each spawning process tails its node's log dir
  and batches appended lines; node daemons push batches to the head;
- the head keeps a bounded per-file ring (CLI / dashboard / state API all
  read it, so logs from remote nodes work without a shared filesystem)
  and fans batches out to connected drivers, which print them — a remote
  task's `print()` appears on the submitting driver by default
  (disable with `RAY_TPU_LOG_TO_DRIVER=0`).
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Callable, Dict, List, Optional, TextIO, Tuple

from ray_tpu.utils.platform import STATE_DIR

MAX_LINE_LEN = 8192          # one pathological line must not balloon a batch
MAX_BATCH_LINES = 512
RING_LINES = 2000            # head-side retained lines per file
POLL_S = 0.15


def session_log_dir(session: str, subdir: Optional[str] = None) -> str:
    """`<STATE_DIR>/<session>/logs[/<subdir>]`. Each spawner tails only
    its own directory (the head the root, each node daemon a `node-<id>`
    subdir) so co-located monitors never double-report a line."""
    d = os.path.join(STATE_DIR, session, "logs")
    if subdir:
        d = os.path.join(d, subdir)
    os.makedirs(d, exist_ok=True)
    return d


def open_worker_logs(session: str, tag: Optional[str] = None,
                     subdir: Optional[str] = None
                     ) -> Tuple[TextIO, TextIO, str]:
    """Create the stdout/stderr files for a worker about to be spawned.
    Returns (out_file, err_file, tag); the spawner passes the files to
    Popen and the tag to the worker env (`RAY_TPU_LOG_TAG`) so the worker
    can report which files are its own at registration."""
    tag = tag or uuid.uuid4().hex[:10]
    d = session_log_dir(session, subdir)
    out = open(os.path.join(d, f"worker-{tag}.out"), "ab", buffering=0)
    err = open(os.path.join(d, f"worker-{tag}.err"), "ab", buffering=0)
    return out, err, tag


def find_log_file(session: str, filename: str) -> Optional[str]:
    """Locate a log file on this machine: session log root or any
    node subdir."""
    root = os.path.join(STATE_DIR, session, "logs")
    cand = os.path.join(root, filename)
    if os.path.exists(cand):
        return cand
    try:
        for sub in os.listdir(root):
            cand = os.path.join(root, sub, filename)
            if os.path.isdir(os.path.join(root, sub)) and os.path.exists(cand):
                return cand
    except OSError:
        pass
    return None


def list_log_files(session: str) -> Dict[str, int]:
    """All log files visible on this machine's session log tree."""
    out: Dict[str, int] = {}
    root = os.path.join(STATE_DIR, session, "logs")
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        path = os.path.join(root, name)
        if os.path.isdir(path):
            try:
                for sub in os.listdir(path):
                    try:
                        out[sub] = os.path.getsize(os.path.join(path, sub))
                    except OSError:
                        pass
            except OSError:
                pass
        else:
            try:
                out[name] = os.path.getsize(path)
            except OSError:
                pass
    return out


class LogMonitor(threading.Thread):
    """Tails `worker-*.{out,err}` files in one directory; invokes
    `emit(entries)` with `entries = [{"file": name, "lines": [...]}]`
    for freshly appended complete lines. Thread-safe against concurrent
    file creation; a deleted/truncated file restarts from its new end."""

    def __init__(self, log_dir: str,
                 emit: Callable[[List[dict]], None]):
        super().__init__(daemon=True, name="log-monitor")
        self.log_dir = log_dir
        self.emit = emit
        self._offsets: Dict[str, int] = {}
        self._partial: Dict[str, bytes] = {}
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                batch = self._scan()
            except Exception:
                batch = []
            if batch:
                try:
                    self.emit(batch)
                except Exception:
                    pass
            self._stop.wait(POLL_S)

    def _scan(self) -> List[dict]:
        entries: List[dict] = []
        try:
            names = sorted(os.listdir(self.log_dir))
        except OSError:
            return entries
        for name in names:
            if not (name.startswith("worker-")
                    and (name.endswith(".out") or name.endswith(".err"))):
                continue
            path = os.path.join(self.log_dir, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                self._offsets.pop(name, None)
                self._partial.pop(name, None)
                continue
            off = self._offsets.get(name, 0)
            if size < off:      # truncated/replaced: resync to the start
                off = 0
                self._partial.pop(name, None)
            if size == off:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(off)
                    data = f.read(min(size - off,
                                      MAX_BATCH_LINES * MAX_LINE_LEN))
            except OSError:
                continue
            self._offsets[name] = off + len(data)
            data = self._partial.pop(name, b"") + data
            *lines, tail = data.split(b"\n")
            if tail:
                if len(tail) > MAX_LINE_LEN:  # unterminated runaway line
                    lines.append(tail)
                else:
                    self._partial[name] = tail
            out = [ln[:MAX_LINE_LEN].decode("utf-8", "replace")
                   for ln in lines if ln]
            if out:
                entries.append({"file": name, "lines": out})
        return entries


MAX_LOG_FILES_RETAINED = 512   # head-side ring: bound files under churn


def read_log_lines(path: str, tail: Optional[int] = None) -> List[str]:
    """Read a log file's lines; `tail` reads only the end of the file
    (seek-from-end, bounded bytes) so a multi-GB log never loads whole."""
    with open(path, "rb") as f:
        if tail:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            budget = min(size, (tail + 1) * MAX_LINE_LEN)
            f.seek(size - budget)
            data = f.read(budget)
            if budget < size:  # first line is probably partial: drop it
                data = data.split(b"\n", 1)[-1]
        else:
            data = f.read()
    lines = [ln.decode("utf-8", "replace") for ln in data.split(b"\n") if ln]
    return lines[-tail:] if tail else lines


def format_driver_line(entry: dict, line: str) -> str:
    """Reference-style prefix: `(pid=123, worker-ab12cd) line`; stderr
    lines keep their stream visible."""
    pid = entry.get("pid")
    stem = entry["file"].rsplit(".", 1)[0]
    stream = entry["file"].rsplit(".", 1)[-1]
    who = f"pid={pid}, {stem}" if pid else stem
    mark = " [err]" if stream == "err" else ""
    return f"({who}){mark} {line}"


def print_driver_entries(entries: List[dict]) -> None:
    """Print streamed worker-log entries at a driver's terminal (local
    CoreClient and remote ProxyClient share this; format changes and the
    RAY_TPU_LOG_TO_DRIVER opt-out must never diverge between them)."""
    import sys

    from ray_tpu.core import config as _config

    if not _config.get("log_to_driver"):
        return
    out = []
    for e in entries:
        for line in e.get("lines", []):
            out.append(format_driver_line(e, line))
    if out:
        print("\n".join(out), file=sys.stderr, flush=True)
