"""Node daemon (`python -m ray_tpu.core.node_main`): joins a cluster.

The per-node agent — the raylet's role split (SURVEY §2.1 N1/N3): advertise
this node's resources+labels to the head, spawn/kill local worker processes
on request, AND run the node-local half of the two-level scheduler: a
scheduler server that grants/returns worker leases from a local pool, so a
client in steady state never touches the head (reference
`ClusterTaskManager::ScheduleAndDispatchTasks` + worker-pool ownership).
Pool state is gossiped to the head as versioned resource-view deltas
(`ray_syncer` role); the head pushes back the compacted cluster view.
Workers connect straight to the head; object data rides the node-local shm
store.

`ray start --address=...` equivalent for worker nodes.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List

from ray_tpu.core import config as _config
from ray_tpu.core import protocol
from ray_tpu.core.ids import NodeID
from ray_tpu.core.resource_view import ClusterView, matches_labels


class NodeDaemon:
    def __init__(self, head_host: str, head_port: int,
                 num_cpus=None, num_tpu_chips=None, resources=None,
                 labels=None, max_workers=None):
        from ray_tpu.core.resources import node_labels, node_resources

        self.head_host, self.head_port = head_host, head_port
        self.node_id = NodeID.generate()
        self.resources = node_resources(num_cpus, num_tpu_chips, resources)
        self.labels = {**node_labels(), **(labels or {})}
        self.max_workers = max_workers or max(
            int(self.resources.get("CPU", 4)) * 2, 8)
        self.session: str = ""
        self.conn: protocol.Connection = None
        self.procs: Dict[int, subprocess.Popen] = {}
        self.stopping = asyncio.Event()
        # object data plane: this daemon serves its node's store to remote
        # pullers (the raylet/object-manager role). Under isolation mode
        # the node gets its own store namespace, making single-machine
        # clusters exercise real remote fetches.
        self.store = None
        self.data_port: int = 0
        self._data_server: protocol.Server = None
        # node-local scheduler: warm lease pool + gossip state
        self.sched_port: int = 0
        self._sched_server: protocol.Server = None
        self.pool_idle: List[dict] = []     # {wid, addr, venv_key, shape, since}
        self.pool_leases: Dict[bytes, dict] = {}  # wid -> pool entry
        self.cluster_view = ClusterView()
        self._gossip_version = 0
        self._gossip_pending = False
        # flight recorder: bounded ring of lease-lifecycle/gossip events +
        # monotonic counters, both piggybacked on the resource_view_delta
        # gossip this daemon already sends — telemetry costs zero extra
        # round trips (core/flight_recorder.py)
        from ray_tpu.core.flight_recorder import EventRing

        self.fr_events = EventRing(_config.get("flight_recorder_events"))
        self.sched_stats = {"local_grants": 0, "spillbacks": 0,
                            "pool_acquires": 0, "lease_returns": 0,
                            "pool_releases": 0, "pool_worker_deaths": 0,
                            "peer_spillbacks": 0, "peer_grants": 0,
                            # data-plane cold misses: pulls that fell back
                            # to the head's locate_object (the scoped
                            # directory didn't cover the serving node —
                            # interest-on-demand widening should make
                            # these stop recurring per node)
                            "locate_fallbacks": 0}
        # interest-on-demand: shards this daemon widened its scoped view
        # subscription to (beyond its own), re-asserted after reconnects
        self._interest_extra: set = set()
        self._fr_metrics_ts = 0.0   # last registry snapshot ride-along
        self._last_gossip_ts = 0.0  # heartbeat bookkeeping (monotonic)
        # partition tolerance: the cluster epoch observed from the head
        # (stamped into pool/lease traffic; stale-epoch ops are rejected
        # head-side and routed into reconciliation), drained-but-unacked
        # flight-recorder events (resent until the head acks their seq),
        # and pool_release carve-out returns awaiting delivery (requeued
        # with bounded backoff instead of fire-and-forget — a release
        # lost mid-head-outage must not leak the head-side carve-out)
        self.head_epoch = 0
        self._reconnecting = False
        self._fr_pending: List[dict] = []
        self._pending_releases: List[dict] = []
        # object data plane: cached copy of the gossiped object directory
        # (applied from cluster_view broadcasts), the full metas of
        # objects PRIMARY on this node (the spill-restore inventory the
        # reconcile handshake re-advertises after a head restart), queued
        # replica announcements for the next gossip delta, and the node
        # pull manager (created with the store in start())
        from ray_tpu.core.object_directory import ObjectDirectory

        self.object_dir = ObjectDirectory()
        self.local_objects: Dict[bytes, object] = {}   # oid bytes -> meta
        self._dir_out: List[dict] = []
        self.pull = None
        isolation = _config.get("store_isolation")
        self.store_ns = _config.get("store_namespace") or (
            self.node_id.hex()[:8] if isolation else "")
        self._create_arena = isolation

    async def start(self):
        from ray_tpu.core import flight_recorder, object_transfer
        from ray_tpu.util import metrics as _metrics

        _metrics.disable_pusher()  # daemon metrics ride gossip, not the KV
        flight_recorder.install("daemon")
        self._data_server = protocol.Server(
            object_transfer.make_data_handlers(lambda: self.store,
                                               lambda: self.pull),
            name="node-data")
        self.data_port = await self._data_server.start(
            host=_config.get("bind_host"))
        self._sched_server = protocol.Server(
            {}, on_connect=self._on_sched_connect, name="node-sched")
        self.sched_port = await self._sched_server.start(
            host=_config.get("bind_host"))
        self.conn = await protocol.connect(
            self.head_host, self.head_port,
            handlers=self._head_handlers(), name="node")
        self.conn.on_close = self._on_head_conn_close
        reply = await self.conn.request(
            "register_node", node_id=self.node_id.binary(),
            resources=self.resources, labels=self.labels,
            max_workers=self.max_workers, data_port=self.data_port,
            sched_port=self.sched_port,
            # interest-scoped view plane: when the head shards the
            # cluster_view broadcast, this daemon only needs the shard it
            # lives in (its own entry + neighbors) plus the digest — full
            # fan-out of the whole node list does not scale past ~200
            # nodes ("auto" = the head computes the scope; ignored when
            # sharding is off)
            interest="auto")
        self.session = reply["session"]
        self.head_epoch = reply.get("epoch", 0)
        # reconciliation handshake runs on EVERY (re)connect — trivially
        # empty on first boot, the ledger-rebuild source of truth after a
        # head restart
        await self._send_reconcile()
        asyncio.ensure_future(self._pool_shrink_loop())
        asyncio.ensure_future(self._fr_heartbeat_loop())
        asyncio.ensure_future(self._release_flush_loop())
        from ray_tpu.core.store import (SharedMemoryStore,
                                        default_store_bytes as _default_store_bytes)

        self.store = SharedMemoryStore(
            self.session,
            capacity_bytes=(
                int(_config.get("object_store_bytes"))
                or _default_store_bytes()),
            create_arena=self._create_arena, namespace=self.store_ns)
        # spills retarget our local meta copy; the head owns the canonical
        # entry and must learn the new location
        self.store.on_spill = lambda m: self.conn.push("object_spilled",
                                                       meta=m)
        # node pull manager: local workers' remote pulls funnel through
        # here (`pull_object` on the data server) so each object crosses
        # the network once per node; pulled replicas are announced into
        # the gossiped directory as extra sources for everyone else
        self.pull = object_transfer.PullManager(
            lambda: self.store, role="daemon",
            resolve=self._resolve_pull_sources,
            on_replica=self._on_replica_created,
            on_replica_gone=self._on_replica_dropped)
        # tail this node's worker log files; new lines ride the control
        # connection to the head, which fans them out to drivers and keeps
        # its ring for the CLI/dashboard (reference log_monitor.py role)
        from ray_tpu.core import worker_logs

        loop = asyncio.get_running_loop()

        def _emit(batch):
            loop.call_soon_threadsafe(
                lambda: self.conn.push("log_batch", entries=batch)
                if self.conn is not None and not self.conn.closed else None)

        self._log_monitor = worker_logs.LogMonitor(
            worker_logs.session_log_dir(
                self.session, f"node-{self.node_id.hex()[:12]}"),
            emit=_emit)
        self._log_monitor.start()

    async def _health_ping(self):
        return True

    def _head_handlers(self) -> Dict[str, object]:
        return {
            "spawn_worker": self._spawn_worker,
            "kill_worker": self._kill_worker,
            "shutdown_node": self._shutdown_node,
            "free_object": self._free_object,
            "drop_replica": self._on_drop_replica,
            "adopt_object": self._adopt_object,
            "health_ping": self._health_ping,
            "cluster_view": self._on_cluster_view,
            "pool_worker_died": self._on_pool_worker_died,
            "pool_trim": self._on_pool_trim,
            "reconcile_request": self._on_reconcile_request,
            "chaos": self._on_chaos,
        }

    async def _on_pool_trim(self, resources=None):
        """Head-pushed reclaim: queued head-path tasks are starving for
        capacity this pool holds idle (pools can otherwise hoard a
        node's entire ledger until pool_idle_s). Release one idle worker
        — preferring the starving shape — through the normal ack-tracked
        release path."""
        shape = tuple(sorted(resources.items())) if resources else None
        ent = self._pool_take(shape, None) if shape is not None else None
        if ent is None and self.pool_idle:
            ent = self.pool_idle.pop()
        if ent is None:
            return False
        self._fr("pool_release", worker=ent["wid"].hex()[:12], trim=True)
        self._pending_releases.append(
            {"wid": ent["wid"], "seq": ent.get("seq"),
             "epoch": self.head_epoch, "attempts": 0,
             "next_try": time.monotonic()})
        self._gossip_soon()
        return True

    async def _on_reconcile_request(self):
        """Head-pushed when it saw a stale-epoch op from us: re-run the
        inventory handshake so its ledger matches our pools."""
        asyncio.ensure_future(self._send_reconcile())
        return True

    async def _on_chaos(self, spec):
        """Chaos control plane: the head relays a fault plan for THIS
        process (tests partition the daemon<->head edge on demand)."""
        protocol.configure_chaos(spec)
        self._fr("chaos_config", spec=spec)
        return True

    # -------------------------------------------- head outage / reconnect
    def _on_head_conn_close(self, c) -> None:
        """Graceful degradation instead of suicide: during a head outage
        or partition the daemon keeps serving warm-path leases from its
        existing pools, queues gossip/flight-recorder deltas, and drains
        them after the reconciliation handshake on heal."""
        if self.stopping.is_set() or self._reconnecting:
            return
        timeout = float(_config.get("node_reconnect_timeout_s"))
        if timeout <= 0:
            self.stopping.set()
            return
        self._reconnecting = True
        self._fr("head_lost", epoch=self.head_epoch)
        asyncio.ensure_future(self._head_reconnect_loop(timeout))

    async def _head_reconnect_loop(self, timeout: float) -> None:
        try:
            deadline = time.monotonic() + timeout
            delay = 0.2
            while not self.stopping.is_set() and time.monotonic() < deadline:
                try:
                    conn = await protocol.connect(
                        self.head_host, self.head_port,
                        handlers=self._head_handlers(), name="node")
                except OSError:
                    await asyncio.sleep(delay)
                    delay = min(delay * 1.6, 2.0)
                    continue
                try:
                    reply = await conn.request(
                        "register_node", node_id=self.node_id.binary(),
                        resources=self.resources, labels=self.labels,
                        max_workers=self.max_workers,
                        data_port=self.data_port,
                        sched_port=self.sched_port,
                        interest="auto")
                except Exception:
                    try:
                        await conn.close()
                    except Exception:
                        pass
                    await asyncio.sleep(delay)
                    delay = min(delay * 1.6, 2.0)
                    continue
                self.conn = conn
                conn.on_close = self._on_head_conn_close
                self.head_epoch = reply.get("epoch", 0)
                self._fr("head_reconnect", epoch=self.head_epoch)
                await self._send_reconcile()
                if self._interest_extra:
                    # re-assert on-demand interest widening: the fresh
                    # registration reset our view_sub to the auto scope
                    try:
                        conn.push("widen_interest",
                                  shards=sorted(self._interest_extra))
                    except Exception:
                        pass
                # drain queued telemetry + re-advertise pool state under
                # the (possibly new) epoch
                self._gossip_send(bump=True)
                if conn.closed:
                    # the head died again mid-handshake; its on_close was
                    # swallowed by the _reconnecting guard — retry here
                    # instead of returning detached forever
                    await asyncio.sleep(delay)
                    continue
                return
            self.stopping.set()
        finally:
            self._reconnecting = False
            if (not self.stopping.is_set() and self.conn is not None
                    and self.conn.closed):
                # close landed between the in-loop check and the guard
                # clearing: re-enter the normal head-loss path now that
                # it will no longer be swallowed
                self._on_head_conn_close(self.conn)

    async def _send_reconcile(self) -> None:
        """Report the full pool inventory (idle + live local leases) so
        the head rebuilds its carve-out ledger from us — the daemon is
        the source of truth for carved capacity."""
        inventory = []
        for ent in list(self.pool_idle) + list(self.pool_leases.values()):
            inventory.append({
                "wid": ent["wid"],
                "resources": dict(ent.get("res") or dict(ent["shape"])),
                "venv_key": ent.get("venv_key"),
                "seq": ent.get("seq")})
        if self.conn is None or self.conn.closed:
            return
        # spill-restore: re-advertise this node's surviving object
        # inventory (primary shm/arena/spilled metas cached from the
        # directory gossip + our pulled replicas) so a restarted head
        # rebuilds its object directory from daemon truth — shm objects
        # no longer die with the head
        objects = None
        if _config.get("object_directory"):
            objects = {
                "metas": list(self.local_objects.values()),
                "replicas": [oid.binary() for oid in
                             (self.pull.replica_ids() if self.pull else ())]}
        try:
            rep = await self.conn.request(
                "pool_reconcile", inventory=inventory,
                epoch=self.head_epoch, objects=objects)
        except protocol.RpcError:
            return
        if rep:
            self.head_epoch = rep.get("epoch", self.head_epoch)
            self._fr("pool_reconcile", reported=len(inventory),
                     adopted=rep.get("adopted"),
                     released=rep.get("released"),
                     objects=len(self.local_objects))
        # the rebuilt ledger covers releases queued under a dead epoch
        # (their workers are simply absent from the report) — drop them
        self._pending_releases = [p for p in self._pending_releases
                                  if p["epoch"] == self.head_epoch]

    # ------------------------------------------- node-local scheduling
    def _on_sched_connect(self, conn: protocol.Connection) -> None:
        """Per-client scheduler session. Leases are bound to the client's
        live connection — its death returns every held worker to the pool
        (the renew protocol is connection liveness, like the reference's
        lease expiry on client disconnect)."""
        held: set = set()

        def _spill(reason: str) -> dict:
            self._fr("spillback", reason=reason)
            return {"spill": reason}

        async def lease_grant(resources, label_selector=None, venv_key=None,
                              epoch=None, referred=None):
            if epoch is not None and self.head_epoch \
                    and epoch != self.head_epoch:
                # the client's cached view predates a head restart (or
                # lags ours): refuse and let it spill to the head, which
                # grants under the current epoch — stale-epoch traffic is
                # fenced, never silently applied. The same fence covers
                # peer-referred grants: a daemon partitioned across an
                # epoch bump cannot double-grant against a rebuilt ledger.
                if referred:
                    self._fr("peer_refuse", reason="epoch", referrer=referred)
                return _spill("epoch")
            if not matches_labels(self.labels, label_selector):
                if referred:
                    self._fr("peer_refuse", reason="labels",
                             referrer=referred)
                return _spill("labels")
            shape = tuple(sorted(resources.items()))
            t0 = time.monotonic()
            ent = self._pool_take(shape, venv_key)
            warm = ent is not None
            if ent is None and referred:
                # a peer daemon referred this client here expecting a warm
                # worker; the referral was stale — refuse WITHOUT
                # cascading (no head carve, no further referral: referral
                # chains must terminate after one hop)
                self._fr("peer_refuse", reason="cold", referrer=referred)
                return _spill("cold")
            if ent is None:
                # cold pool. Daemon-to-daemon spillback first: a peer
                # whose gossiped pool shows warm idle workers can grant
                # NOW with zero head involvement (warm steal beats a cold
                # head carve, and it is the only path that keeps task
                # throughput alive while the head is paused/partitioned).
                # The head carve remains the growth path when no peer
                # advertises warm capacity — the last resort, not the
                # default.
                peers = self._spill_candidates(resources, label_selector)
                if peers:
                    self._fr("peer_spill", shape=list(shape),
                             peers=[p["node_id"][:12] for p in peers])
                    return {"spill": "peer", "peers": peers}
                if self.conn is None or self.conn.closed:
                    return _spill("head")
                try:
                    fut = self.conn.request_future(
                        "pool_acquire", resources=resources,
                        venv_key=venv_key, epoch=self.head_epoch)
                except Exception:
                    return _spill("head")
                try:
                    # bounded: a SIGSTOPped head keeps the TCP connection
                    # alive, so an unbounded carve RPC would stall every
                    # cold grant on this node for the whole outage. The
                    # request itself is shielded — a LATE grant (slow
                    # worker spawn, head resuming) is adopted into the
                    # pool instead of leaking the head-side carve-out.
                    rep = await asyncio.wait_for(
                        asyncio.shield(fut),
                        timeout=float(
                            _config.get("pool_acquire_timeout_s")))
                except protocol.RpcError:
                    return _spill("head")
                except asyncio.TimeoutError:
                    fut.add_done_callback(
                        lambda f: self._adopt_late_carve(
                            f, venv_key, shape, dict(resources)))
                    return _spill("head")
                if rep is None:
                    return _spill("resources")
                self._fr("pool_acquire", shape=list(shape),
                         wait_s=round(time.monotonic() - t0, 6))
                ent = {"wid": rep["worker_id"], "addr": tuple(rep["addr"]),
                       "venv_key": venv_key, "shape": shape,
                       "res": dict(resources),
                       "seq": rep.get("grant_seq"),
                       "since": time.monotonic()}
                if conn.closed:
                    # client died during the head round trip: its on_close
                    # already drained `held`, so lease it to nobody — pool
                    # the fresh worker instead of leaking it forever
                    self.pool_idle.append(ent)
                    self._gossip_soon()
                    return None
            self.pool_leases[ent["wid"]] = ent
            held.add(ent["wid"])
            if referred:
                # warm grant for a peer referral: count it separately so
                # the mesh is observable (lease_peer_spillbacks_total /
                # peer_grants on /metrics and in the lease-event stream)
                self._fr("peer_grant", shape=list(shape), referrer=referred,
                         worker=ent["wid"].hex()[:12])
            self._fr("local_grant", shape=list(shape), warm=warm,
                     worker=ent["wid"].hex()[:12])
            self._gossip_soon()
            rep = {"worker_id": ent["wid"], "addr": ent["addr"]}
            if referred:
                rep["peer"] = self.node_id.hex()
            return rep

        async def lease_return(worker_id):
            held.discard(worker_id)
            self._fr("lease_return", worker=worker_id.hex()[:12])
            self._pool_return(worker_id)
            return True

        async def health_ping():
            return True

        conn.handlers.update({"lease_grant": lease_grant,
                              "lease_return": lease_return,
                              "health_ping": health_ping})
        orig_close = conn.on_close

        def on_close(c):
            if orig_close:
                orig_close(c)
            for wid in list(held):
                self._pool_return(wid)

        conn.on_close = on_close

    _FR_COUNTERS = {"local_grant": "local_grants", "spillback": "spillbacks",
                    "pool_acquire": "pool_acquires",
                    "lease_return": "lease_returns",
                    "pool_release": "pool_releases",
                    "pool_worker_died": "pool_worker_deaths",
                    "peer_spill": "peer_spillbacks",
                    "peer_grant": "peer_grants"}

    def _adopt_late_carve(self, fut, venv_key, shape, resources) -> None:
        """A pool_acquire we timed out on completed anyway: the head has
        already debited its ledger and marked the worker pooled, so
        dropping the reply would leak the carve-out forever (the head
        never dispatches to pooled workers). Adopt it into the idle pool
        instead — the next matching grant serves it warm."""
        if fut.cancelled() or fut.exception() is not None:
            return
        rep = fut.result()
        if not rep:
            return
        self._fr("pool_acquire", shape=list(shape), late=True)
        self.pool_idle.append(
            {"wid": rep["worker_id"], "addr": tuple(rep["addr"]),
             "venv_key": venv_key, "shape": shape, "res": resources,
             "seq": rep.get("grant_seq"), "since": time.monotonic()})
        self._gossip_soon()

    def _spill_candidates(self, resources, label_selector) -> List[dict]:
        """Peer daemons this node can refer a cold lease request to,
        resolved entirely from the cached cluster view + digest (zero
        head RPCs — that is the point)."""
        limit = int(_config.get("peer_spill_attempts"))
        if limit <= 0:
            return []
        return self.cluster_view.spill_candidates(
            resources, label_selector, exclude=self.node_id.hex(),
            limit=limit)

    def _fr(self, kind: str, **detail) -> None:
        """Record a flight-recorder event + bump its lifetime counter; the
        ring drains into the next gossip delta (no RPC of its own)."""
        self.fr_events.record(kind, **detail)
        key = self._FR_COUNTERS.get(kind)
        if key is not None:
            self.sched_stats[key] += 1

    def _pool_take(self, shape: tuple, venv_key):
        for i in range(len(self.pool_idle) - 1, -1, -1):
            ent = self.pool_idle[i]
            if ent["shape"] == shape and ent["venv_key"] == venv_key:
                del self.pool_idle[i]
                return ent
        return None

    def _pool_return(self, worker_id: bytes) -> None:
        ent = self.pool_leases.pop(worker_id, None)
        if ent is None:
            return  # already reaped (worker died) or double return
        ent["since"] = time.monotonic()
        self.pool_idle.append(ent)
        self._gossip_soon()

    async def _pool_shrink_loop(self) -> None:
        """Return pooled workers (and their head-side carve-outs) after
        they idle too long — the pool borrows capacity, it doesn't own
        it forever."""
        idle_s = _config.get("pool_idle_s")
        while not self.stopping.is_set():
            await asyncio.sleep(max(idle_s / 2, 0.5))
            now = time.monotonic()
            keep = [e for e in self.pool_idle
                    if now - e["since"] <= idle_s]
            drop = [e for e in self.pool_idle
                    if now - e["since"] > idle_s]
            if not drop:
                continue
            self.pool_idle = keep
            for ent in drop:
                self._fr("pool_release", worker=ent["wid"].hex()[:12],
                         idle_s=round(now - ent["since"], 3))
                # NOT fire-and-forget: an unreachable head mid-release
                # used to leak the head-side carve-out forever — queue it
                # for delivery with bounded backoff; the (epoch,
                # grant_seq) key makes duplicates/retries idempotent
                self._pending_releases.append(
                    {"wid": ent["wid"], "seq": ent.get("seq"),
                     "epoch": self.head_epoch, "attempts": 0,
                     "next_try": time.monotonic()})
            self._gossip_soon()

    async def _release_flush_loop(self) -> None:
        """Deliver queued pool_release returns; retry with bounded
        exponential backoff while the head is unreachable. Stale-epoch
        entries are settled by the reconciliation handshake instead
        (the head rebuilds its ledger from our inventory)."""
        while not self.stopping.is_set():
            await asyncio.sleep(0.25)
            if not self._pending_releases:
                continue
            if self.conn is None or self.conn.closed:
                continue
            now = time.monotonic()
            for p in list(self._pending_releases):
                if p["next_try"] > now:
                    continue
                try:
                    await self.conn.request(
                        "pool_release", worker_id=p["wid"],
                        grant_seq=p["seq"], epoch=p["epoch"])
                except protocol.RpcError:
                    p["attempts"] += 1
                    p["next_try"] = time.monotonic() + min(
                        0.5 * (2 ** p["attempts"]), 5.0)
                    continue
                # applied, idempotent no-op, or stale-epoch (reconcile
                # covers it): the head-side carve-out is settled
                try:
                    self._pending_releases.remove(p)
                except ValueError:
                    pass

    def _gossip_soon(self) -> None:
        """Debounced versioned delta to the head (ray_syncer node half)."""
        if self._gossip_pending:
            return
        self._gossip_pending = True
        asyncio.get_running_loop().call_later(
            _config.get("gossip_debounce_s"), self._gossip_flush)

    def _gossip_flush(self) -> None:
        self._gossip_pending = False
        self._gossip_send(bump=True)

    def _gossip_send(self, bump: bool) -> None:
        """Send a resource_view_delta (a request now: the reply acks the
        flight-recorder batch). `bump=True` is a real state change (new
        version, head re-evaluates the view); `bump=False` is the
        telemetry heartbeat — it resends the CURRENT version so the head
        merges the piggybacked flight-recorder payload and refreshes its
        staleness clock without the view plane rebroadcasting anything.

        Delivery acks: drained ring events wait in `_fr_pending` until
        the head acknowledges their seq; un-acked batches ride every
        delta (the head drops duplicates by per-node seq) and survive a
        dying connection — a delta lost mid-daemon-death no longer loses
        its drained batch (the reconnect resends it)."""
        if self.conn is None or self.conn.closed:
            return  # ring + pending keep buffering; drained on reconnect
        if bump:
            self._gossip_version += 1
        # resend buffer bounded at 1024 (drained ≤256 per delta): when
        # acks stall long enough to fill it, further events stay in the
        # ring, which bounds itself and counts overflow as dropped
        room = min(256, 1024 - len(self._fr_pending))
        if room > 0:
            self._fr_pending.extend(self.fr_events.drain(limit=room))
        events = list(self._fr_pending)
        gossip = {"view_version": self.cluster_view.version,
                  "view_age_s": round(self.cluster_view.staleness_s(), 3),
                  "dir_age_s": round(self.object_dir.staleness_s(), 3),
                  "dir_v": self.object_dir.last_v,
                  "events_dropped": self.fr_events.dropped}
        # replica announcements (pull-replica created / evicted) ride the
        # same delta; a batch lost with a dying connection only delays an
        # optimization, so no ack tracking — the reconcile handshake
        # re-advertises surviving replicas wholesale anyway
        dir_out, self._dir_out = self._dir_out, []
        stats = dict(self.sched_stats)
        if self.pull is not None:
            stats.update(self.pull.stats)
            stats["replica_count"] = self.pull.replica_count()
        if self.store is not None:
            # object-store pressure rides the gossip so the head can stamp
            # store_frac into the broadcast view entries — the data
            # plane's backpressure signal, zero extra RPCs
            stats["store_used"] = int(self.store.used)
            stats["store_cap"] = int(getattr(self.store, "capacity", 0))
        metrics_snap = None
        drained_spans = None
        now = time.monotonic()
        from ray_tpu.util import metrics as _metrics
        from ray_tpu.util import tracing as _tracing

        if now - self._fr_metrics_ts >= _config.get(
                "metrics_push_interval_s"):
            self._fr_metrics_ts = now
            # full telemetry payload: registry snapshot + piggybacked
            # workload stats and drained spans (same channel, zero RPCs).
            # Spans drained explicitly so a failed/nacked delta can put
            # them back instead of holing the cross-process timeline.
            drained_spans = _tracing.drain_push_spans()
            metrics_snap = _metrics.push_payload(drained_spans)
        self._last_gossip_ts = now
        # per-shape composition of the warm pool (the exact sorted
        # (resource, amount) tuples _pool_take matches on): broadcast via
        # the view so peer-spillback referrals can skip peers that
        # provably hold no matching warm worker. Always sent (possibly
        # empty) — an empty list is a real signal ("warm but wrong-shaped
        # pools elsewhere won't help you"), None would mean "unknown".
        shape_counts: Dict[tuple, int] = {}
        for ent in self.pool_idle:
            sh = tuple(tuple(p) for p in (ent.get("shape") or ()))
            shape_counts[sh] = shape_counts.get(sh, 0) + 1
        pool_shapes = [[[list(p) for p in sh], c]
                       for sh, c in sorted(shape_counts.items())]
        try:
            fut = self.conn.request_future(
                "resource_view_delta", version=self._gossip_version,
                idle_workers=len(self.pool_idle),
                leased_workers=len(self.pool_leases),
                events=events, stats=stats,
                gossip=gossip, metrics=metrics_snap,
                epoch=self.head_epoch, objects=dir_out or None,
                pool_shapes=pool_shapes)
        except Exception:
            self._dir_out = dir_out + self._dir_out
            if drained_spans:
                _tracing.requeue_push_spans(drained_spans)
            return  # events stay pending; the next heartbeat retries

        def _acked(f, spans=drained_spans):
            if f.cancelled() or f.exception() is not None:
                if spans:
                    _tracing.requeue_push_spans(spans)
                return  # still pending; resent with the next delta
            rep = f.result()
            if not isinstance(rep, dict):
                # head replied but didn't merge (e.g. our node record is
                # mid-reconnect): the delta's telemetry never landed —
                # resend the spans like the failure path does
                if spans:
                    _tracing.requeue_push_spans(spans)
                return
            if rep.get("nack"):
                # stale epoch: the head dropped the whole delta before
                # the telemetry merge; reconciliation (already requested
                # by the head) will refresh the epoch — resend the spans
                # with a later delta like the event batch
                if spans:
                    _tracing.requeue_push_spans(spans)
                return
            ack = rep.get("acked_seq", 0)
            if ack:
                self._fr_pending = [e for e in self._fr_pending
                                    if e["seq"] > ack]

        fut.add_done_callback(_acked)

    async def _fr_heartbeat_loop(self) -> None:
        """Telemetry liveness: a quiet daemon (no pool churn → no deltas)
        must still deliver its ring/stats and keep the head's
        cluster_view_staleness_s honest — heartbeats reuse the gossip
        channel with an unchanged version (zero view-plane cost)."""
        interval = max(float(_config.get("metrics_push_interval_s")), 0.25)
        while not self.stopping.is_set():
            await asyncio.sleep(interval / 2)
            if time.monotonic() - self._last_gossip_ts >= interval:
                self._gossip_send(bump=False)

    async def _on_cluster_view(self, snap):
        prev_age = self.cluster_view.staleness_s()
        if "shards" in snap:
            # interest-scoped broadcast: only the shards this daemon
            # subscribed to (plus the digest) — adopt per-shard
            self.cluster_view.adopt_shards(snap)
            nodes = sum(len(b.get("nodes") or ())
                        for b in snap.get("shards") or ())
        else:
            self.cluster_view.adopt(snap)
            nodes = len(snap.get("nodes", []))
        self.head_epoch = snap.get("epoch", self.head_epoch)
        self._adopt_directory(snap.get("objects"))
        self._fr("view_adopt", version=snap.get("version"),
                 nodes=nodes, age_s=round(prev_age, 3))
        return True

    # ------------------------------------------------ object data plane
    def _adopt_directory(self, payload) -> None:
        """Apply an object-directory payload from a cluster_view push.

        Alongside the shared cache, track full metas of objects PRIMARY
        on this node in `local_objects` — the inventory the reconcile
        handshake re-advertises so a restarted head rebuilds its object
        directory from daemon truth. A FULL payload only ADDS to
        local_objects (a freshly restarted head's wholesale snapshot is
        empty — wiping here would destroy the very inventory the
        handshake exists to restore); removals ride explicit free
        records and head-pushed free_object."""
        if not payload:
            return
        me = self.node_id.hex()
        for rec in (payload.get("delta") or ()):
            op = rec.get("op")
            if op in ("seal", "spill"):
                meta = rec["meta"]
                if meta.node_id is not None and meta.node_id.hex() == me:
                    self.local_objects[meta.object_id.binary()] = meta
            elif op == "free":
                self.local_objects.pop(rec["oid"], None)
        for ent in (payload.get("full") or ()):
            meta = ent["meta"]
            if meta.node_id is not None and meta.node_id.hex() == me:
                self.local_objects[meta.object_id.binary()] = meta
        self.object_dir.apply(payload)

    async def _resolve_pull_sources(self, meta) -> list:
        """Pull sources for this node's pull manager: the cached gossiped
        directory + cluster-view data addresses first (zero head RPCs on
        the warm path); the head's locate_object only on a cold miss."""
        from ray_tpu.core.object_directory import resolve_addrs

        out = resolve_addrs(self.object_dir, meta,
                            self.cluster_view.data_addr_of,
                            self.head_host, exclude=self.node_id.hex())
        if not out and self.conn is not None and not self.conn.closed:
            self.sched_stats["locate_fallbacks"] += 1
            try:
                rep = await self.conn.request(
                    "locate_object",
                    object_id=meta.object_id.binary(), timeout=15)
            except protocol.RpcError:
                rep = None
            if rep:
                for s in (rep.get("sources")
                          or ([rep["data_addr"]]
                              if rep.get("data_addr") else [])):
                    out.append((s[0] or self.head_host, s[1]))
                self._maybe_widen_interest(rep.get("nodes") or ())
        return out

    def _maybe_widen_interest(self, serving_hexes) -> None:
        """Interest-on-demand (ROADMAP item 1 follow-on): a cold miss on
        a scoped view means the serving node lives outside our interest
        shards — widen the subscription to its shard so repeated
        data-plane pulls from that neighborhood stop paying the
        locate_object fallback. One fire-and-forget push per new shard;
        the head replies with a fresh scoped view covering it."""
        nshards = self.cluster_view.nshards
        if nshards <= 1 or not serving_hexes:
            return
        from ray_tpu.core.resource_view import shard_of

        own = shard_of(self.node_id.hex(), nshards)
        new = {shard_of(h, nshards) for h in serving_hexes}
        new -= self._interest_extra | {own}
        if not new:
            return
        self._interest_extra |= new
        self._fr("interest_widen", shards=sorted(new))
        if self.conn is not None and not self.conn.closed:
            try:
                self.conn.push("widen_interest", shards=sorted(new))
            except Exception:
                pass

    def _on_replica_created(self, local_meta) -> None:
        from ray_tpu.core import object_directory as objdir

        self._dir_out.append(objdir.replica_record(
            local_meta.object_id, self.node_id.hex()))
        self._gossip_soon()

    def _on_replica_dropped(self, oid) -> None:
        from ray_tpu.core import object_directory as objdir

        self._dir_out.append(objdir.replica_gone_record(
            oid, self.node_id.hex()))
        self._gossip_soon()

    async def _on_drop_replica(self, object_id):
        """Head-pushed when the canonical object is freed: unlink our
        pulled replica (the meta the head holds describes the primary's
        storage, not our copy)."""
        from ray_tpu.core.ids import ObjectID

        if self.pull is not None:
            self.pull.drop(ObjectID(object_id))
        return True

    async def _on_pool_worker_died(self, worker_id):
        self.pool_leases.pop(worker_id, None)
        self.pool_idle = [e for e in self.pool_idle
                          if e["wid"] != worker_id]
        self._fr("pool_worker_died", worker=worker_id.hex()[:12])
        self._gossip_soon()
        return True

    async def _spawn_worker(self, pip=None, pip_key=None):
        from ray_tpu.core.resources import strip_device_env
        from ray_tpu.core import worker_logs

        env = strip_device_env(dict(os.environ))
        env["RAY_TPU_HEAD_PORT"] = str(self.head_port)
        env["RAY_TPU_HEAD_HOST"] = self.head_host
        env["RAY_TPU_SESSION"] = self.session
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        # local workers route remote-object pulls through this daemon's
        # pull manager (each object crosses the network once per node)
        env["RAY_TPU_NODE_DATA_PORT"] = str(self.data_port)
        if self.store_ns:
            env["RAY_TPU_STORE_NAMESPACE"] = self.store_ns
        python = sys.executable
        if pip:
            # pip-isolated worker: build/reuse the content-addressed venv
            # OFF the daemon loop (first build runs pip install) and start
            # the worker from its interpreter (reference
            # runtime_env_agent.py:298 GetOrCreateRuntimeEnv + pip.py)
            from ray_tpu.core import runtime_env as _renv

            loop = asyncio.get_running_loop()
            python = await loop.run_in_executor(
                None, _renv.materialize_venv, pip, pip_key)
            env["RAY_TPU_VENV_KEY"] = pip_key or _renv.pip_env_key(pip)
        # fd-level stdio capture; the daemon's LogMonitor tails these and
        # pushes appended lines to the head (reference log_monitor.py)
        out, err, tag = worker_logs.open_worker_logs(
            self.session, tag=f"{self.node_id.hex()[:6]}-{os.urandom(3).hex()}",
            subdir=f"node-{self.node_id.hex()[:12]}")
        env["RAY_TPU_LOG_TAG"] = tag
        env.setdefault("PYTHONUNBUFFERED", "1")
        with out, err:
            proc = subprocess.Popen(
                [python, "-m", "ray_tpu.core.worker_main"],
                env=env, stdout=out, stderr=err)
        self.procs[proc.pid] = proc
        return proc.pid

    async def _kill_worker(self, pid):
        proc = self.procs.pop(pid, None)
        try:
            if proc is not None:
                proc.kill()
            else:
                os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        return True

    async def _adopt_object(self, meta):
        """Track an object the head can't see (isolation/multi-host):
        capacity accounting + watermark spilling live with this node."""
        if self.store is not None:
            try:
                self.store.adopt(meta)
            except Exception:
                pass
        return True

    async def _free_object(self, meta):
        """Head-forwarded free of an object living on this node."""
        self.local_objects.pop(meta.object_id.binary(), None)
        if self.pull is not None:
            self.pull.drop(meta.object_id)
        if self.store is not None:
            try:
                self.store.free(meta)
            except Exception:
                pass
        return True

    async def _shutdown_node(self):
        self.stopping.set()
        return True

    async def run(self):
        await self.stopping.wait()
        if getattr(self, "_log_monitor", None) is not None:
            self._log_monitor.stop()
        for proc in self.procs.values():
            try:
                proc.kill()
            except ProcessLookupError:
                pass
        if self._sched_server is not None:
            await self._sched_server.stop()
        if self._data_server is not None:
            await self._data_server.stop()
        if self.pull is not None:
            await self.pull.close()
        if self.store is not None:
            # node death takes its objects with it (reference: plasma dies
            # with the raylet); unlink what this store still maps
            self.store.shutdown()


async def amain(args):
    protocol.enable_eager_tasks(asyncio.get_running_loop())
    host, port_s = args.address.rsplit(":", 1)
    daemon = NodeDaemon(
        host, int(port_s), num_cpus=args.num_cpus,
        num_tpu_chips=args.num_tpu_chips,
        resources=json.loads(args.resources) if args.resources else None,
        labels=json.loads(args.labels) if args.labels else None,
        max_workers=args.max_workers)
    await daemon.start()
    print(f"RAY_TPU_NODE_ID={daemon.node_id.hex()}", flush=True)
    await daemon.run()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--address", required=True, help="head host:port")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpu-chips", type=int, default=None)
    p.add_argument("--resources", type=str, default=None)
    p.add_argument("--labels", type=str, default=None)
    p.add_argument("--max-workers", type=int, default=None)
    args = p.parse_args()
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
