"""ctypes bindings for the native arena object store (ray_tpu/_native).

Builds `libraytpu_store.so` on demand (make, cached) and exposes `Arena`:
one shm segment per node holding every object, with the C++ side owning the
allocator/table/LRU and Python mapping the same segment via `mmap` for
zero-copy payload views. Falls back cleanly (`Arena.available() -> False`)
when no toolchain is present; callers then use per-object segments.

Reference counterpart: the plasma client (`src/ray/object_manager/plasma/
client.h`) — except create/seal/get here are in-process calls on shared
state, not socket round-trips.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import subprocess
import threading
from typing import Dict, List, Optional, Tuple

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "_native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libraytpu_store.so")
_lib = None
_lib_lock = threading.Lock()
ID_LEN = 16


def _build_and_load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        sources = [os.path.join(_NATIVE_DIR, f)
                   for f in os.listdir(_NATIVE_DIR) if f.endswith(".cc")]
        if not os.path.exists(_LIB_PATH) or any(
                os.path.getmtime(_LIB_PATH) < os.path.getmtime(s)
                for s in sources):
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                               capture_output=True, timeout=120)
            except Exception:
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.rtpu_store_create.restype = ctypes.c_void_p
        lib.rtpu_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rtpu_store_attach.restype = ctypes.c_void_p
        lib.rtpu_store_attach.argtypes = [ctypes.c_char_p]
        lib.rtpu_store_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.rtpu_store_alloc.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.rtpu_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_store_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int]
        lib.rtpu_store_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_int]
        lib.rtpu_store_evict_candidates.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_int]
        lib.rtpu_store_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64)]
        lib.rtpu_store_data_offset.restype = ctypes.c_uint64
        lib.rtpu_store_data_offset.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _build_and_load() is not None


class ArenaError(Exception):
    pass


class ObjectExistsError(ArenaError):
    pass


class ArenaFullError(ArenaError):
    pass


class Arena:
    """A created-or-attached node arena. Thread-safe (C side locks)."""

    def __init__(self, name: str, handle, lib):
        self.name = name
        self._h = handle
        self._lib = lib
        # map the same segment for python-side payload access
        fd = os.open(f"/dev/shm/{name}", os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, 0)
        finally:
            os.close(fd)
        self._view = memoryview(self._mm)
        self._pins: Dict[bytes, int] = {}
        self._pin_lock = threading.Lock()
        self._closed = False

    # -- lifecycle ---------------------------------------------------------
    @classmethod
    def create(cls, name: str, capacity: int) -> "Arena":
        lib = _build_and_load()
        if lib is None:
            raise ArenaError("native store unavailable")
        h = lib.rtpu_store_create(name.encode(), capacity)
        if not h:
            raise ArenaError(f"failed to create arena {name}")
        return cls(name, h, lib)

    @classmethod
    def attach(cls, name: str) -> "Arena":
        lib = _build_and_load()
        if lib is None:
            raise ArenaError("native store unavailable")
        h = lib.rtpu_store_attach(name.encode())
        if not h:
            raise ArenaError(f"failed to attach arena {name}")
        return cls(name, h, lib)

    def close(self, unlink: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        with self._pin_lock:
            for oid, n in list(self._pins.items()):
                for _ in range(n):
                    self._lib.rtpu_store_release(self._h, oid)
            self._pins.clear()
        try:
            self._view.release()
            self._mm.close()
        except BufferError:
            pass  # live views alias the mapping; keep it until GC
        self._lib.rtpu_store_close(self._h, 1 if unlink else 0)
        self._h = None

    # -- object ops --------------------------------------------------------
    def create_buffer(self, oid: bytes, size: int) -> memoryview:
        """Allocate an unsealed object; returns a writable view of its bytes."""
        off = ctypes.c_uint64()
        rc = self._lib.rtpu_store_alloc(self._h, oid, size, ctypes.byref(off))
        if rc == -2:
            raise ObjectExistsError(oid.hex())
        if rc in (-1, -3):
            raise ArenaFullError(f"arena {self.name} cannot fit {size} bytes")
        if rc != 0:
            raise ArenaError(f"alloc failed rc={rc}")
        return self._view[off.value:off.value + size]

    def seal(self, oid: bytes) -> None:
        if self._lib.rtpu_store_seal(self._h, oid) != 0:
            raise ArenaError(f"seal: unknown object {oid.hex()}")

    def get(self, oid: bytes, pin: bool = True) -> memoryview:
        """Zero-copy read view; pins the object until release()/close()."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.rtpu_store_get(self._h, oid, ctypes.byref(off),
                                      ctypes.byref(size), 1 if pin else 0)
        if rc == -1:
            raise KeyError(oid.hex())
        if rc == -3:
            raise ArenaError(f"object {oid.hex()} not sealed")
        if rc != 0:
            raise ArenaError(f"get failed rc={rc}")
        if pin:
            with self._pin_lock:
                self._pins[oid] = self._pins.get(oid, 0) + 1
        return self._view[off.value:off.value + size.value]

    def release(self, oid: bytes) -> None:
        with self._pin_lock:
            if self._pins.get(oid, 0) <= 0:
                return
            self._pins[oid] -= 1
            if self._pins[oid] == 0:
                del self._pins[oid]
        self._lib.rtpu_store_release(self._h, oid)

    def delete(self, oid: bytes, force: bool = False) -> bool:
        return self._lib.rtpu_store_delete(self._h, oid, 1 if force else 0) == 0

    def contains(self, oid: bytes) -> bool:
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        return self._lib.rtpu_store_get(self._h, oid, ctypes.byref(off),
                                        ctypes.byref(size), 0) == 0

    def evict_candidates(self, needed: int, max_out: int = 256) -> List[bytes]:
        buf = ctypes.create_string_buffer(max_out * ID_LEN)
        n = self._lib.rtpu_store_evict_candidates(self._h, needed, buf, max_out)
        if n < 0:
            return []
        raw = buf.raw
        return [raw[i * ID_LEN:(i + 1) * ID_LEN] for i in range(n)]

    def stats(self) -> Tuple[int, int, int]:
        used = ctypes.c_uint64()
        cap = ctypes.c_uint64()
        cnt = ctypes.c_uint64()
        self._lib.rtpu_store_stats(self._h, ctypes.byref(used),
                                   ctypes.byref(cap), ctypes.byref(cnt))
        return used.value, cap.value, cnt.value
