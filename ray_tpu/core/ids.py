"""Opaque identifiers for tasks/actors/objects/nodes.

The reference uses structured binary IDs with embedded job/actor indices
(`src/ray/common/id.h`, `id_specification.md`). We keep flat 16-byte random
ids — the ownership metadata lives in the tables instead — plus a readable
hex repr for logs.
"""

from __future__ import annotations

import os


class BaseID:
    __slots__ = ("_bin",)
    _size = 16

    def __init__(self, binary: bytes):
        assert isinstance(binary, bytes) and len(binary) == self._size, binary
        self._bin = binary

    @classmethod
    def generate(cls):
        return cls(os.urandom(cls._size))

    @classmethod
    def from_hex(cls, h: str):
        return cls(bytes.fromhex(h))

    def binary(self) -> bytes:
        return self._bin

    def hex(self) -> str:
        return self._bin.hex()

    def __eq__(self, other):
        return type(other) is type(self) and other._bin == self._bin

    def __hash__(self):
        return hash((type(self).__name__, self._bin))

    def __repr__(self):
        return f"{type(self).__name__}({self._bin.hex()[:12]})"

    def __reduce__(self):
        return (type(self), (self._bin,))


class ObjectID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ActorID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class JobID(BaseID):
    _size = 4


class PlacementGroupID(BaseID):
    pass
