"""Control-plane flight recorder: always-on RPC instrumentation.

Every process (head, node daemon, driver, worker) registers ONE
interposer through `protocol.add_rpc_interposer` that turns the
existing req/push/rep event stream into `util/metrics` series:

- ``rpc_requests_total{method, role, kind}``  — counter per outbound
  request/push;
- ``rpc_latency_seconds{method, role}``       — histogram of
  request→reply latency (the interposer's "rep" events carry
  ``duration_s`` measured inside the protocol layer).

``role`` names the control-plane edge, derived from the connection name
plus which process we are: ``client_head`` (driver/worker → head),
``client_daemon`` (driver → node-daemon scheduler), ``client_worker``
(driver → leased/direct worker), ``daemon_head`` (node daemon → head),
``head_peer`` (head → daemon/worker over its accepted connections),
``data`` (bulk object pulls).

This is passive telemetry riding connections that already exist — it
adds zero RPCs anywhere. Daemons cannot push snapshots through the KV
pusher (they hold no CoreClient), so their registry piggybacks on the
`resource_view_delta` gossip instead (see `core/node_main.py`); drivers
and workers push through the normal metrics pusher; the head's registry
is read in-process by the dashboard's `/metrics` scrape.

Reference: the production pattern in "Collective Communication for
100k+ GPUs" (arXiv:2510.20171) — always-on lightweight telemetry on the
control plane, not bolted-on sampling.
"""

from __future__ import annotations

import time
from typing import Optional

from ray_tpu.core import config as _config
from ray_tpu.core import protocol

# latency buckets biased to control-plane RPC scales (100µs .. 10s)
RPC_LATENCY_BOUNDARIES = [
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0]

_installed: Optional[str] = None   # process role once installed
_interposer = None


def _role_of(conn_name: str, process_role: str) -> str:
    if conn_name == "head":
        # the head names its accepted connections "head" too; requests it
        # issues through them (spawn_worker, health_ping) are head→peer
        return "head_peer" if process_role == "head" else "client_head"
    if conn_name == "node":
        return "daemon_head"
    if conn_name.startswith("sched"):
        return "client_daemon"
    if conn_name.startswith(("lease-", "direct-", "dev-")):
        return "client_worker"
    if conn_name.startswith(("data-", "node-data", "head-data")):
        return "data"
    return conn_name or "other"


def install(process_role: str) -> bool:
    """Register the RPC metrics interposer for this process (idempotent).

    `process_role`: "head" | "daemon" | "driver" | "worker" — only used
    to disambiguate the head's outbound requests; the connection name
    carries the rest.
    """
    global _installed, _interposer
    if _installed is not None:
        return False
    if not _config.get("rpc_metrics"):
        return False
    from ray_tpu.util import metrics

    requests = metrics.Counter(
        "rpc_requests_total",
        "Outbound control-plane RPCs by method and edge role",
        tag_keys=("method", "role", "kind"))
    latency = metrics.Histogram(
        "rpc_latency_seconds",
        "Control-plane request round-trip latency by method and edge role",
        boundaries=RPC_LATENCY_BOUNDARIES,
        tag_keys=("method", "role"))
    chaos = metrics.Counter(
        "chaos_injected_total",
        "Faults injected by the chaos plane (protocol.configure_chaos) "
        "by method and fault kind",
        tag_keys=("method", "kind"))

    def _record(name, kind, method, **extra):
        if kind == "chaos":
            chaos.inc(tags={"method": method,
                            "kind": extra.get("chaos_kind", "?")})
            return
        role = _role_of(name, process_role)
        if kind == "rep":
            latency.observe(extra.get("duration_s", 0.0),
                            tags={"method": method, "role": role})
        else:
            requests.inc(tags={"method": method, "role": role, "kind": kind})

    protocol.add_rpc_interposer(_record)
    _installed = process_role
    _interposer = _record
    return True


def uninstall() -> None:
    """Remove the interposer (tests)."""
    global _installed, _interposer
    if _interposer is not None:
        protocol.remove_rpc_interposer(_interposer)
    _installed = None
    _interposer = None


def installed_role() -> Optional[str]:
    return _installed


class EventRing:
    """Bounded ring of flight-recorder events with monotonic sequence
    numbers and drain-for-send — the node daemon's per-node buffer
    piggybacked on resource_view_delta gossip. Delivery reliability
    lives one level up: drained events wait in the daemon's ack-tracked
    pending buffer until the head acknowledges their seq (see
    node_main._gossip_send)."""

    def __init__(self, cap: int):
        from collections import deque

        self.cap = int(cap)
        self._events: "deque[dict]" = deque(maxlen=self.cap)
        self._seq = 0
        self.dropped = 0

    def record(self, kind: str, **detail) -> dict:
        self._seq += 1
        if len(self._events) == self.cap:
            self.dropped += 1
        ev = {"seq": self._seq, "ts": time.time(), "kind": kind, **detail}
        self._events.append(ev)
        return ev

    def drain(self, limit: Optional[int] = None) -> list:
        """Pop up to `limit` oldest events (all when limit is None)."""
        out = []
        n = len(self._events) if limit is None else min(limit,
                                                       len(self._events))
        for _ in range(n):
            out.append(self._events.popleft())
        return out

