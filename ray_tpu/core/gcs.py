"""Head process: cluster control plane + single-node scheduler + worker pool.

Capability-equivalent of the reference's GCS (`src/ray/gcs/gcs_server/`) fused
with the raylet's scheduling/worker-pool role (`src/ray/raylet/`) for the
single-node case: node/actor/object/KV tables, pubsub, resource-based task
scheduling with dependency-aware dispatch, worker lifecycle, actor restarts,
placement groups. Multi-node support hangs off the same tables (a remote node
daemon registers like a worker pool with its own resources).

Design differences from the reference (deliberate, TPU-first):
- steady-state actor calls NEVER pass through here (direct worker<->worker
  connections, like the reference's core-worker gRPC) — the head only does
  placement, restarts, and failure pubsub;
- the object store is per-object shm segments (store.py) with head-side
  accounting; device arrays stay in per-actor device stores (collective layer)
  and only metadata flows through the head.
"""

from __future__ import annotations

import asyncio
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu.core import protocol
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, PlacementGroupID, TaskID, WorkerID
from ray_tpu.core.store import ObjectMeta, SharedMemoryStore


class WorkerInfo:
    def __init__(self, worker_id: WorkerID, conn: protocol.Connection, pid: int,
                 port: int, is_driver: bool):
        self.worker_id = worker_id
        self.conn = conn
        self.pid = pid
        self.port = port  # direct-call server port
        self.is_driver = is_driver
        self.running_task: Optional[TaskID] = None
        self.actor_id: Optional[ActorID] = None
        self.blocked = False
        self.acquired: Dict[str, float] = {}
        self.acquired_pg = None  # PlacementGroupID the resources came from
        self.proc: Optional[subprocess.Popen] = None
        self.current_record = None


class ActorInfo:
    def __init__(self, actor_id: ActorID, spec: dict):
        self.actor_id = actor_id
        self.spec = spec                  # serialized class, args, options
        self.state = "PENDING"            # PENDING/ALIVE/RESTARTING/DEAD
        self.worker: Optional[WorkerInfo] = None
        self.address: Optional[Tuple[str, int]] = None
        self.restarts_left = spec["options"].get("max_restarts", 0)
        self.ready_event = asyncio.Event()
        self.death_cause: Optional[str] = None


class TaskRecord:
    def __init__(self, spec: dict, submitter: WorkerInfo):
        self.spec = spec
        self.task_id: TaskID = spec["task_id"]
        self.submitter = submitter
        self.retries_left = spec["options"].get("max_retries", 3)
        self.pending_deps: Set[ObjectID] = set()


class PlacementGroupInfo:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[dict], strategy: str,
                 name: str = ""):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self.state = "PENDING"
        self.ready_event = asyncio.Event()
        self.capacity: Dict[str, float] = {}   # total reservation (set on CREATED)
        self.available: Dict[str, float] = {}  # unclaimed portion of it


class Head:
    def __init__(self, session: str, num_cpus: Optional[float] = None,
                 resources: Optional[dict] = None, num_tpu_chips: Optional[int] = None,
                 object_store_bytes: int = 2 << 30, max_workers: Optional[int] = None,
                 labels: Optional[dict] = None):
        self.session = session
        self.node_id = NodeID.generate()
        from ray_tpu.core.resources import node_resources

        self.total_resources = node_resources(num_cpus, num_tpu_chips, resources)
        self.available = dict(self.total_resources)
        self.labels = labels or {}
        self.max_workers = max_workers or max(int(self.total_resources.get("CPU", 4)) * 2, 8)

        self.store = SharedMemoryStore(session, capacity_bytes=object_store_bytes)
        self.workers: Dict[WorkerID, WorkerInfo] = {}
        self.idle: List[WorkerInfo] = []
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}
        self.objects: Dict[ObjectID, ObjectMeta] = {}
        self.object_waiters: Dict[ObjectID, List[asyncio.Future]] = {}
        self.kv: Dict[Tuple[str, bytes], bytes] = {}
        self.pgs: Dict[PlacementGroupID, PlacementGroupInfo] = {}
        self.queue: List[TaskRecord] = []
        self.dep_index: Dict[ObjectID, List[TaskRecord]] = {}
        self.subscribers: Dict[str, List[protocol.Connection]] = {}
        self.port: Optional[int] = None
        self._server: Optional[protocol.Server] = None
        self._starting_workers = 0
        self._shutdown = False
        self.job_counter = 0
        self.start_time = time.time()
        self._spawned: Dict[int, subprocess.Popen] = {}

    # ------------------------------------------------------------------ rpc
    def _handlers(self, conn_state: dict):
        async def register_worker(worker_id, pid, port, is_driver):
            w = WorkerInfo(WorkerID(worker_id), conn_state["conn"], pid, port, is_driver)
            proc = self._spawned.pop(pid, None)
            w.proc = proc
            self.workers[w.worker_id] = w
            conn_state["worker"] = w
            if not is_driver:
                self.idle.append(w)
                self._starting_workers = max(0, self._starting_workers - 1)
                self._kick()
            return {"node_id": self.node_id.binary(), "session": self.session,
                    "resources": self.total_resources, "labels": self.labels}

        async def submit_task(spec):
            w = conn_state["worker"]
            rec = TaskRecord(spec, w)
            self._enqueue(rec)
            return True

        async def create_actor(spec):
            actor_id = ActorID(spec["actor_id"])
            name = spec["options"].get("name")
            key = None
            if name:
                key = (spec["options"].get("namespace", "default"), name)
                if key in self.named_actors:
                    existing = self.actors[self.named_actors[key]]
                    if existing.state != "DEAD":
                        if spec["options"].get("get_if_exists"):
                            return {"actor_id": self.named_actors[key].binary()}
                        raise ValueError(f"actor name {name!r} already taken")
            info = ActorInfo(actor_id, spec)
            self.actors[actor_id] = info
            if key is not None:
                self.named_actors[key] = actor_id
            self._schedule_actor(info)
            return {"actor_id": actor_id.binary()}

        async def wait_actor(actor_id):
            info = self.actors[ActorID(actor_id)]
            await info.ready_event.wait()
            if info.state == "DEAD":
                return {"state": "DEAD", "death_cause": info.death_cause}
            return {"state": info.state, "address": info.address}

        async def get_actor_address(actor_id):
            info = self.actors.get(ActorID(actor_id))
            if info is None:
                return {"state": "DEAD", "death_cause": "actor not found"}
            if info.state in ("PENDING", "RESTARTING"):
                await info.ready_event.wait()
            if info.state == "DEAD":
                return {"state": "DEAD", "death_cause": info.death_cause}
            return {"state": info.state, "address": info.address}

        async def get_named_actor(name, namespace):
            key = (namespace, name)
            actor_id = self.named_actors.get(key)
            if actor_id is None or self.actors[actor_id].state == "DEAD":
                return None
            info = self.actors[actor_id]
            meta = {"actor_id": actor_id.binary(),
                    "methods": info.spec.get("methods", {})}
            return meta

        async def kill_actor(actor_id, no_restart=True):
            info = self.actors.get(ActorID(actor_id))
            if info is None:
                return False
            if no_restart:
                info.restarts_left = 0
            if info.worker is not None:
                self._terminate_worker(info.worker)
            else:
                self._mark_actor_dead(info, "killed")
            return True

        async def put_meta(meta):
            self._seal(meta)
            return True

        async def get_meta(object_id, timeout=None):
            oid = ObjectID(object_id)
            meta = self.objects.get(oid)
            if meta is not None:
                return meta
            fut = asyncio.get_running_loop().create_future()
            self.object_waiters.setdefault(oid, []).append(fut)
            if timeout is None:
                return await fut
            try:
                return await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                return None

        async def wait_objects(object_ids, num_returns, timeout):
            ids = [ObjectID(b) for b in object_ids]
            num_returns = min(num_returns, len(ids))
            deadline = None if timeout is None else time.monotonic() + timeout

            def ready():
                return [i for i, oid in enumerate(ids) if oid in self.objects]

            while len(ready()) < num_returns:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    break
                futs = []
                for oid in ids:
                    if oid not in self.objects:
                        fut = asyncio.get_running_loop().create_future()
                        self.object_waiters.setdefault(oid, []).append(fut)
                        futs.append(fut)
                if not futs:
                    break
                try:
                    await asyncio.wait(futs, timeout=remaining,
                                       return_when=asyncio.FIRST_COMPLETED)
                finally:
                    for fut in futs:
                        fut.cancel()
            return ready()

        async def free_objects(object_ids):
            for b in object_ids:
                meta = self.objects.pop(ObjectID(b), None)
                if meta is not None:
                    self.store.free(meta)
            return True

        async def kv_put(ns, key, value, overwrite=True):
            k = (ns, key)
            if not overwrite and k in self.kv:
                return False
            self.kv[k] = value
            return True

        async def kv_get(ns, key):
            return self.kv.get((ns, key))

        async def kv_del(ns, key):
            return self.kv.pop((ns, key), None) is not None

        async def kv_keys(ns, prefix):
            return [k for (n, k) in self.kv if n == ns and k.startswith(prefix)]

        async def create_pg(pg_id, bundles, strategy, name):
            pgid = PlacementGroupID(pg_id)
            pg = PlacementGroupInfo(pgid, bundles, strategy, name)
            self.pgs[pgid] = pg
            self._try_reserve_pg(pg)
            return True

        async def wait_pg(pg_id, timeout=None):
            pg = self.pgs.get(PlacementGroupID(pg_id))
            if pg is None:
                return {"state": "REMOVED"}
            if timeout is not None:
                try:
                    await asyncio.wait_for(pg.ready_event.wait(), timeout)
                except asyncio.TimeoutError:
                    pass
            else:
                await pg.ready_event.wait()
            return {"state": pg.state}

        async def remove_pg(pg_id):
            pg = self.pgs.pop(PlacementGroupID(pg_id), None)
            if pg is not None and pg.state == "CREATED":
                # return only the unclaimed portion; in-use resources flow back
                # to the node ledger when their tasks release (pg is gone then)
                for res, amt in pg.available.items():
                    self.available[res] = self.available.get(res, 0) + amt
                self._kick()
            return True

        async def blocked(value):
            w = conn_state.get("worker")
            if w is not None and w.blocked != value:
                w.blocked = value
                if value:
                    self._release(w, cpu_only=True)
                self._kick()
            return True

        async def subscribe(channel):
            self.subscribers.setdefault(channel, []).append(conn_state["conn"])
            return True

        async def cluster_info():
            return {
                "node_id": self.node_id.binary(),
                "session": self.session,
                "total_resources": self.total_resources,
                "available_resources": self.available,
                "labels": self.labels,
                "num_workers": len(self.workers),
                "actors": {a.hex(): info.state for a, info in self.actors.items()},
                "uptime": time.time() - self.start_time,
            }

        async def job_counter_next():
            self.job_counter += 1
            return self.job_counter

        async def list_state(kind):
            return self._list_state(kind)

        async def task_done(task_id):
            w = conn_state.get("worker")
            if w is not None:
                self.notify_task_done(w)
            return True

        async def actor_ready(actor_id, address):
            info = self.actors.get(ActorID(actor_id))
            if info is not None:
                self.notify_actor_ready(info, address)
            return True

        async def actor_creation_failed(actor_id, cause):
            info = self.actors.get(ActorID(actor_id))
            if info is not None:
                w = info.worker
                info.restarts_left = 0  # constructor errors are not retried
                self._mark_actor_dead(info, f"creation failed: {cause}")
                if w is not None:
                    info.worker = None
                    w.actor_id = None
                    self._release(w)
                    if w not in self.idle:
                        self.idle.append(w)
                    self._kick()
            return True

        import inspect

        return {k: v for k, v in locals().items() if inspect.iscoroutinefunction(v)}

    # ---------------------------------------------------------------- sched
    def _enqueue(self, rec: TaskRecord) -> None:
        for dep in rec.spec.get("deps", []):
            oid = ObjectID(dep)
            if oid not in self.objects:
                rec.pending_deps.add(oid)
                self.dep_index.setdefault(oid, []).append(rec)
        self.queue.append(rec)
        self._kick()

    def _seal(self, meta: ObjectMeta) -> None:
        existing = self.objects.get(meta.object_id)
        if existing is not None:
            # objects are immutable: first seal wins (a racing retry must not
            # replace a good value, especially not with its own error)
            self.store.free(meta)
            return
        self.objects[meta.object_id] = meta
        if meta.kind == "shm":
            self.store.adopt(meta)  # accounting + LRU/spill tracking
        for fut in self.object_waiters.pop(meta.object_id, []):
            if not fut.done():
                fut.set_result(meta)
        for rec in self.dep_index.pop(meta.object_id, []):
            rec.pending_deps.discard(meta.object_id)
        self._kick()

    def _fits(self, resources: Dict[str, float]) -> bool:
        return all(self.available.get(r, 0) >= amt - 1e-9 for r, amt in resources.items())

    def _pg_for(self, options: dict) -> Optional[PlacementGroupInfo]:
        pgb = options.get("placement_group")
        return self.pgs.get(PlacementGroupID(pgb)) if pgb else None

    @staticmethod
    def _fits_pg(pg: PlacementGroupInfo, resources: Dict[str, float]) -> bool:
        return pg.state == "CREATED" and all(
            pg.available.get(r, 0) >= amt - 1e-9 for r, amt in resources.items())

    def _acquire(self, w: WorkerInfo, resources: Dict[str, float],
                 pg: Optional[PlacementGroupInfo] = None) -> None:
        ledger = pg.available if pg is not None else self.available
        for r, amt in resources.items():
            ledger[r] = ledger.get(r, 0) - amt
        w.acquired = dict(resources)
        w.acquired_pg = pg.pg_id if pg is not None else None

    def _release(self, w: WorkerInfo, cpu_only: bool = False) -> None:
        pg = self.pgs.get(w.acquired_pg) if getattr(w, "acquired_pg", None) else None
        # if the pg was removed while the work ran, resources return to the node
        ledger = pg.available if pg is not None else self.available
        for r, amt in list(w.acquired.items()):
            if cpu_only and r != "CPU":
                continue
            ledger[r] = ledger.get(r, 0) + amt
            del w.acquired[r]
        if not w.acquired:
            w.acquired_pg = None

    def _kick(self) -> None:
        """Dispatch as many queued tasks as possible; spawn workers if useful."""
        if self._shutdown:
            return
        self._retry_pending_pgs()
        still_queued: List[TaskRecord] = []
        for rec in self.queue:
            if rec.pending_deps:
                still_queued.append(rec)
                continue
            resources = rec.spec["options"].get("resources", {"CPU": 1})
            if rec.spec["options"].get("placement_group"):
                pg = self._pg_for(rec.spec["options"])
                if pg is None:
                    self._fail_task(rec, "placement group was removed")
                    continue
                if not self._fits_pg(pg, resources) or not self.idle:
                    still_queued.append(rec)
                    continue
            else:
                pg = None
                if not self._fits(resources) or not self.idle:
                    still_queued.append(rec)
                    continue
            w = self.idle.pop()
            self._acquire(w, resources, pg)
            w.running_task = rec.task_id
            w.current_record = rec
            w.conn.push("exec_task", spec=rec.spec)
        self.queue = still_queued
        # Pending actors also need workers.
        for info in self.actors.values():
            if info.state in ("PENDING", "RESTARTING") and info.worker is None:
                self._schedule_actor(info)
        demand = len([r for r in self.queue if not r.pending_deps]) + len(
            [a for a in self.actors.values()
             if a.state in ("PENDING", "RESTARTING") and a.worker is None])
        can_start = (self.max_workers - len([w for w in self.workers.values()
                                             if not w.is_driver]) - self._starting_workers)
        for _ in range(min(demand - len(self.idle) - self._starting_workers, can_start)):
            self._spawn_worker()

    def _schedule_actor(self, info: ActorInfo) -> None:
        resources = info.spec["options"].get("resources", {"CPU": 0})
        pg = self._pg_for(info.spec["options"])
        if info.spec["options"].get("placement_group") and pg is None:
            self._mark_actor_dead(info, "placement group was removed")
            return
        fits = self._fits_pg(pg, resources) if pg else self._fits(resources)
        if not self.idle or not fits:
            self._maybe_spawn_for_demand()
            return
        w = self.idle.pop()
        self._acquire(w, resources, pg)
        w.actor_id = info.actor_id
        info.worker = w
        w.conn.push("start_actor", spec=info.spec)

    def _maybe_spawn_for_demand(self) -> None:
        alive = len([w for w in self.workers.values() if not w.is_driver])
        if alive + self._starting_workers < self.max_workers:
            self._spawn_worker()

    # -------------------------------------------------------------- workers
    def _spawn_worker(self) -> None:
        self._starting_workers += 1
        from ray_tpu.core.resources import strip_device_env

        env = strip_device_env(dict(os.environ))
        env["RAY_TPU_HEAD_PORT"] = str(self.port)
        env["RAY_TPU_SESSION"] = self.session
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.worker_main"],
            env=env, stdout=None, stderr=None)
        self._spawned[proc.pid] = proc

    def _on_worker_disconnect(self, w: WorkerInfo) -> None:
        self.workers.pop(w.worker_id, None)
        if w in self.idle:
            self.idle.remove(w)
        self._release(w)
        rec = getattr(w, "current_record", None)
        if rec is not None and w.running_task is not None:
            if rec.retries_left > 0:
                rec.retries_left -= 1
                rec.pending_deps = set()
                self._enqueue(rec)
            else:
                self._fail_task(rec, f"worker {w.worker_id} died (pid {w.pid})")
        if w.actor_id is not None:
            info = self.actors.get(w.actor_id)
            if info is not None and info.state != "DEAD":
                info.worker = None
                info.address = None
                if info.restarts_left != 0:
                    if info.restarts_left > 0:
                        info.restarts_left -= 1
                    info.state = "RESTARTING"
                    info.ready_event = asyncio.Event()
                    self._publish("actor_state", {"actor_id": w.actor_id.binary(),
                                                  "state": "RESTARTING"})
                    self._schedule_actor(info)
                else:
                    self._mark_actor_dead(info, f"worker died (pid {w.pid})")
        if w.is_driver:
            pass  # job cleanup: objects are session-scoped in round 1
        self._kick()

    def _mark_actor_dead(self, info: ActorInfo, cause: str) -> None:
        info.state = "DEAD"
        info.death_cause = cause
        info.ready_event.set()
        self._publish("actor_state", {"actor_id": info.actor_id.binary(),
                                      "state": "DEAD", "cause": cause})

    def _terminate_worker(self, w: WorkerInfo) -> None:
        try:
            if w.proc is not None:
                w.proc.kill()
            else:
                os.kill(w.pid, 9)
        except ProcessLookupError:
            pass

    def _fail_task(self, rec: TaskRecord, cause: str) -> None:
        from ray_tpu.core import serialization
        from ray_tpu.core.exceptions import WorkerCrashedError

        err = serialization.serialize(WorkerCrashedError(cause))
        for rid in rec.spec["return_ids"]:
            meta = self.store.put_serialized(ObjectID(rid), err)
            meta.error = True
            self._seal(meta)

    def _publish(self, channel: str, msg: dict) -> None:
        for conn in self.subscribers.get(channel, []):
            if not conn.closed:
                conn.push("pubsub", channel=channel, msg=msg)

    def _retry_pending_pgs(self) -> None:
        for pg in self.pgs.values():
            if pg.state == "PENDING":
                self._try_reserve_pg(pg)

    # ------------------------------------------------------------------ pgs
    def _try_reserve_pg(self, pg: PlacementGroupInfo) -> None:
        need: Dict[str, float] = {}
        for bundle in pg.bundles:
            for r, amt in bundle.items():
                need[r] = need.get(r, 0) + amt
        if self._fits(need):
            for r, amt in need.items():
                self.available[r] -= amt
            pg.capacity = dict(need)
            pg.available = dict(need)
            pg.state = "CREATED"
            pg.ready_event.set()
        # else stays PENDING; re-tried on resource release (single-node round 1)

    # ---------------------------------------------------------------- state
    def _list_state(self, kind: str):
        if kind == "actors":
            return [{"actor_id": a.hex(), "state": i.state,
                     "name": i.spec["options"].get("name"),
                     "restarts_left": i.restarts_left}
                    for a, i in self.actors.items()]
        if kind == "workers":
            return [{"worker_id": w.hex(), "pid": i.pid, "is_driver": i.is_driver,
                     "actor": i.actor_id.hex() if i.actor_id else None,
                     "task": i.running_task.hex() if i.running_task else None}
                    for w, i in self.workers.items()]
        if kind == "objects":
            return [{"object_id": o.hex(), "size": m.size, "kind": m.kind}
                    for o, m in self.objects.items()]
        if kind == "tasks":
            return [{"task_id": r.task_id.hex(),
                     "pending_deps": len(r.pending_deps)} for r in self.queue]
        if kind == "nodes":
            return [{"node_id": self.node_id.hex(), "resources": self.total_resources,
                     "available": self.available, "labels": self.labels,
                     "alive": True}]
        if kind == "placement_groups":
            return [{"pg_id": p.hex(), "state": g.state, "strategy": g.strategy,
                     "bundles": g.bundles} for p, g in self.pgs.items()]
        raise ValueError(f"unknown state kind {kind}")

    # --------------------------------------------------------------- server
    async def start(self, port: int = 0) -> int:
        def on_connect(conn: protocol.Connection):
            conn_state = {"conn": conn}
            conn.handlers.update(self._handlers(conn_state))
            orig_close = conn.on_close

            def on_close(c):
                if orig_close:
                    orig_close(c)
                w = conn_state.get("worker")
                if w is not None:
                    self._on_worker_disconnect(w)

            conn.on_close = on_close

        # handlers installed per-connection (they close over conn_state)
        self._server = protocol.Server({}, on_connect=on_connect, name="head")
        self.port = await self._server.start(port=port)
        # task completion wiring: workers push task_done
        return self.port

    def notify_task_done(self, w: WorkerInfo) -> None:
        w.running_task = None
        w.current_record = None
        self._release(w)
        if not w.is_driver and w.actor_id is None and w not in self.idle:
            self.idle.append(w)
        self._kick()

    def notify_actor_ready(self, info: ActorInfo, address) -> None:
        info.state = "ALIVE"
        info.address = tuple(address)
        info.ready_event.set()
        self._publish("actor_state", {"actor_id": info.actor_id.binary(),
                                      "state": "ALIVE"})

    async def stop(self) -> None:
        self._shutdown = True
        for w in list(self.workers.values()):
            if not w.is_driver:
                self._terminate_worker(w)
        if self._server:
            await self._server.stop()
        self.store.shutdown()
