"""Head process: cluster control plane + two-level scheduler + worker pools.

Capability-equivalent of the reference's GCS (`src/ray/gcs/gcs_server/`) plus
the scheduling half of the raylet (`src/ray/raylet/scheduling/
cluster_task_manager.cc:201`): node/actor/object/KV tables, pubsub,
resource-based task scheduling with dependency-aware dispatch, label
selectors, worker lifecycle, actor restarts, placement groups with
PACK/SPREAD/STRICT_* bundle placement across nodes.

Topology: the head owns the tables and the placement decisions; every node
(including the head's own) contributes a worker pool. Remote nodes run a thin
node daemon (`node_main.py`) that only spawns/kills local workers on request —
workers connect straight to the head, and steady-state actor traffic is
direct worker<->worker (reference's core-worker gRPC model, SURVEY §3.3).

Single-machine multi-node: exactly the reference's `cluster_utils.Cluster`
strategy (SURVEY §4.2) — N node daemons as local processes with fake
resource dicts exercise all distributed logic over real sockets.
"""

from __future__ import annotations

import asyncio
import os
import subprocess
import sys
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu.core import config as _config
from ray_tpu.core import object_directory as objdir
from ray_tpu.core import protocol
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, PlacementGroupID, TaskID, WorkerID
from ray_tpu.core.store import ObjectMeta, SharedMemoryStore


class NodeInfo:
    def __init__(self, node_id: NodeID, resources: Dict[str, float],
                 labels: Dict[str, str], conn: Optional[protocol.Connection],
                 max_workers: int, is_head: bool = False):
        self.node_id = node_id
        self.resources = dict(resources)
        self.available = dict(resources)
        self.labels = dict(labels)
        self.conn = conn              # None for the head-local node
        self.max_workers = max_workers
        self.is_head = is_head
        # (host, port) of the node's object data server; host None = "the
        # head's host" (clients substitute their known route to the head)
        self.data_addr = None
        # (host, port) of the node daemon's scheduler server — clients
        # route warm lease requests here directly (two-level scheduling);
        # None for the head's own node and for daemons predating the view
        self.sched_addr = None
        # gossiped node-daemon state (resource_view_delta): the daemon's
        # own version counter, its warm lease-pool idle count, and the
        # pool's per-shape composition (None until the daemon gossips one)
        self.view_version = 0
        self.pool_idle = 0
        self.pool_shapes = None
        # flight recorder: when the last delta arrived (feeds the
        # cluster_view_staleness_s gauge), the daemon's lifetime scheduler
        # counters, and its reported gossip health (view_age_s etc.)
        self.last_delta_ts = time.time()
        self.sched_stats: Dict[str, float] = {}
        self.gossip_health: Dict[str, float] = {}
        # partition tolerance: the daemon's gossiped live-lease count, the
        # highest flight-recorder event seq merged (duplicate deliveries of
        # un-acked batches are dropped below it), and the reconciliation
        # handshake state — False from every (re)registration until the
        # daemon's pool_reconcile report rebuilds this node's carve-outs
        self.pool_leased = 0
        self.fr_last_seq = 0
        self.reconciled = conn is None  # head-local node: nothing to do
        # interest-scoped view plane: None = legacy full-fanout; else
        # {"interest": [shard ids], "sent": {sid: version last pushed},
        #  "digest_ts": monotonic ts of the last digest refresh}
        self.view_sub: Optional[dict] = None
        self.pending_pool: Dict[WorkerID, dict] = {}  # claimed at register
        self.unadopted: Set["WorkerInfo"] = set()     # parked reconnectors
        self.alive = True
        self.idle: List["WorkerInfo"] = []
        self.workers: Set[WorkerID] = set()
        self.starting_workers = 0

    def fits(self, resources: Dict[str, float]) -> bool:
        return all(self.available.get(r, 0) >= amt - 1e-9
                   for r, amt in resources.items())

    def could_ever_fit(self, resources: Dict[str, float]) -> bool:
        return all(self.resources.get(r, 0) >= amt - 1e-9
                   for r, amt in resources.items())

    def matches_labels(self, selector: Optional[Dict[str, str]]) -> bool:
        from ray_tpu.core.resource_view import matches_labels

        return matches_labels(self.labels, selector)

    def utilization(self) -> float:
        fracs = [1 - self.available.get(r, 0) / t
                 for r, t in self.resources.items() if t > 0]
        return max(fracs) if fracs else 0.0


class WorkerInfo:
    def __init__(self, worker_id: WorkerID, conn: protocol.Connection, pid: int,
                 port: int, is_driver: bool, node_id: NodeID):
        self.worker_id = worker_id
        self.conn = conn
        self.pid = pid
        self.port = port  # direct-call server port
        self.is_driver = is_driver
        self.node_id = node_id
        self.running_task: Optional[TaskID] = None
        self.actor_id: Optional[ActorID] = None
        self.blocked = False
        self.acquired: Dict[str, float] = {}
        self.acquired_pg: Optional[PlacementGroupID] = None
        self.acquired_bundle: Optional[int] = None
        self.proc: Optional[subprocess.Popen] = None
        # pip-isolated workers run a venv interpreter; tasks whose
        # runtime_env carries the same pip_key route here exclusively
        self.venv_key: Optional[str] = None
        self.current_record = None
        self.retiring = False  # max_calls reached; exiting after current task
        self.host: Optional[str] = None  # peer host of the registration conn
        # lease protocol: WorkerID of the client this worker is leased to
        # for direct task pushes (None = scheduled by the head)
        self.leased_to: Optional[WorkerID] = None
        # two-level scheduling: True while this worker (and its resource
        # carve-out) belongs to its node daemon's lease pool — the head
        # never dispatches to it until the daemon releases it back.
        # pool_grant_seq keys the carve-out generation: a pool_release
        # must echo it, so duplicate/late releases of an older generation
        # are no-ops (epoch + seq keyed idempotence)
        self.pooled = False
        self.pool_grant_seq: Optional[int] = None
        # the node id the worker's registration named (survives the
        # fallback to head_node when its daemon is mid-reconnect)
        self.declared_node: Optional[NodeID] = None
        self.log_tag: Optional[str] = None  # stem of its log files


class ActorInfo:
    def __init__(self, actor_id: ActorID, spec: dict):
        self.actor_id = actor_id
        self.spec = spec                  # serialized class, args, options
        self.state = "PENDING"            # PENDING/ALIVE/RESTARTING/DEAD
        self.worker: Optional[WorkerInfo] = None
        self.address: Optional[Tuple[str, int]] = None
        self.restarts_left = spec["options"].get("max_restarts", 0)
        self.ready_event = asyncio.Event()
        self.death_cause: Optional[str] = None


class TaskRecord:
    def __init__(self, spec: dict, submitter: WorkerInfo):
        self.spec = spec
        self.task_id: TaskID = spec["task_id"]
        self.submitter = submitter
        self.retries_left = spec["options"].get("max_retries", 3)
        self.pending_deps: Set[ObjectID] = set()
        self.cancelled = False
        self.dispatch_ts: Optional[float] = None
        self.pinned: List[ObjectID] = []  # deps pinned while in flight


class TaskQueue:
    """Pending tasks bucketed by scheduling shape (resources + selector +
    PG + strategy). Identical shapes get identical placement verdicts while
    cluster state is unchanged, so the dispatcher stops scanning a bucket at
    its first non-dispatchable record — the reference ClusterTaskManager's
    per-class queueing, without which a deep queue makes every scheduling
    event O(queue) and pipelined submission collapses."""

    def __init__(self):
        self._shapes: "OrderedDict[tuple, deque]" = OrderedDict()
        self._len = 0

    @staticmethod
    def shape_of(rec: "TaskRecord") -> tuple:
        o = rec.spec["options"]
        sel = o.get("label_selector")
        sel_key = (tuple(sorted(
            (k, tuple(v) if isinstance(v, (list, tuple, set)) else str(v))
            for k, v in sel.items())) if sel else None)
        # same normalization as _try_dispatch: an EXPLICIT resources={} is a
        # zero-resource task and must not share a bucket with CPU:1 defaults
        res = o.get("resources", {"CPU": 1})
        return (tuple(sorted(res.items())), sel_key,
                o.get("placement_group"),
                o.get("placement_group_bundle_index"),
                o.get("scheduling_strategy", "hybrid"))

    def append(self, rec: "TaskRecord") -> None:
        key = self.shape_of(rec)
        dq = self._shapes.get(key)
        if dq is None:
            dq = self._shapes[key] = deque()
        dq.append(rec)
        self._len += 1

    def scan(self, dispatch) -> None:
        """One scheduling pass: per bucket, dispatch ready records until the
        first non-dispatchable one (same shape ⇒ same verdict until cluster
        state changes). `dispatch(rec, remaining)` returns None on success,
        else a block reason. Owns all length bookkeeping."""
        for key in list(self._shapes.keys()):
            dq = self._shapes.get(key)
            if dq is None:
                continue
            kept: deque = deque()   # dep-waiting records stepped over
            while dq:
                rec = dq[0]
                if rec.pending_deps:
                    kept.append(dq.popleft())
                    continue
                if dispatch(rec, len(dq)) is None:
                    dq.popleft()
                    self._len -= 1
                else:
                    break
            if kept:
                kept.extend(dq)
                self._shapes[key] = kept
            elif not dq:
                self._shapes.pop(key, None)

    def remove(self, rec: "TaskRecord") -> None:
        key = self.shape_of(rec)
        dq = self._shapes.get(key)
        if dq is None:
            return
        try:
            dq.remove(rec)
            self._len -= 1
        except ValueError:
            pass
        if not dq:
            del self._shapes[key]

    def __iter__(self):
        for dq in list(self._shapes.values()):
            yield from list(dq)

    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0


class GeneratorState:
    """Streaming-generator bookkeeping (reference: dynamic return refs +
    `_generator_backpressure_num_objects`, SURVEY §2.12b)."""

    def __init__(self, backpressure: int = 0):
        self.items: List[bytes] = []      # yielded object ids, in order
        self.delivered: Set[int] = set()  # indices handed to the consumer
        self.done = False
        self.released = False             # consumer dropped the generator
        self.backpressure = backpressure
        self.consumed = 0                 # highest index the consumer fetched
        self.consumer_waiters: List[asyncio.Future] = []
        self.producer_waiters: List[asyncio.Future] = []

    def wake(self, waiters: List[asyncio.Future]) -> None:
        for fut in waiters:
            if not fut.done():
                fut.set_result(None)
        waiters.clear()


class BundleState:
    def __init__(self, index: int, resources: Dict[str, float]):
        self.index = index
        self.resources = dict(resources)
        self.node_id: Optional[NodeID] = None
        self.available: Dict[str, float] = {}

    def fits(self, resources: Dict[str, float]) -> bool:
        return all(self.available.get(r, 0) >= amt - 1e-9
                   for r, amt in resources.items())


class PlacementGroupInfo:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[dict], strategy: str,
                 name: str = ""):
        self.pg_id = pg_id
        self.bundles = [BundleState(i, b) for i, b in enumerate(bundles)]
        self.strategy = strategy
        self.name = name
        self.state = "PENDING"
        self.ready_event = asyncio.Event()


class Head:
    def __init__(self, session: str, num_cpus: Optional[float] = None,
                 resources: Optional[dict] = None, num_tpu_chips: Optional[int] = None,
                 object_store_bytes: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 labels: Optional[dict] = None):
        self.session = session
        self.node_id = NodeID.generate()
        from ray_tpu.core.resources import node_labels, node_resources

        head_resources = node_resources(num_cpus, num_tpu_chips, resources)
        head_max = max_workers or max(int(head_resources.get("CPU", 4)) * 2, 8)
        self.head_node = NodeInfo(self.node_id, head_resources,
                                  {**node_labels(), **(labels or {})},
                                  conn=None, max_workers=head_max, is_head=True)
        self.nodes: Dict[NodeID, NodeInfo] = {self.node_id: self.head_node}

        from ray_tpu.core.store import default_store_bytes

        if object_store_bytes is None or object_store_bytes <= 0:
            # reference-parity: 30% of RAM capped by /dev/shm (node.py:1409)
            object_store_bytes = default_store_bytes()
        self.store = SharedMemoryStore(
            session, capacity_bytes=object_store_bytes, create_arena=True,
            namespace=(self.node_id.hex()[:8]
                       if _config.get("store_isolation")
                       and not _config.get("store_namespace")
                       else None))
        self.workers: Dict[WorkerID, WorkerInfo] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}
        self.objects: Dict[ObjectID, ObjectMeta] = {}
        self.object_waiters: Dict[ObjectID, List[asyncio.Future]] = {}
        self.kv: Dict[Tuple[str, bytes], bytes] = {}
        self.pgs: Dict[PlacementGroupID, PlacementGroupInfo] = {}
        self.queue = TaskQueue()
        self.dep_index: Dict[ObjectID, List[TaskRecord]] = {}
        self.generators: Dict[bytes, GeneratorState] = {}
        self.subscribers: Dict[str, List[protocol.Connection]] = {}
        self.port: Optional[int] = None
        self._server: Optional[protocol.Server] = None
        self._shutdown = False
        self.job_counter = 0
        self.start_time = time.time()
        self._spawned: Dict[int, subprocess.Popen] = {}
        # ring buffer of task lifecycle events (reference: task_event_buffer
        # → gcs_task_manager; feeds the state API + `timeline()`)
        from collections import OrderedDict, deque
        self.task_events: deque = deque(maxlen=20000)
        # flight recorder: merged per-node lease-lifecycle/gossip events
        # (piggybacked on resource_view_delta) + the head's own scheduler
        # counters — feeds list_lease_events/list_scheduler_stats and the
        # dashboard's /api/scheduler
        self.lease_events: deque = deque(
            maxlen=_config.get("flight_recorder_head_events"))
        # workload flight recorder: finished spans pushed by every
        # process (metrics_push for workers/drivers, resource_view_delta
        # gossip for daemons) keyed by span id — timeline(format="chrome")
        # merges them into one cross-process trace
        self.trace_spans: "OrderedDict[str, dict]" = OrderedDict()
        # parsed copy of each _metrics KV payload, decoded ONCE at push
        # arrival (the watchdog + /api/workloads + span extraction would
        # otherwise re-json.loads every process's snapshot on the event
        # loop several times per interval); entries die with their KV key
        self._metrics_parsed: Dict[bytes, list] = {}
        self._watchdog_state: dict = {}
        self._anomaly_counter = None
        self.sched_totals = {"head_grants": 0, "pool_acquires": 0,
                             "pool_releases": 0, "stale_epoch_rejects": 0,
                             "reconciles": 0,
                             # lineage recovery: objects re-sealed by
                             # re-running their producing task after every
                             # copy was lost; data_reconstructs counts the
                             # data library's stage/shuffle blocks
                             # (data_blocks_reconstructed_total on /metrics)
                             "reconstructs": 0, "data_reconstructs": 0}
        # epoch fencing: a cluster epoch stamped into cluster_view and
        # every grant/carve-out; daemons and clients tag pool/lease traffic
        # with the epoch they observed, and stale-epoch operations are
        # rejected and routed into reconciliation instead of silently
        # mutating the ledger. Wall-clock seeded so a restart without a
        # snapshot still moves forward; restore bumps past the snapshot's.
        self.cluster_epoch = int(time.time())
        self._pool_seq = 0  # carve-out generation counter (grant_seq)
        # object lineage: return oid -> producing task spec, for
        # reconstruction of lost objects (reference: TaskManager lineage +
        # object_recovery_manager). Bounded FIFO.
        self.lineage: "OrderedDict[ObjectID, dict]" = OrderedDict()
        self.lineage_cap = _config.get("lineage_cap")
        # byte cap mirrors the reference's RAY_max_lineage_bytes: specs keep
        # inline args alive, so count must not be the only bound
        self.lineage_bytes_cap = _config.get("lineage_bytes")
        self.lineage_bytes = 0
        self._reconstructing: Set[ObjectID] = set()
        # ------- distributed object lifetime (reference_count.h parity) ---
        # An object stays alive while ANY of: a process holds a live
        # ObjectRef (obj_holders), an in-flight task/actor-call references
        # it (obj_pins, incl. containment in a live object and queued
        # generator items), or a reconstructable lineage entry needs it as
        # an input (lineage_dep_pins). When all empty, it is evicted after
        # a short grace window that absorbs in-flight handoffs.
        self.refcount_enabled = _config.get("refcount")
        self.obj_holders: Dict[ObjectID, Set[WorkerID]] = {}
        # bounded-wait lease requests served as workers free up; entries
        # are dicts {resources, selector, venv_key, node_id, fut} so a
        # grant can honor the waiter's label selector / venv / node pin
        self._lease_waiters: list = []
        # versioned cluster resource view (ray_syncer role): broadcast
        # debounced to node daemons + subscribed drivers
        self._view_seq = 0
        self._last_view_snap: Optional[dict] = None
        self._view_wake: Optional[asyncio.Event] = None
        # sharded view plane (view_shards > 1): independent per-shard
        # versions bumped whenever any node in the shard changes, and the
        # scoped pubsub subscribers' send state (daemons keep theirs on
        # NodeInfo). Interest-scoped subscribers receive only changed
        # interest shards (as shard snapshots) plus a compact digest —
        # never the full node list.
        self._shard_vs: Dict[int, int] = {}
        self._sub_views: Dict[protocol.Connection, dict] = {}
        # serve-replica live-load rows piggybacked on the cluster_view
        # broadcast (changed-only): routers/handles/autoscalers read the
        # gossiped queue depth / EWMA latency with ZERO head RPCs on the
        # request path (serve/live_signals.py)
        self._last_serve_rows: List[dict] = []
        # gossiped object directory (authoritative copy): seal/spill/free
        # of non-inline objects and daemon replica announcements append
        # delta records that ride the next cluster_view broadcast; daemons
        # and drivers keep cached copies so warm pulls resolve peer-to-peer
        # with zero head RPCs (core/object_directory.py)
        from ray_tpu.core.object_directory import ObjectDirectory
        self.object_dir = ObjectDirectory()
        self._dir_seq = 0
        self._dir_pending: List[dict] = []
        self._dir_full_resync = False  # pending overflow: broadcast full
        self.obj_pins: Dict[ObjectID, int] = {}
        self.worker_holds: Dict[WorkerID, Set[ObjectID]] = {}
        self.lineage_dep_pins: Dict[ObjectID, int] = {}
        # borrower protocol (reference reference_count.h:73): token ->
        # (oid, sender worker); a pin opened when a ref is pickled, closed
        # by the deserializer's commit or the sender's death. Commits that
        # outrace their begin (receiver's flush beat the sender's) park in
        # a bounded seen-set so the late begin is dropped, not leaked.
        self.borrow_pins: Dict[bytes, tuple] = {}
        self.obj_borrows: Dict[ObjectID, Set[bytes]] = {}
        self.worker_borrows: Dict[WorkerID, Set[bytes]] = {}
        self._committed_tokens: "OrderedDict[bytes, None]" = OrderedDict()
        # zero-grace eviction support: an object with NO recorded interest
        # yet (its owner's inc is still in flight) is "newborn" and never
        # evicted — the first interest event arms normal lifetime. Dropped
        # objects leave a bounded tombstone so a late seal (slow retry)
        # frees its orphan copy instead of resurrecting a newborn.
        self.obj_interest_seen: Set[ObjectID] = set()
        self._tombstones: "OrderedDict[ObjectID, None]" = OrderedDict()
        self._evict_due: Dict[ObjectID, float] = {}
        # borrow pins make lifetime explicit, so no grace window is needed
        # to absorb in-flight handoffs (was 2.0 s of correctness-by-timing)
        self.evict_grace_s = _config.get("evict_grace_s")
        self.objects_evicted = 0
        # produced objects lost to node death, awaiting lazy reconstruction;
        # if their lineage entry gets cap-evicted meanwhile, consumers must
        # get ObjectLostError, not an eternal hang
        self._lost_pending: Set[ObjectID] = set()
        # worker log capture (reference log_monitor.py): per-file ring of
        # recent lines — the CLI/dashboard read this, so logs from remote
        # nodes work without a shared filesystem. LRU-bounded by file
        # count: worker churn must not grow head memory forever.
        self.log_ring: "OrderedDict[str, deque]" = OrderedDict()
        self._log_monitor = None

    def _task_event(self, task_id, name: str, state: str, *,
                    worker=None, node_id=None, error: str = None) -> None:
        self.task_events.append({
            "task_id": task_id.hex() if hasattr(task_id, "hex") else str(task_id),
            "name": name, "state": state, "ts": time.time(),
            "worker_id": worker.worker_id.hex() if worker else None,
            "node_id": (node_id.hex() if node_id is not None else
                        (worker.node_id.hex() if worker else None)),
            "error": error,
        })

    # ------------------------------------------------------------------ rpc
    def _handlers(self, conn_state: dict):
        def _peer_host():
            try:
                peer = conn_state["conn"].writer.get_extra_info("peername")
                return peer[0] if peer else None
            except Exception:
                return None

        async def register_worker(worker_id, pid, port, is_driver, node_id=None,
                                  log_tag=None, venv_key=None,
                                  reconnect=False):
            nid = NodeID(node_id) if node_id else self.node_id
            node = self.nodes.get(nid) or self.head_node
            w = WorkerInfo(WorkerID(worker_id), conn_state["conn"], pid, port,
                           is_driver, node.node_id)
            w.host = _peer_host()  # reachable host for direct actor calls
            w.proc = self._spawned.pop(pid, None)
            w.log_tag = log_tag    # maps this worker to its log files
            w.venv_key = venv_key
            # the node the worker CLAIMS to belong to (its spawn-time env),
            # kept even when the lookup fell back to head_node because the
            # daemon has not re-registered yet — pool_reconcile uses it to
            # find fallback-parked workers
            w.declared_node = nid
            self.workers[w.worker_id] = w
            conn_state["worker"] = w
            node.workers.add(w.worker_id)
            if not is_driver:
                node.starting_workers = max(0, node.starting_workers - 1)
                item = (node.pending_pool.pop(w.worker_id, None)
                        if node.conn is not None else None)
                # declared a remote node that has not re-registered yet:
                # its daemon may still pool this worker — treat like an
                # unreconciled node (the fallback to head_node must not
                # bypass the double-grant fence)
                daemon_pending = (node is self.head_node
                                  and nid != self.node_id)
                if item is not None:
                    # its daemon's reconciliation report already claimed
                    # this worker for a lease pool: restore the carve-out
                    # instead of exposing it to head dispatch
                    self._adopt_pooled(node, w, item)
                elif reconnect and (daemon_pending or (
                        node.conn is not None and not node.reconciled)):
                    # a surviving worker re-registering after a head
                    # restart: its node daemon may still hold it in a
                    # lease pool — park it until pool_reconcile claims or
                    # disowns it (double-grant fence), with a promotion
                    # timeout in case the daemon never reports. 10 s: a
                    # live daemon reconciles within ~1 s of reconnecting
                    # (its backoff caps at 2 s), so the fence comfortably
                    # outlasts reconcile without stranding workers whose
                    # daemon died for good.
                    node.unadopted.add(w)
                    asyncio.get_running_loop().call_later(
                        10.0, self._promote_unadopted, node, w)
                else:
                    node.idle.append(w)
                    self._grant_lease_waiters(node)
                    self._kick()
            return {"node_id": node.node_id.binary(), "session": self.session,
                    "epoch": self.cluster_epoch,
                    # lets clients recognize the restart-recovery window
                    # (a young head may still be re-learning state from
                    # reconnecting exporters)
                    "head_uptime_s": time.time() - self.start_time,
                    "resources": node.resources, "labels": node.labels,
                    # the head's refcount setting is authoritative; clients
                    # enable/disable their trackers from this reply
                    "refcount": self.refcount_enabled,
                    # full negotiated-config snapshot (ray_config_def.h
                    # style single source of truth; "refcount" above is
                    # the r3-era key, kept for compatibility)
                    "config": _config.GLOBAL.negotiated_snapshot(),
                    "driver_sys_path": self.kv.get(("cluster", b"driver_sys_path"))}

        async def register_node(node_id, resources, labels, max_workers,
                                data_port=None, sched_port=None,
                                interest=None):
            nid = NodeID(node_id)
            existing = self.nodes.get(nid)
            if existing is not None and not existing.is_head:
                # re-registration after a connection flap / healed
                # partition: keep the ledger, workers and pool state —
                # only the transport is new. The reconciliation handshake
                # re-runs (the daemon reports its inventory right after
                # this reply) to settle any drift from the outage.
                old_conn = existing.conn
                node = existing
                node.conn = conn_state["conn"]
                node.alive = True
                node.reconciled = False
                node.view_sub = self._make_view_sub(interest, nid)
                if data_port:
                    node.data_addr = (_peer_host() or "127.0.0.1", data_port)
                if sched_port:
                    node.sched_addr = (_peer_host() or "127.0.0.1",
                                       sched_port)
                conn_state["node"] = node
                if old_conn is not None and not old_conn.closed:
                    asyncio.ensure_future(old_conn.close())
                self.lease_events.append(
                    {"ts": time.time(), "kind": "node_reregister",
                     "node_id": nid.hex()})
                self._kick()
                self._view_changed()
                self._push_full_view(conn_state["conn"],
                                     sub=node.view_sub)
                return {"session": self.session,
                        "head_node_id": self.node_id.binary(),
                        "epoch": self.cluster_epoch}
            node = NodeInfo(nid, resources, labels, conn_state["conn"],
                            max_workers)
            node.view_sub = self._make_view_sub(interest, nid)
            if data_port:
                node.data_addr = (_peer_host() or "127.0.0.1", data_port)
            if sched_port:
                node.sched_addr = (_peer_host() or "127.0.0.1", sched_port)
            self.nodes[nid] = node
            conn_state["node"] = node
            self._publish("node_state", {"node_id": nid.binary(), "state": "ALIVE"})
            self._kick()
            self._view_changed()
            self._push_full_view(conn_state["conn"], sub=node.view_sub)
            return {"session": self.session,
                    "head_node_id": self.node_id.binary(),
                    "epoch": self.cluster_epoch}

        async def resource_view_delta(version, idle_workers, labels=None,
                                      events=None, stats=None, gossip=None,
                                      metrics=None, epoch=None,
                                      leased_workers=None, objects=None,
                                      pool_shapes=None):
            """Node-daemon gossip: its lease-pool state changed. Stale
            versions (a reconnect replaying an old delta) are ignored.
            The reply acks the highest flight-recorder event seq merged —
            the daemon keeps un-acked batches pending and resends them
            (duplicates are dropped here by per-node seq), so a delta
            lost on a dying connection no longer loses its events."""
            node = conn_state.get("node")
            if node is None:
                return False
            if epoch is not None and epoch != self.cluster_epoch:
                # a delta stamped with a dead epoch must not mutate the
                # view or the telemetry merge — route the daemon into the
                # reconciliation handshake instead
                self._stale_epoch("resource_view_delta", node)
                return {"nack": True, "epoch": self.cluster_epoch}
            node.last_delta_ts = time.time()
            if events:
                nid = node.node_id.hex()
                for ev in events:
                    seq = ev.get("seq", 0)
                    if seq and seq <= node.fr_last_seq:
                        continue  # re-delivery of an un-acked batch
                    ev["node_id"] = nid
                    self.lease_events.append(ev)
                    if seq:
                        node.fr_last_seq = seq
            if stats:
                node.sched_stats = stats
            if gossip:
                node.gossip_health = gossip
            if leased_workers is not None:
                node.pool_leased = leased_workers
            if objects:
                # replica announcements from the daemon's pull manager
                # (pull-replica created / cache-evicted): merge into the
                # authoritative directory and rebroadcast so every
                # consumer gains the extra pull source
                nid_hex = node.node_id.hex()
                for rec in objects:
                    if rec.get("op") not in ("replica", "replica_gone") \
                            or rec.get("node") != nid_hex:
                        continue
                    self._dir_announce(rec)
                    if rec["op"] == "replica_gone":
                        # the evicted replica may have been the LAST copy
                        # of an object whose primary already died: run
                        # loss handling (reconstruct / seal lost) now
                        # instead of leaving a dangling meta forever
                        oid = ObjectID(rec["oid"])
                        m = self.objects.get(oid)
                        if (m is not None and m.kind in ("shm", "arena")
                                and m.node_id is not None
                                and not self._node_alive(m.node_id)
                                and not self.object_dir.locations(oid)):
                            self._handle_lost_object(
                                oid, f"last replica evicted on {nid_hex}")
            if metrics is not None:
                # daemons have no CoreClient/pusher: their metrics registry
                # snapshot rides the gossip into the same _metrics KV
                # namespace the scrape endpoint aggregates (expired with
                # the node on disconnect)
                import json as _json

                mkey = f"proc:node-{node.node_id.hex()[:12]}".encode()
                self.kv[("_metrics", mkey)] = _json.dumps(metrics).encode()
                self._metrics_parsed[mkey] = metrics
                for fam in metrics:
                    if fam.get("name") == "__spans__":
                        self._adopt_spans(
                            fam.get("series") or (),
                            proc=f"node-{node.node_id.hex()[:12]}",
                            node=node.node_id.hex()[:12])
            if version > node.view_version:
                node.view_version = version
                node.pool_idle = idle_workers
                if pool_shapes is not None:
                    # per-shape pool composition: broadcast in the view so
                    # peer-spillback referrals name peers actually holding
                    # a matching warm worker (cuts dead-referral hops)
                    node.pool_shapes = pool_shapes
                if labels:
                    node.labels.update(labels)
                self._view_changed()
            return {"acked_seq": node.fr_last_seq,
                    "epoch": self.cluster_epoch}

        async def metrics_push(value):
            """Per-process metrics snapshot (drivers/workers push on a
            cadence — fire-and-forget so telemetry never adds control
            round trips). Keyed by the pushing worker id; expired by
            _on_worker_disconnect so dead processes stop being scraped."""
            w = conn_state.get("worker")
            if w is None:
                return False
            import json as _json

            key = f"proc:{w.worker_id.hex()}".encode()
            self.kv[("_metrics", key)] = value
            try:
                payload = _json.loads(value)
            except Exception:
                # kv now holds the bad bytes: a stale cache entry would
                # serve the PREVIOUS snapshot forever
                self._metrics_parsed.pop(key, None)
                return False
            self._metrics_parsed[key] = payload
            for fam in payload:
                if fam.get("name") == "__spans__":
                    self._adopt_spans(
                        fam.get("series") or (),
                        proc=w.worker_id.hex()[:12],
                        node=w.node_id.hex()[:12] if w.node_id else None)
            return True

        async def pool_acquire(resources, venv_key=None, epoch=None):
            """A node daemon carves a lease worker out of its own node for
            its local pool: the head debits the ledger ONCE here; all
            subsequent grant/return cycles on that worker are daemon-local
            (reference raylet worker-pool ownership). The reply stamps the
            cluster epoch and a carve-out generation (grant_seq) the
            daemon must echo on release."""
            node = conn_state.get("node")
            if node is None or not node.could_ever_fit(resources):
                return None
            if epoch is not None and epoch != self.cluster_epoch:
                self._stale_epoch("pool_acquire", node)
                return None
            lw = None
            if node.fits(resources):
                lw = self._idle_worker_on(node, venv_key)
            if lw is None:
                self._request_worker(node, pip=None, pip_key=venv_key)
                fut = asyncio.get_running_loop().create_future()
                ent = {"resources": resources, "selector": None,
                       "venv_key": venv_key, "node_id": node.node_id,
                       "fut": fut}
                self._lease_waiters.append(ent)
                try:
                    # generous: a cold pool needs a full worker spawn
                    # (python boot + register), seconds on a small host
                    lw = await asyncio.wait_for(fut, timeout=5.0)
                except (asyncio.TimeoutError, asyncio.CancelledError):
                    try:
                        self._lease_waiters.remove(ent)
                    except ValueError:
                        pass
                    return None
                # granted pre-acquired by _grant_lease_waiters
            else:
                self._acquire(lw, resources)
            lw.pooled = True
            self._pool_seq += 1
            lw.pool_grant_seq = self._pool_seq
            self.sched_totals["pool_acquires"] += 1
            self._last_dispatch_ts = time.monotonic()
            self._view_changed()
            return {"worker_id": lw.worker_id.binary(),
                    "addr": (lw.host or "127.0.0.1", lw.port),
                    "epoch": self.cluster_epoch,
                    "grant_seq": lw.pool_grant_seq}

        async def pool_release(worker_id, grant_seq=None, epoch=None):
            """Daemon returns a pooled worker (idle too long, or pool
            teardown): resources flow back to the node ledger and the
            worker rejoins the head's dispatchable idle set. Idempotent —
            keyed by (epoch, worker, grant_seq) so the daemon's
            requeue-with-backoff retries and duplicate deliveries are
            safe: an already-released worker, a mismatched carve-out
            generation, or a stale epoch are all no-ops."""
            if epoch is not None and epoch != self.cluster_epoch:
                # reconciliation already rebuilt (or will rebuild) this
                # ledger from the daemon's inventory; applying a stale
                # release would double-credit the node
                self._stale_epoch("pool_release", conn_state.get("node"))
                return {"stale_epoch": True, "epoch": self.cluster_epoch}
            lw = self.workers.get(WorkerID(worker_id))
            if lw is None or not lw.pooled:
                return True  # already released / died / reconciled away
            if (grant_seq is not None and lw.pool_grant_seq is not None
                    and grant_seq != lw.pool_grant_seq):
                return True  # duplicate from an older carve-out generation
            lw.pooled = False
            lw.pool_grant_seq = None
            lw.leased_to = None
            self.sched_totals["pool_releases"] += 1
            self.notify_task_done(lw)
            self._view_changed()
            return True

        async def pool_reconcile(inventory, epoch=None, objects=None):
            """Reconciliation handshake: on every (re)connect the daemon
            reports its full pool inventory (idle entries + live local
            leases). The daemon is the source of truth for carved
            capacity — the head rebuilds its ledger from this report
            rather than from a possibly-stale snapshot: unclaimed
            head-side carve-outs are released (leak fence), claimed
            workers are (re-)pooled (double-grant fence), and workers
            that have not re-registered yet are parked in pending_pool
            for adoption at registration."""
            node = conn_state.get("node")
            if node is None:
                return None
            reported: Dict[WorkerID, dict] = {}
            for item in inventory or []:
                reported[WorkerID(item["wid"])] = item
            released = 0
            for w in list(self.workers.values()):
                if (w.node_id == node.node_id and w.pooled
                        and w.worker_id not in reported):
                    # head thinks pooled, daemon disowns it: the carve-out
                    # would leak forever (e.g. a pool_release lost while
                    # the head was unreachable)
                    w.pooled = False
                    w.pool_grant_seq = None
                    released += 1
                    self.sched_totals["pool_releases"] += 1
                    self.notify_task_done(w)
            adopted = 0
            node.pending_pool = {}
            for wid, item in reported.items():
                w = self.workers.get(wid)
                if w is None:
                    node.pending_pool[wid] = item
                    continue
                self._adopt_pooled(node, w, item)
                adopted += 1
            adopted_objects = 0
            stale_objects = []
            if objects:
                # spill-restore: the daemon re-advertises its node's
                # surviving object inventory (shm/arena/spilled primaries
                # from its cached directory + pulled replicas), and the
                # head rebuilds the object directory from daemon truth —
                # the ledger pattern applied to data. _seal is idempotent
                # (first seal wins) so a live head's entries are untouched.
                for meta in objects.get("metas") or ():
                    if (meta.kind not in objdir.PULLABLE_KINDS
                            or meta.node_id != node.node_id):
                        continue
                    if meta.object_id in self._tombstones:
                        # freed while the daemon's free push was lost in a
                        # connection flap: tell it to reclaim the storage
                        # instead of resurrecting the object
                        stale_objects.append(meta)
                        continue
                    if meta.object_id not in self.objects:
                        self._seal(meta)
                        adopted_objects += 1
                nid_hex = node.node_id.hex()
                for oid_b in objects.get("replicas") or ():
                    oid = ObjectID(oid_b)
                    if oid in self.objects:
                        self._dir_announce(
                            objdir.replica_record(oid, nid_hex))
            node.reconciled = True
            self.sched_totals["reconciles"] += 1
            for w in list(node.unadopted):
                self._promote_unadopted(node, w)
            # fallback-parked workers (re-registered before this daemon
            # did, so they landed on head_node): claimed ones were
            # re-homed by _adopt_pooled above; disowned ones go to work
            for w in list(self.head_node.unadopted):
                if getattr(w, "declared_node", None) == node.node_id:
                    self._promote_unadopted(self.head_node, w)
            self.lease_events.append(
                {"ts": time.time(), "kind": "pool_reconcile",
                 "node_id": node.node_id.hex(), "adopted": adopted,
                 "released": released, "pending": len(node.pending_pool),
                 "objects_readvertised": adopted_objects})
            self._view_changed()
            self._kick()
            for meta in stale_objects:
                try:
                    node.conn.push("free_object", meta=meta)
                except Exception:
                    pass
            return {"epoch": self.cluster_epoch, "adopted": adopted,
                    "released": released}

        async def set_node_chaos(node_id, spec):
            """Chaos control plane: apply a fault plan inside a node
            daemon (tests sever the daemon<->head edge at a controlled
            moment without SIGSTOP-freezing the whole process)."""
            n = self.nodes.get(NodeID(node_id))
            if n is None or n.conn is None or n.conn.closed:
                return False
            n.conn.push("chaos", spec=spec)
            return True

        async def submit_task(spec):
            w = conn_state["worker"]
            rec = TaskRecord(spec, w)
            for rid in spec["return_ids"]:
                # the submitter constructs ObjectRefs for every return
                # id; record it as holder NOW so a fast task's sealed
                # result can't be evicted before the submitter's inc
                # flush lands. A lease-failover resubmission only skips
                # this when the head has provably seen AND released the
                # submitter's ref (inc + dec both landed) — re-adding
                # then would leak the sealed result forever. A
                # connect-phase failover fires milliseconds after the
                # original submit, when the inc can still be inside the
                # refcount flush window, so "failover" alone is not
                # evidence the holder exists.
                oid = ObjectID(rid)
                if (spec.get("failover")
                        and (oid in self.obj_interest_seen
                             or oid in self._tombstones)
                        and oid not in self.worker_holds.get(w.worker_id, ())):
                    # inc + dec both landed (live interest released, or the
                    # dropped ref was already tombstoned): re-adding the
                    # holder would never be released → sealed-result leak
                    continue
                self._add_holder(oid, w.worker_id)
            if spec["options"].get("num_returns") != "streaming":
                self._lineage_record_spec(spec)
            self._enqueue(rec)
            return True

        async def record_lineage(spec):
            """Out-of-band lineage registration for tasks dispatched
            WITHOUT the head (the lease/peer warm path): the client ships
            the full spec so a result lost to node death can re-run
            through the normal queue. Opt-in per task via
            options['lineage'] — set by the data library's stage tasks —
            so the default warm path stays zero-head-message."""
            if spec["options"].get("num_returns") == "streaming":
                return False
            self._lineage_record_spec(spec)
            return True

        async def release_lineage(return_ids):
            """Eager lineage retirement for consumed intermediates (the
            streaming data executor's per-partition chain release): pop
            the entries so their input dep pins release and the blocks
            follow normal refcount eviction — a long pipeline's store
            footprint stays bounded by the in-flight window, not the
            lineage cap."""
            for rid in return_ids:
                oid = ObjectID(rid)
                self._lineage_pop(oid)
                self._maybe_evict(oid)
            return True

        async def create_actor(spec):
            actor_id = ActorID(spec["actor_id"])
            name = spec["options"].get("name")
            key = None
            if name:
                key = (spec["options"].get("namespace", "default"), name)
                if key in self.named_actors:
                    existing = self.actors[self.named_actors[key]]
                    if existing.state != "DEAD":
                        if spec["options"].get("get_if_exists"):
                            return {"actor_id": self.named_actors[key].binary()}
                        raise ValueError(f"actor name {name!r} already taken")
            info = ActorInfo(actor_id, spec)
            self.actors[actor_id] = info
            if key is not None:
                self.named_actors[key] = actor_id
            self._schedule_actor(info)
            self._spawn_for_demand()
            return {"actor_id": actor_id.binary()}

        async def wait_actor(actor_id):
            info = self.actors[ActorID(actor_id)]
            await info.ready_event.wait()
            if info.state == "DEAD":
                return {"state": "DEAD", "death_cause": info.death_cause}
            return {"state": info.state, "address": info.address}

        async def get_actor_address(actor_id):
            info = self.actors.get(ActorID(actor_id))
            if info is None:
                return {"state": "DEAD", "death_cause": "actor not found"}
            if info.state in ("PENDING", "RESTARTING"):
                await info.ready_event.wait()
            if info.state == "DEAD":
                return {"state": "DEAD", "death_cause": info.death_cause}
            return {"state": info.state, "address": info.address,
                    # placement: compiled-DAG channel planning needs to
                    # know which node each endpoint lives on
                    "node_id": (info.worker.node_id.binary()
                                if info.worker is not None else None)}

        async def get_named_actor(name, namespace):
            key = (namespace, name)
            actor_id = self.named_actors.get(key)
            if actor_id is None or self.actors[actor_id].state == "DEAD":
                return None
            info = self.actors[actor_id]
            return {"actor_id": actor_id.binary(),
                    "methods": info.spec.get("methods", {})}

        async def kill_actor(actor_id, no_restart=True):
            info = self.actors.get(ActorID(actor_id))
            if info is None:
                return False
            if no_restart:
                info.restarts_left = 0
            if info.worker is not None:
                self._terminate_worker(info.worker)
            else:
                self._mark_actor_dead(info, "killed")
            return True

        async def put_meta(meta):
            w = conn_state.get("worker")
            if meta.node_id is None and w is not None:
                meta.node_id = w.node_id  # locate for node-loss recovery
            self._seal(meta)
            return True

        async def get_meta(object_id, timeout=None):
            oid = ObjectID(object_id)
            meta = self.objects.get(oid)
            if meta is not None:
                return meta
            self._maybe_reconstruct(oid)
            fut = asyncio.get_running_loop().create_future()
            self.object_waiters.setdefault(oid, []).append(fut)
            if timeout is None:
                return await fut
            try:
                return await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                return None

        async def ref_update(ops):
            """Batched, ORDERED ObjectRef count transitions and borrow
            events from one process (reference ReferenceCounter ownership
            updates + borrower registration)."""
            w = conn_state.get("worker")
            if w is None:
                return True
            held = self.worker_holds.setdefault(w.worker_id, set())
            for op in ops:
                kind, b = op[0], op[1]
                oid = ObjectID(b)
                if kind == "i":
                    held.add(oid)
                    self.obj_holders.setdefault(oid, set()).add(w.worker_id)
                    self.obj_interest_seen.add(oid)
                    self._evict_due.pop(oid, None)
                elif kind == "d":
                    held.discard(oid)
                    hs = self.obj_holders.get(oid)
                    if hs is not None:
                        hs.discard(w.worker_id)
                        if not hs:
                            self.obj_holders.pop(oid, None)
                            self._maybe_evict(oid)
                elif kind == "b":
                    self._borrow_begin(oid, op[2], w.worker_id)
                elif kind == "c":
                    self._borrow_commit(oid, op[2])
            return True

        async def object_spilled(meta):
            """A node daemon spilled an object it tracks: retarget the
            canonical directory entry so new readers hit the spill file."""
            canonical = self.objects.get(meta.object_id)
            if canonical is not None and canonical.kind in ("shm", "arena"):
                canonical.kind = meta.kind
                canonical.spill_path = meta.spill_path
                canonical.segment = meta.segment
                self._dir_announce(objdir.spill_record(canonical))
            return True

        async def announce_prefix(model_key, oid, block_size, rows):
            """A serve replica exported a KV prefix blob into the store:
            bind its content hashes — one row per covered block boundary,
            `rows=[(hash, n_tokens), ...]`, all naming the same blob — and
            ride them out on the next cluster_view broadcast, so any
            decode replica can warm-start from the blob at ANY shared
            depth with zero head RPCs. Pushed fire-and-forget on the
            replica's existing head connection (FIFO after the blob's
            put_meta, so consumers never see a binding before its blob's
            location)."""
            o = ObjectID(oid)
            for phash, n_tokens in rows:
                self._dir_announce(objdir.prefix_record(
                    model_key, phash, o, n_tokens, block_size))
            return True

        async def withdraw_prefix(model_key, phashes, oid=None):
            """Publisher-side eviction (its pin LRU rotated a blob out):
            retire its bindings promptly instead of waiting for the
            refcount plane to free the object. `oid` scopes the retire to
            the publisher's OWN blob: two replicas racing to publish the
            same prefix rebind last-write-wins, and the loser's later
            eviction must not delete the winner's live binding."""
            rows = self.object_dir.prefixes.get(model_key) or {}
            for phash in phashes:
                ent = rows.get(phash)
                if ent is None or (oid is not None and ent["oid"] != oid):
                    continue          # rebound to another blob: keep it
                self._dir_announce(
                    objdir.prefix_gone_record(model_key, phash))
            return True

        async def announce_weights(weights_id, oid):
            """A serve replica published a weight manifest (plus its chunk
            objects) into the store: bind `weights_id -> manifest oid` and
            ride it out on the next cluster_view broadcast, so any cold
            replica resolves the manifest from its cached directory with
            zero head RPCs (serve/weight_store.py). Pushed fire-and-forget
            FIFO after the blobs' put_meta, so consumers never see the
            binding before the manifest's location."""
            self._dir_announce(objdir.weights_record(weights_id,
                                                     ObjectID(oid)))
            return True

        async def withdraw_weights(weights_id, oid=None):
            """Publisher-side eviction (its published-model LRU rotated a
            manifest out): retire the binding promptly. `oid` scopes the
            retire to the publisher's OWN manifest — two replicas racing
            to publish the same weights rebind last-write-wins, and the
            loser's later eviction must not delete the winner's live
            binding."""
            ent = self.object_dir.weights.get(weights_id)
            if ent is None or (oid is not None and ent["oid"] != oid):
                return True           # rebound to another blob: keep it
            self._dir_announce(objdir.weights_gone_record(weights_id))
            return True

        async def worker_address(worker_id):
            """Direct-server address of a live worker (device-object
            fetches go straight to the owning process)."""
            w = self.workers.get(WorkerID(worker_id))
            if w is None:
                return None
            return (w.host or "127.0.0.1", w.port)

        async def node_data_addr(node_id):
            """Data-server address of a node (for pulls of unregistered
            direct actor-reply objects, which carry only a node_id)."""
            n = self.nodes.get(NodeID(node_id))
            if n is None or not n.alive:
                return None
            return n.data_addr

        async def locate_object(object_id, timeout=None):
            """Object directory lookup — now the COLD-MISS fallback behind
            the gossiped directory (reference ownership_object_directory
            semantics). Returns the fresh meta, the primary's data-server
            address, and every advertised replica address so the puller
            can fail over without another round trip."""
            meta = await get_meta(object_id, timeout=timeout)
            if meta is None:
                return None
            addr = None
            sources = []
            serving = []
            if meta.kind in objdir.PULLABLE_KINDS:
                for node_hex in (self.object_dir.locations(meta.object_id)
                                 or ([meta.node_id.hex()]
                                     if meta.node_id is not None else [])):
                    try:
                        n = self.nodes.get(NodeID.from_hex(node_hex))
                    except Exception:
                        n = None
                    if n is not None and n.alive and n.data_addr:
                        sources.append(n.data_addr)
                        # serving-node hexes ride the reply so a scoped
                        # subscriber can widen its shard interest to the
                        # nodes it actually pulls from (interest-on-demand)
                        serving.append(node_hex)
                addr = sources[0] if sources else None
            return {"meta": meta, "data_addr": addr, "sources": sources,
                    "nodes": serving}

        async def widen_interest(shards):
            """Interest-on-demand (scoped daemon push): the subscriber
            cold-missed a data-plane pull into the locate_object fallback;
            widening its shard subscription to the serving node's shard
            makes subsequent pulls from that neighborhood resolve from
            the gossiped directory instead. Replies with a fresh scoped
            view so the newly-covered shards' entries and directory rows
            arrive immediately."""
            node = conn_state.get("node")
            nshards = int(_config.get("view_shards"))
            if node is None or node.view_sub is None or nshards <= 1:
                return False
            cur = set(node.view_sub["interest"])
            new = {int(s) % nshards for s in shards} - cur
            if not new:
                return True
            node.view_sub["interest"] = sorted(cur | new)
            self.lease_events.append(
                {"ts": time.time(), "kind": "interest_widen",
                 "node_id": node.node_id.hex(), "shards": sorted(new)})
            self._push_full_view(node.conn, sub=node.view_sub)
            return True

        async def wait_objects(object_ids, num_returns, timeout):
            object_ids = [ObjectID(b) if not isinstance(b, ObjectID) else b
                          for b in object_ids]
            for oid in object_ids:
                if oid not in self.objects:
                    self._maybe_reconstruct(oid)
            ids = list(object_ids)
            num_returns = min(num_returns, len(ids))
            deadline = None if timeout is None else time.monotonic() + timeout

            def ready():
                return [i for i, oid in enumerate(ids) if oid in self.objects]

            while len(ready()) < num_returns:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    break
                futs = []
                for oid in ids:
                    if oid not in self.objects:
                        fut = asyncio.get_running_loop().create_future()
                        self.object_waiters.setdefault(oid, []).append(fut)
                        futs.append(fut)
                if not futs:
                    break
                try:
                    await asyncio.wait(futs, timeout=remaining,
                                       return_when=asyncio.FIRST_COMPLETED)
                finally:
                    for fut in futs:
                        fut.cancel()
            return ready()

        async def free_objects(object_ids):
            for oid in [ObjectID(b) for b in object_ids]:
                self._drop_object(oid)
            return True

        async def kv_put(ns, key, value, overwrite=True):
            if ns == "_runtime_env":
                self._bound_runtime_env_cache(len(value))
            k = (ns, key)
            if not overwrite and k in self.kv:
                return False
            self.kv[k] = value
            return True

        async def kv_get(ns, key):
            return self.kv.get((ns, key))

        async def kv_del(ns, key):
            if ns == "_runtime_env":
                self._drop_runtime_env_blob_file(key)
            return self.kv.pop((ns, key), None) is not None

        async def kv_keys(ns, prefix):
            return [k for (n, k) in self.kv if n == ns and k.startswith(prefix)]

        async def create_pg(pg_id, bundles, strategy, name):
            pgid = PlacementGroupID(pg_id)
            pg = PlacementGroupInfo(pgid, bundles, strategy, name)
            self.pgs[pgid] = pg
            self._try_reserve_pg(pg)
            # reservation is attempted synchronously: when it committed,
            # the reply says so and the client's ready() needs no second
            # round trip (the PG-cycle hot path is 1 RPC, not 3)
            return {"state": pg.state,
                    "bundle_nodes": [b.node_id.binary() if b.node_id else None
                                     for b in pg.bundles]}

        async def wait_pg(pg_id, timeout=None):
            pg = self.pgs.get(PlacementGroupID(pg_id))
            if pg is None:
                return {"state": "REMOVED"}
            if timeout is not None:
                try:
                    await asyncio.wait_for(pg.ready_event.wait(), timeout)
                except asyncio.TimeoutError:
                    pass
            else:
                await pg.ready_event.wait()
            return {"state": pg.state,
                    "bundle_nodes": [b.node_id.binary() if b.node_id else None
                                     for b in pg.bundles]}

        async def remove_pg(pg_id):
            pg = self.pgs.pop(PlacementGroupID(pg_id), None)
            if pg is not None and pg.state == "CREATED":
                # return only the unclaimed portion; in-use resources flow back
                # to the node ledger when their tasks release (pg is gone then)
                for b in pg.bundles:
                    node = self.nodes.get(b.node_id)
                    if node is not None:
                        for res, amt in b.available.items():
                            node.available[res] = node.available.get(res, 0) + amt
                self._kick()
            return True

        async def blocked(value):
            w = conn_state.get("worker")
            if w is not None and w.blocked != value:
                w.blocked = value
                if value:
                    self._release(w, cpu_only=True)
                self._kick()
            return True

        async def subscribe(channel, interest=None):
            conn = conn_state["conn"]
            self.subscribers.setdefault(channel, []).append(conn)
            if channel == "cluster_view":
                sub = self._make_view_sub(
                    interest, conn_state["worker"].node_id
                    if conn_state.get("worker") else None)
                if sub is not None:
                    # interest-scoped pubsub subscriber: tracked alongside
                    # the daemons' send state; pruned with the connection
                    self._sub_views[conn] = sub
                # late subscribers must not wait for the next view CHANGE
                # to learn the current one (object-directory payload
                # included wholesale — deltas only carry recent history)
                self._push_full_view(conn, pubsub=True, sub=sub)
            return True

        async def cluster_info():
            total: Dict[str, float] = {}
            avail: Dict[str, float] = {}
            for node in self.nodes.values():
                if not node.alive:
                    continue
                for r, v in node.resources.items():
                    total[r] = total.get(r, 0) + v
                for r, v in node.available.items():
                    avail[r] = avail.get(r, 0) + v
            return {
                "node_id": self.node_id.binary(),
                "session": self.session,
                "total_resources": total,
                "available_resources": avail,
                "labels": self.head_node.labels,
                "num_workers": len(self.workers),
                "num_nodes": len([n for n in self.nodes.values() if n.alive]),
                "actors": {a.hex(): info.state for a, info in self.actors.items()},
                "uptime": time.time() - self.start_time,
                "dashboard_port": getattr(self, "dashboard_port", None),
                "client_proxy_port": getattr(self, "client_proxy_port", None),
            }

        async def submit_job(entrypoint, metadata=None, env=None,
                             working_dir=None, job_id=None):
            return await self.job_manager.submit(
                entrypoint, metadata=metadata, env=env,
                working_dir=working_dir, job_id=job_id)

        async def get_job(job_id):
            return self.job_manager.get(job_id)

        async def list_jobs():
            return self.job_manager.list()

        async def stop_job(job_id):
            return self.job_manager.stop(job_id)

        async def job_logs(job_id):
            return self.job_manager.logs(job_id)

        async def cluster_demand():
            """Unmet resource demand: queued, dep-ready tasks whose asks
            don't fit any alive node's *available* resources right now
            (feeds the autoscaler, reference load_metrics semantics)."""
            demand = []
            for rec in self.queue:
                if rec.pending_deps:
                    continue
                if rec.spec["options"].get("placement_group"):
                    continue  # counted via its PG's unplaced bundles below
                res = rec.spec["options"].get("resources", {"CPU": 1})
                sel = rec.spec["options"].get("label_selector")
                if not any(n.matches_labels(sel) and n.fits(res)
                           for n in self._alive_nodes()):
                    demand.append(res)
            # pending placement groups count too
            for pg in self.pgs.values():
                if pg.state == "PENDING":
                    demand.extend(b.resources for b in pg.bundles
                                  if b.node_id is None)
            return demand

        async def job_counter_next():
            self.job_counter += 1
            return self.job_counter

        async def list_state(kind):
            return self._list_state(kind)

        async def train_event(run, phase, t0=None, t1=None, detail=None):
            """A train controller's lifecycle phase (group_start /
            death_detected / restore / resize / finished), appended to
            the merged flight-recorder stream so `ray_tpu.timeline()`
            renders train restarts alongside the epoch-fence/reconcile
            windows they ride."""
            self.lease_events.append({
                "ts": time.time(), "kind": f"train_{phase}", "run": run,
                "t0": t0, "t1": t1, **(detail or {})})
            return True

        async def chain_event(chain, kind, detail=None):
            """A compiled serve chain's failure-plane event (chain_fence /
            chain_failover), mirrored from the chain's private event log
            into the flight-recorder stream: `state.list_lease_events()`
            and the timeline reconcile row show replica-death windows on
            the compiled plane next to the scheduler's view. Never on
            the warm path — fences already pay control-plane RPCs."""
            if kind not in ("chain_fence", "chain_failover"):
                return False
            self.lease_events.append({
                "ts": time.time(), "kind": kind, "chain": chain,
                **(detail or {})})
            return True

        async def get_config():
            """The head's full flag table (ray-tpu config CLI, dashboard)."""
            return _config.GLOBAL.dump()

        async def reporter_stats():
            """Per-process stats for every registered worker (reference
            dashboard reporter module): RSS/CPU/threads from /proc."""
            page = os.sysconf("SC_PAGE_SIZE")
            tick = os.sysconf("SC_CLK_TCK")
            rows = []
            for w in self.workers.values():
                row = {"worker_id": w.worker_id.hex(), "pid": w.pid,
                       "is_driver": w.is_driver,
                       "node_id": w.node_id.hex(),
                       "actor": w.actor_id.hex() if w.actor_id else None,
                       "log_tag": getattr(w, "log_tag", None)}
                if w.node_id != self.node_id:
                    # remote pid: /proc here would be a STRANGER's process
                    row["alive"] = w.conn is not None and not w.conn.closed
                    row["remote"] = True
                    rows.append(row)
                    continue
                try:
                    with open(f"/proc/{w.pid}/stat") as f:
                        parts = f.read().rsplit(") ", 1)[1].split()
                    # fields after comm: state utime=11 stime=12 (0-based
                    # within this tail), num_threads=17, rss=21
                    row["cpu_seconds"] = round(
                        (int(parts[11]) + int(parts[12])) / tick, 2)
                    row["num_threads"] = int(parts[17])
                    row["rss_bytes"] = int(parts[21]) * page
                    row["alive"] = True
                except (OSError, IndexError, ValueError):
                    row["alive"] = False  # remote node or exited
                rows.append(row)
            return rows

        async def worker_stacks(worker_id):
            """Live thread stacks of one worker (cooperative py-spy)."""
            w = self.workers.get(WorkerID(worker_id))
            if w is None or w.conn is None or w.conn.closed:
                return None
            try:
                # bounded: a GIL-wedged worker (the exact case being
                # debugged) can't run its handler — report unreachable
                # instead of hanging the CLI/dashboard
                return await asyncio.wait_for(
                    w.conn.request("dump_stacks"), timeout=10.0)
            except asyncio.TimeoutError:
                return ("<worker did not respond within 10s — event loop "
                        "wedged (GIL-holding C call?); use kernel-level "
                        "tools for a non-cooperative dump>")

        async def log_batch(entries):
            """Tailed lines pushed by a node daemon's LogMonitor."""
            self._on_log_batch(entries)
            return True

        async def list_logs():
            """Log files known to the head: this machine's session log
            tree plus everything the ring has seen from remote nodes."""
            from ray_tpu.core import worker_logs

            out = worker_logs.list_log_files(self.session)
            for name in self.log_ring:
                out.setdefault(name, None)  # remote: size unknown
            return [{"file": n, "size": s}
                    for n, s in sorted(out.items())]

        async def get_log(filename, tail=None):
            """Lines of one log file: full file when it lives on this
            machine, ring contents otherwise (remote nodes, no shared FS).
            File IO runs in an executor — a multi-GB log must not stall
            the head's event loop."""
            from ray_tpu.core import worker_logs

            if os.sep in filename or filename.startswith("."):
                raise ValueError(f"bad log filename {filename!r}")
            lines = None
            path = worker_logs.find_log_file(self.session, filename)
            if path is not None:
                try:
                    lines = await asyncio.get_running_loop().run_in_executor(
                        None, worker_logs.read_log_lines, path,
                        int(tail) if tail else None)
                except OSError:
                    lines = None
            if lines is None:
                ring = self.log_ring.get(filename)
                if ring is None:
                    return None
                lines = list(ring)
                if tail:
                    lines = lines[-int(tail):]
            return lines

        async def acquire_lease(options):
            """Grant an idle worker to the requesting client for DIRECT
            task pushes — the reference's lease protocol
            (`normal_task_submitter.cc:328` RequestWorkerLease + `:515`
            PushNormalTask): once granted, same-shape submissions bypass
            this head entirely until the lease is released/revoked.

            With no idle worker, the request WAITS (bounded) for the next
            one instead of failing: under multi-client load the head-path
            queue would otherwise swallow every freed worker before any
            client could re-ask, starving leases exactly when they matter
            most (the r4 multi-client throughput inversion)."""
            w = conn_state.get("worker")
            if w is None:
                return None
            resources = options.get("resources", {"CPU": 1})
            node = self._select_node(resources, options.get("label_selector"),
                                     options.get("scheduling_strategy",
                                                 "hybrid"))
            if node is None:
                # no node has the resources FREE right now — but a node
                # whose total capacity covers the ask will free up; wait
                # there instead of failing (under full load availability
                # is zero by definition, yet that's exactly when a lease
                # pays the most)
                sel = options.get("label_selector")
                feasible = [n for n in self._alive_nodes()
                            if n.matches_labels(sel)
                            and all(n.resources.get(r, 0) >= v
                                    for r, v in resources.items())]
                if not feasible:
                    return None
                node = min(feasible, key=lambda n: n.utilization())
            venv_key = (options.get("runtime_env") or {}).get("pip_key")
            lw = self._idle_worker_on(node, venv_key)
            if lw is None:
                self._request_worker(node, pip_key=venv_key)  # warm the pool
                fut = asyncio.get_running_loop().create_future()
                ent = {"resources": resources,
                       "selector": options.get("label_selector"),
                       "venv_key": venv_key, "node_id": None, "fut": fut}
                self._lease_waiters.append(ent)
                try:
                    lw = await asyncio.wait_for(fut, timeout=1.0)
                except (asyncio.TimeoutError, asyncio.CancelledError):
                    try:
                        self._lease_waiters.remove(ent)
                    except ValueError:
                        pass
                    return None
                # granted pre-acquired by _grant_lease_waiters
            else:
                self._acquire(lw, resources)
            lw.leased_to = w.worker_id
            self._last_dispatch_ts = time.monotonic()
            # head-granted lease = the client either had no feasible view
            # node or a daemon refused (spillback): record it in the merged
            # flight-recorder stream alongside daemon-local grants
            self.sched_totals["head_grants"] += 1
            self.lease_events.append(
                {"ts": time.time(), "kind": "head_grant",
                 "node_id": lw.node_id.hex(),
                 "worker": lw.worker_id.hex()[:12],
                 "client": w.worker_id.hex()[:12]})
            return {"worker_id": lw.worker_id.binary(),
                    "addr": (lw.host or "127.0.0.1", lw.port)}

        async def release_lease(worker_id):
            lw = self.workers.get(WorkerID(worker_id))
            if lw is not None and getattr(lw, "leased_to", None) is not None:
                lw.leased_to = None
                self.notify_task_done(lw)  # resources back + idle + kick
            return True

        async def task_done(task_id):
            w = conn_state.get("worker")
            if w is not None:
                self._task_event(TaskID(task_id), "", "FINISHED", worker=w)
                self.notify_task_done(w)
            return True

        async def worker_retiring():
            # max_calls reached: stop dispatching to this worker; it exits
            # right after its final task_done (reference max_calls semantics)
            w = conn_state.get("worker")
            if w is not None:
                w.retiring = True
                node = self.nodes.get(w.node_id)
                if node is not None and w in node.idle:
                    node.idle.remove(w)
            return True

        def _gen(gen_id: bytes, backpressure: int = 0) -> GeneratorState:
            gs = self.generators.get(gen_id)
            if gs is None:
                gs = self.generators[gen_id] = GeneratorState(backpressure)
            if backpressure:
                gs.backpressure = backpressure
            return gs

        async def generator_yield(gen_id, meta, backpressure=0):
            gs = _gen(gen_id, backpressure)
            self._seal(meta)
            if gs.released:
                # consumer is gone: nothing will ever fetch this item —
                # don't pin or queue it (it evicts once unreferenced)
                return True
            # queued items are pinned until the consumer takes delivery
            # (nobody holds a ref to them yet)
            self._pin(meta.object_id)
            gs.items.append(meta.object_id.binary())
            gs.wake(gs.consumer_waiters)
            # backpressure: hold the producer's reply until consumed catches up
            while (gs.backpressure and not gs.done
                   and len(gs.items) - gs.consumed > gs.backpressure):
                fut = asyncio.get_running_loop().create_future()
                gs.producer_waiters.append(fut)
                await fut
            return True

        async def generator_done(gen_id):
            gs = _gen(gen_id)
            gs.done = True
            gs.wake(gs.consumer_waiters)
            gs.wake(gs.producer_waiters)
            if gs.released:
                self.generators.pop(gen_id, None)
            return True

        async def generator_next(gen_id, index):
            gs = _gen(gen_id)
            gs.consumed = max(gs.consumed, index)
            gs.wake(gs.producer_waiters)
            while True:
                if index < len(gs.items):
                    item = gs.items[index]
                    if index not in gs.delivered:
                        gs.delivered.add(index)
                        # interest transfers to the consumer atomically with
                        # delivery: holder first, then the yield-pin drops —
                        # race-free at zero eviction grace
                        wc = conn_state.get("worker")
                        if wc is not None:
                            self._add_holder(ObjectID(item), wc.worker_id)
                        self._unpin(ObjectID(item))
                    return {"ref": item}
                # a failed generator task seals gen_id itself with the error;
                # the consumer receives it once, after draining real items
                err_meta = self.objects.get(ObjectID(gen_id))
                if err_meta is not None and err_meta.error:
                    return {"ref": gen_id, "error": True}
                if gs.done:
                    return {"done": True}
                fut = asyncio.get_running_loop().create_future()
                gs.consumer_waiters.append(fut)
                await fut

        async def generator_release(gen_id):
            """Consumer dropped its ObjectRefGenerator: unpin undelivered
            items and mark the stream released — NOT popped, or a still-
            producing task's later yields would recreate a fresh state
            whose pins nothing ever drops."""
            gs = self.generators.get(gen_id)
            if gs is None:
                return True
            for idx, item in enumerate(gs.items):
                if idx not in gs.delivered:
                    self._unpin(ObjectID(item))
            gs.released = True
            gs.wake(gs.consumer_waiters)
            gs.wake(gs.producer_waiters)
            if gs.done:
                self.generators.pop(gen_id, None)
            return True

        async def cancel_task(return_id, force=False):
            """ray.cancel: drop a queued task, or interrupt/kill a running
            one (reference CancelTask; force kills the worker)."""
            for rec in list(self.queue):
                if return_id in rec.spec["return_ids"]:
                    self.queue.remove(rec)  # shape-bucket removal
                    rec.cancelled = True
                    self._fail_task(rec, "task was cancelled", cancelled=True)
                    return "cancelled_queued"
            for w in self.workers.values():
                rec = w.current_record
                if rec is not None and return_id in rec.spec["return_ids"]:
                    rec.cancelled = True
                    rec.retries_left = 0
                    if force:
                        self._terminate_worker(w)
                        return "killed"
                    w.conn.push("cancel_task",
                                task_id=rec.spec["task_id"].binary())
                    return "interrupt_sent"
            return "not_found"

        async def actor_ready(actor_id, address):
            info = self.actors.get(ActorID(actor_id))
            if info is not None:
                # workers self-report loopback; substitute the host we see
                # them on so cross-node callers can reach the actor
                w = conn_state.get("worker")
                if w is not None and w.host:
                    address = (w.host, address[1])
                self.notify_actor_ready(info, address)
            return True

        async def actor_creation_failed(actor_id, cause):
            info = self.actors.get(ActorID(actor_id))
            if info is not None:
                w = info.worker
                info.restarts_left = 0  # constructor errors are not retried
                self._mark_actor_dead(info, f"creation failed: {cause}")
                if w is not None:
                    info.worker = None
                    w.actor_id = None
                    self._release(w)
                    node = self.nodes.get(w.node_id)
                    if node is not None and w not in node.idle:
                        node.idle.append(w)
                    self._kick()
            return True

        import inspect

        return {k: v for k, v in locals().items() if inspect.iscoroutinefunction(v)}

    # ---------------------------------------------------------------- sched
    def _enqueue(self, rec: TaskRecord) -> None:
        self._unpin_task(rec)  # no-op for fresh records; retries re-pin
        rec.pinned = [ObjectID(dep) for dep in rec.spec.get("deps", [])]
        for oid in rec.pinned:
            self._pin(oid)  # inputs stay alive until the task finishes
        for dep in rec.spec.get("deps", []):
            oid = ObjectID(dep)
            if oid not in self.objects:
                self._maybe_reconstruct(oid)
                rec.pending_deps.add(oid)
                self.dep_index.setdefault(oid, []).append(rec)
        self.queue.append(rec)
        self._task_event(rec.task_id, rec.spec["options"].get("name", "task"),
                         "PENDING_ARGS_AVAIL" if rec.pending_deps
                         else "PENDING_NODE_ASSIGNMENT")
        self._kick()

    # ------------------------------------------------- object lifetime
    def _pin(self, oid: ObjectID) -> None:
        self.obj_pins[oid] = self.obj_pins.get(oid, 0) + 1
        self.obj_interest_seen.add(oid)
        self._evict_due.pop(oid, None)

    def _add_holder(self, oid: ObjectID, worker_id: WorkerID) -> None:
        """Head-side interest transfer: record `worker_id` as a holder
        ahead of its own (in-flight) ref_update inc, so handing it an
        object over a head-mediated reply is race-free at zero grace."""
        self.obj_holders.setdefault(oid, set()).add(worker_id)
        self.worker_holds.setdefault(worker_id, set()).add(oid)
        self.obj_interest_seen.add(oid)
        self._evict_due.pop(oid, None)

    def _unpin(self, oid: ObjectID) -> None:
        c = self.obj_pins.get(oid, 0) - 1
        if c <= 0:
            self.obj_pins.pop(oid, None)
            self._maybe_evict(oid)
        else:
            self.obj_pins[oid] = c

    def _unpin_task(self, rec: "TaskRecord") -> None:
        for oid in getattr(rec, "pinned", None) or []:
            self._unpin(oid)
        rec.pinned = []

    def _borrow_begin(self, oid: ObjectID, token: bytes,
                      sender: WorkerID) -> None:
        if token in self._committed_tokens:
            # the receiver's commit outraced this begin (distinct head
            # connections): the handoff already completed, drop both sides
            self._committed_tokens.pop(token, None)
            return
        self.borrow_pins[token] = (oid, sender)
        self.obj_borrows.setdefault(oid, set()).add(token)
        self.worker_borrows.setdefault(sender, set()).add(token)
        self.obj_interest_seen.add(oid)
        self._evict_due.pop(oid, None)

    def _borrow_commit(self, oid: ObjectID, token: bytes) -> None:
        ent = self.borrow_pins.pop(token, None)
        if ent is None:
            # begin not seen yet — remember so the late begin is a no-op.
            # Bounded: an overflowed token leaks one pin until its sender
            # dies, it never frees a live object.
            self._committed_tokens[token] = None
            while len(self._committed_tokens) > 200_000:
                self._committed_tokens.popitem(last=False)
            return
        self._drop_borrow(token, ent)

    def _drop_borrow(self, token: bytes, ent: tuple) -> None:
        oid, sender = ent
        toks = self.obj_borrows.get(oid)
        if toks is not None:
            toks.discard(token)
            if not toks:
                self.obj_borrows.pop(oid, None)
                self._maybe_evict(oid)
        sent = self.worker_borrows.get(sender)
        if sent is not None:
            sent.discard(token)
            if not sent:
                self.worker_borrows.pop(sender, None)

    def _maybe_evict(self, oid: ObjectID) -> None:
        if not self.refcount_enabled:
            return
        if (self.obj_holders.get(oid) or self.obj_pins.get(oid)
                or self.obj_borrows.get(oid)
                or self.lineage_dep_pins.get(oid)):
            return
        if oid not in self.obj_interest_seen:
            return  # newborn: its holder's first inc is still in flight
        if oid in self.objects or oid in self.lineage:
            self._evict_due[oid] = time.monotonic() + self.evict_grace_s
        else:
            # nothing registered and no interest left (e.g. a direct
            # actor-call result ref that was dropped): forget the id —
            # interest_seen must not grow by one entry per actor call.
            # The tombstone makes a late-arriving seal free itself.
            self.obj_interest_seen.discard(oid)
            self._tombstones[oid] = None
            while len(self._tombstones) > 100_000:
                self._tombstones.popitem(last=False)

    async def _evict_loop(self) -> None:
        while not self._shutdown:
            await asyncio.sleep(min(max(self.evict_grace_s / 2, 0.05), 1.0))
            if not self._evict_due:
                continue
            now = time.monotonic()
            due = [oid for oid, t in self._evict_due.items() if t <= now]
            for oid in due:
                self._evict_due.pop(oid, None)
                if (self.obj_holders.get(oid) or self.obj_pins.get(oid)
                        or self.obj_borrows.get(oid)
                        or self.lineage_dep_pins.get(oid)):
                    continue
                try:
                    self._drop_object(oid)
                    self.objects_evicted += 1
                    self._publish("object_state",
                                  {"object_id": oid.binary(),
                                   "state": "EVICTED"})
                except Exception as e:
                    # one failing free (e.g. BufferError on an exported shm
                    # mapping) must not kill the eviction loop for the
                    # session — that silently reverts refcounting to a leak
                    print(f"[ray_tpu] evict {oid.hex()} failed: {e!r}",
                          file=sys.stderr, flush=True)

    def _drop_object(self, oid: ObjectID) -> None:
        """Remove an object entirely: storage, directory entry, lineage,
        and the pins it held on nested refs."""
        meta = self.objects.pop(oid, None)
        if meta is not None and meta.kind in objdir.PULLABLE_KINDS:
            # the head's own pull-manager replica dies with the object too
            # (it is never directory-announced, so no push reaches it; a
            # cached copy surviving here could be served stale)
            pm = getattr(self, "pull_manager", None)
            if pm is not None:
                pm.drop(oid)
            # replicas on other nodes die with the canonical object: tell
            # their daemons to unlink before the location knowledge goes
            for node_hex in self.object_dir.locations(oid):
                if meta.node_id is not None \
                        and node_hex == meta.node_id.hex():
                    continue  # the primary; _free_meta reaches it below
                try:
                    n = self.nodes.get(NodeID.from_hex(node_hex))
                except Exception:
                    n = None
                if n is not None and n.conn is not None and n.alive:
                    try:
                        n.conn.push("drop_replica", object_id=oid.binary())
                    except Exception:
                        pass
            self._dir_announce(objdir.free_record(oid))
        self.obj_holders.pop(oid, None)
        for token in self.obj_borrows.pop(oid, set()):
            ent = self.borrow_pins.pop(token, None)
            if ent is not None:
                sent = self.worker_borrows.get(ent[1])
                if sent is not None:
                    sent.discard(token)
        self.obj_interest_seen.discard(oid)
        self._tombstones[oid] = None
        while len(self._tombstones) > 100_000:
            self._tombstones.popitem(last=False)
        self._evict_due.pop(oid, None)
        self._lineage_pop(oid)
        if meta is not None:
            self._free_meta(meta)
            for b in (meta.contained or []):
                self._unpin(ObjectID(b))

    def _lineage_record_spec(self, spec: dict) -> None:
        """Register a task spec as the producer of its return ids (shared
        by head-path submits and out-of-band `record_lineage` pushes)."""
        entry = {"spec": spec, "produced": set(),
                 "recon_left": spec["options"].get("max_retries", 3),
                 "bytes": self._spec_bytes(spec)}
        self._lineage_add_entry(entry)
        for rid in spec["return_ids"]:
            oid = ObjectID(rid)
            self._lineage_pop(oid)
            self.lineage[oid] = entry
            self.lineage_bytes += entry["bytes"]
            if oid in self.objects:
                # the result's seal outraced this record (lease results
                # ride the worker's connection, the record the driver's):
                # mark produced NOW or loss handling would treat the
                # object as still in flight and never reconstruct it
                entry["produced"].add(oid)
        while (len(self.lineage) > self.lineage_cap
               or self.lineage_bytes > self.lineage_bytes_cap):
            oldest = next(iter(self.lineage))
            self._lineage_pop(oldest)

    def _lineage_add_entry(self, entry: dict) -> None:
        """Pin a reconstructable task's inputs: reconstruction needs them
        (reference: lineage pinning in ReferenceCounter)."""
        entry["live_rids"] = len(entry["spec"]["return_ids"])
        for dep in entry["spec"].get("deps", []):
            oid = ObjectID(dep)
            self.lineage_dep_pins[oid] = self.lineage_dep_pins.get(oid, 0) + 1
            self._evict_due.pop(oid, None)

    def _lineage_pop(self, oid: ObjectID):
        old = self.lineage.pop(oid, None)
        if old is None:
            return None
        self.lineage_bytes -= old["bytes"]
        old["live_rids"] = old.get("live_rids", 1) - 1
        if old["live_rids"] <= 0:
            for dep in old["spec"].get("deps", []):
                doid = ObjectID(dep)
                c = self.lineage_dep_pins.get(doid, 0) - 1
                if c <= 0:
                    self.lineage_dep_pins.pop(doid, None)
                    self._maybe_evict(doid)
                else:
                    self.lineage_dep_pins[doid] = c
        return old

    def _free_meta(self, meta: ObjectMeta) -> None:
        """Free an object's storage wherever it lives: locally when this
        process can reach it, and via the owning node's daemon otherwise
        (real multi-host, or namespace isolation)."""
        if meta.kind == "device":
            w = self.workers.get(meta.owner) if meta.owner is not None else None
            if w is not None and w.conn is not None and not w.conn.closed:
                try:
                    w.conn.push("free_device_object",
                                object_id=meta.object_id.binary())
                except Exception:
                    pass
            return
        node = self.nodes.get(meta.node_id) if meta.node_id is not None else None
        if (node is not None and node.conn is not None and node.alive
                and meta.kind in ("shm", "arena", "spilled")):
            try:
                node.conn.push("free_object", meta=meta)
            except Exception:
                pass
        # the owning process must also drop its mapping/accounting — a
        # producer that never sees the eviction keeps the (unlinked) pages
        # mapped and its store's `used` counter inflated forever
        w = self.workers.get(meta.owner) if meta.owner is not None else None
        if w is not None and w.conn is not None and not w.conn.closed:
            try:
                w.conn.push("evicted_object", meta=meta)
            except Exception:
                pass
        if self.store.readable(meta):
            self.store.free(meta)

    def _seal(self, meta: ObjectMeta) -> None:
        if meta.kind in ("shm", "arena") and meta.node_id is not None:
            n = self.nodes.get(meta.node_id)
            if n is None or not n.alive:
                # a stale meta re-registered by a caching client (e.g. the
                # driver passing a ref onward): its data died with the
                # node — sealing it would resurrect a dangling pointer and
                # mask reconstruction
                return
        was_reconstructing = meta.object_id in self._reconstructing
        self._reconstructing.discard(meta.object_id)
        lin = self.lineage.get(meta.object_id)
        if lin is not None:
            # per RETURN id: a sealed sibling must not mark this one
            # reconstructable while its own seal is still in flight
            lin["produced"].add(meta.object_id)
        existing = self.objects.get(meta.object_id)
        if existing is not None:
            # objects are immutable: first seal wins (a racing retry must not
            # replace a good value, especially not with its own error).
            # Only free the loser's storage when it is DISTINCT from the
            # winner's — a re-registration of the same meta (a client
            # passing an adopted actor-reply ref onward) or an arena/device
            # entry keyed by object id refers to the winner's own storage,
            # and freeing it would destroy the live object.
            same_storage = (
                meta.kind == "inline"
                or (meta.kind == "arena" and existing.kind == "arena")
                or (meta.kind == "device" and existing.kind == "device")
                or (meta.kind == "shm" and existing.kind == "shm"
                    and meta.segment == existing.segment)
                or (meta.kind == "spilled" and existing.kind == "spilled"
                    and meta.spill_path == existing.spill_path)
                # re-registration of a stale pre-spill meta: the canonical
                # entry moved to disk but the segment name is its old home —
                # only when the segments actually match; a retried task's
                # duplicate copy has a fresh segment and must be freed
                or (existing.kind == "spilled" and meta.kind == "shm"
                    and meta.segment == existing.segment))
            if not same_storage:
                self._free_meta(meta)  # a genuinely distinct duplicate copy
            return
        self.objects[meta.object_id] = meta
        if was_reconstructing and lin is not None and not meta.error:
            # a genuinely NEW seal of a lost return id (a surviving
            # sibling's duplicate re-seal returns above, so this counts
            # exactly the lost partitions that were rebuilt)
            self.sched_totals["reconstructs"] += 1
            if lin["spec"]["options"].get("data_stage"):
                self.sched_totals["data_reconstructs"] += 1
        if meta.kind in objdir.PULLABLE_KINDS:
            self._dir_announce(objdir.seal_record(meta))
        self._publish("object_state", {"object_id": meta.object_id.binary(),
                                       "state": "SEALED",
                                       "size": meta.size,
                                       "node_id": (meta.node_id.binary()
                                                   if meta.node_id else None)})
        for b in (meta.contained or []):
            self._pin(ObjectID(b))  # nested refs live while container does
        if meta.object_id in self._tombstones:
            # every interest already came and went (ref dropped before the
            # producer finished, or a slow retry's duplicate): free now —
            # the newborn deferral must not resurrect it as a leak
            self.obj_interest_seen.add(meta.object_id)
            self._evict_due[meta.object_id] = time.monotonic()
        self._maybe_evict(meta.object_id)  # fire-and-forget results: nobody
        # may hold a ref by the time the result arrives
        if meta.kind in ("shm", "arena"):
            # accounting + LRU/spill tracking; when the head can't see the
            # object (isolation / real multi-host) the owning node daemon
            # tracks it instead, so capacity enforcement still happens
            if not self.store.adopt(meta):
                n = self.nodes.get(meta.node_id) if meta.node_id else None
                if n is not None and n.conn is not None and n.alive:
                    n.conn.push("adopt_object", meta=meta)
        if meta.error and meta.object_id.binary() in self.generators:
            # a failed generator task: consumers drain produced items, then
            # receive the error ref (generator_next checks this meta)
            gs = self.generators[meta.object_id.binary()]
            gs.done = True
            gs.wake(gs.consumer_waiters)
            gs.wake(gs.producer_waiters)
        for fut in self.object_waiters.pop(meta.object_id, []):
            if not fut.done():
                fut.set_result(meta)
        for rec in self.dep_index.pop(meta.object_id, []):
            rec.pending_deps.discard(meta.object_id)
        self._kick()

    def _alive_nodes(self) -> List[NodeInfo]:
        return [n for n in self.nodes.values() if n.alive]

    def _select_node(self, resources: Dict[str, float],
                     label_selector: Optional[dict] = None,
                     strategy: str = "hybrid") -> Optional[NodeInfo]:
        """Hybrid policy (reference scheduling_policy.h:35-57): prefer the
        head/local node until utilization crosses a threshold, then pack the
        lowest-utilization feasible node; SPREAD picks least-utilized."""
        candidates = [n for n in self._alive_nodes()
                      if n.matches_labels(label_selector) and n.fits(resources)]
        if not candidates:
            return None
        if strategy == "spread":
            return min(candidates, key=lambda n: n.utilization())
        head_first = [n for n in candidates if n.is_head]
        if head_first and head_first[0].utilization() < 0.8:
            return head_first[0]
        return min(candidates, key=lambda n: n.utilization())

    def _pg_for(self, options: dict) -> Optional[PlacementGroupInfo]:
        pgb = options.get("placement_group")
        return self.pgs.get(PlacementGroupID(pgb)) if pgb else None

    def _find_pg_slot(self, pg: PlacementGroupInfo, resources: Dict[str, float],
                      bundle_index: Optional[int]) -> Optional[BundleState]:
        if pg.state != "CREATED":
            return None
        if bundle_index is not None and bundle_index >= 0:
            b = pg.bundles[bundle_index]
            return b if b.fits(resources) else None
        for b in pg.bundles:
            if b.fits(resources):
                return b
        return None

    def _idle_worker_on(self, node: NodeInfo,
                        venv_key: Optional[str] = None
                        ) -> Optional[WorkerInfo]:
        # exact venv match both ways: plain tasks never land on a
        # pip-isolated worker, pip tasks only on THEIR venv's workers
        # (reference per-runtime-env worker pools, worker_pool.h:274)
        for i in range(len(node.idle) - 1, -1, -1):
            w = node.idle[i]
            if w.conn.closed:
                del node.idle[i]
                continue
            if w.venv_key == venv_key:
                del node.idle[i]
                return w
        return None

    def _acquire(self, w: WorkerInfo, resources: Dict[str, float],
                 pg: Optional[PlacementGroupInfo] = None,
                 bundle: Optional[BundleState] = None) -> None:
        if bundle is not None:
            ledger = bundle.available
            w.acquired_pg = pg.pg_id
            w.acquired_bundle = bundle.index
        else:
            ledger = self.nodes[w.node_id].available
            w.acquired_pg = None
            w.acquired_bundle = None
        for r, amt in resources.items():
            ledger[r] = ledger.get(r, 0) - amt
        w.acquired = dict(resources)

    def _release(self, w: WorkerInfo, cpu_only: bool = False) -> None:
        ledger = None
        if w.acquired_pg is not None:
            pg = self.pgs.get(w.acquired_pg)
            if pg is not None and w.acquired_bundle is not None:
                ledger = pg.bundles[w.acquired_bundle].available
        if ledger is None:
            # pg removed while the work ran (or non-pg): back to the node
            node = self.nodes.get(w.node_id)
            ledger = node.available if node is not None else {}
        for r, amt in list(w.acquired.items()):
            if cpu_only and r != "CPU":
                continue
            ledger[r] = ledger.get(r, 0) + amt
            del w.acquired[r]
        if not w.acquired:
            w.acquired_pg = None
            w.acquired_bundle = None

    def _try_dispatch(self, rec: TaskRecord,
                      want_workers: int = 1) -> Optional[str]:
        """Try to place+dispatch one task. Returns None on success, else a
        reason to stay queued ('resources' | 'worker') — or fails the task."""
        options = rec.spec["options"]
        resources = options.get("resources", {"CPU": 1})
        renv = options.get("runtime_env") or {}
        venv_key, pip = renv.get("pip_key"), renv.get("pip")
        if options.get("placement_group"):
            pg = self._pg_for(options)
            if pg is None:
                self._fail_task(rec, "placement group was removed")
                return None
            bundle = self._find_pg_slot(pg, resources,
                                        options.get("placement_group_bundle_index"))
            if bundle is None:
                return "resources"
            node = self.nodes.get(bundle.node_id)
            if node is None or not node.alive:
                return "resources"
            w = self._idle_worker_on(node, venv_key)
            if w is None:
                for _ in range(max(1, want_workers)):
                    self._request_worker(node, pip, venv_key)
                return "worker"
            self._acquire(w, resources, pg, bundle)
        else:
            node = self._select_node(resources, options.get("label_selector"),
                                     options.get("scheduling_strategy", "hybrid"))
            if node is None:
                return "resources"
            w = self._idle_worker_on(node, venv_key)
            if w is None:
                for _ in range(max(1, want_workers)):
                    self._request_worker(node, pip, venv_key)
                return "worker"
            self._acquire(w, resources)
        w.running_task = rec.task_id
        w.current_record = rec
        rec.dispatch_ts = time.time()
        self._last_dispatch_ts = time.monotonic()
        self._task_event(rec.task_id, rec.spec["options"].get("name", "task"),
                         "RUNNING", worker=w)
        spec = rec.spec
        if spec["options"].get("data_stage") and spec.get("deps"):
            # ship the deps' metas with the dispatch so the worker's
            # argument resolution pulls straight through its node's
            # PullManager instead of round-tripping get_meta per block
            # (a reconstructed reduce task resolves rebuilt sub-blocks
            # the same way: a stale meta falls back to locate_object)
            dm = [self.objects.get(ObjectID(d)) for d in spec["deps"]]
            dm = [m for m in dm
                  if m is not None and m.kind in objdir.PULLABLE_KINDS]
            if dm:
                spec = dict(spec)
                spec["dep_metas"] = dm
        w.conn.push("exec_task", spec=spec)
        return None

    def _kick(self) -> None:
        """Dispatch as many queued tasks as possible; spawn workers if useful.

        Re-entrancy-safe: dispatch failure paths (_fail_task → _seal) call
        _kick again; a nested call mutating the deques mid-scan would make
        outer frames pop records the nested pass already handled. Nested
        calls just set a flag and the outermost frame loops."""
        if self._shutdown:
            return
        if getattr(self, "_kick_active", False):
            self._kick_again = True
            return
        self._kick_active = True
        try:
            while True:
                self._kick_again = False
                self._retry_pending_pgs()
                self.queue.scan(self._try_dispatch)
                for info in self.actors.values():
                    if (info.state in ("PENDING", "RESTARTING")
                            and info.worker is None):
                        self._schedule_actor(info)
                if not self._kick_again:
                    break
        finally:
            self._kick_active = False
        self._spawn_for_demand()

    def _schedule_actor(self, info: ActorInfo) -> None:
        options = info.spec["options"]
        resources = options.get("resources", {"CPU": 0})
        renv = options.get("runtime_env") or {}
        venv_key, pip = renv.get("pip_key"), renv.get("pip")
        if options.get("placement_group"):
            pg = self._pg_for(options)
            if pg is None:
                self._mark_actor_dead(info, "placement group was removed")
                return
            bundle = self._find_pg_slot(pg, resources,
                                        options.get("placement_group_bundle_index"))
            if bundle is None:
                return
            node = self.nodes.get(bundle.node_id)
            if node is None or not node.alive:
                return
            w = self._idle_worker_on(node, venv_key)
            if w is None:
                self._request_worker(node, pip, venv_key)
                return
            self._acquire(w, resources, pg, bundle)
        else:
            node = self._select_node(resources, options.get("label_selector"),
                                     options.get("scheduling_strategy", "hybrid"))
            if node is None:
                return
            w = self._idle_worker_on(node, venv_key)
            if w is None:
                self._request_worker(node, pip, venv_key)
                return
            self._acquire(w, resources)
        w.actor_id = info.actor_id
        info.worker = w
        w.conn.push("start_actor", spec=info.spec)

    # -------------------------------------------------------------- workers
    def _request_worker(self, node: NodeInfo, pip=None,
                        pip_key=None) -> None:
        alive = len(node.workers)
        if alive + node.starting_workers >= node.max_workers:
            return
        node.starting_workers += 1
        if node.conn is None:
            self._spawn_local_worker(pip, pip_key)
        else:
            node.conn.push("spawn_worker", pip=pip, pip_key=pip_key)

    def _spawn_for_demand(self) -> None:
        # each queued-but-dispatchable task/actor has already issued a
        # _request_worker for its chosen node inside _try_dispatch; nothing
        # further to do here beyond a safety valve for empty pools
        if not self.queue:
            return
        # fairness valve: reclaim a leased worker ONLY on a genuine
        # dispatch stall (no task dispatched and no lease granted for a
        # while with work queued). Revoking on every transient queue
        # blip cancels leases the instant they're granted, and the
        # resulting all-head-path traffic was the r4 multi-client
        # throughput inversion.
        if time.monotonic() - getattr(self, "_last_dispatch_ts", 0.0) < 0.5:
            return
        for lw in self.workers.values():
            if lw.leased_to is not None:
                holder = self.workers.get(lw.leased_to)
                if (holder is not None and holder.conn is not None
                        and not holder.conn.closed):
                    holder.conn.push("lease_revoke",
                                     worker_id=lw.worker_id.binary())
                    self._last_dispatch_ts = time.monotonic()  # one at a time
                    break

    def _spawn_local_worker(self, pip=None, pip_key=None) -> None:
        from ray_tpu.core.resources import strip_device_env

        env = strip_device_env(dict(os.environ))
        env["RAY_TPU_HEAD_PORT"] = str(self.port)
        env["RAY_TPU_SESSION"] = self.session
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        # head-node workers route remote pulls through the head's data
        # server pull manager (same once-per-node contract as daemons)
        env["RAY_TPU_NODE_DATA_PORT"] = str(self.data_port)
        if not pip:
            self._popen_worker(sys.executable, env)
            return
        # venv materialization runs pip (seconds): NEVER on the head's
        # event loop. Build on a thread, hop back to spawn.
        from ray_tpu.core import runtime_env as _renv

        env["RAY_TPU_VENV_KEY"] = pip_key or _renv.pip_env_key(pip)
        loop = asyncio.get_event_loop()

        def _build():
            try:
                python = _renv.materialize_venv(pip, pip_key)
            except Exception as e:
                print(f"[ray_tpu] venv materialization failed: {e!r}",
                      flush=True)
                # release the starting slot so the request can retry
                loop.call_soon_threadsafe(self._venv_spawn_failed)
                return
            loop.call_soon_threadsafe(self._popen_worker, python, env)

        import threading as _threading

        _threading.Thread(target=_build, daemon=True,
                          name="venv-build").start()

    def _venv_spawn_failed(self) -> None:
        self.head_node.starting_workers = max(
            0, self.head_node.starting_workers - 1)
        self._kick()

    def _popen_worker(self, python: str, env: dict) -> None:
        from ray_tpu.core import worker_logs

        # fd-level stdio capture into the session log dir (reference
        # node.py:1426 worker redirection); unbuffered so a task's print()
        # reaches the tailer (and the driver) promptly
        out, err, tag = worker_logs.open_worker_logs(self.session)
        env = dict(env)
        env["RAY_TPU_LOG_TAG"] = tag
        env.setdefault("PYTHONUNBUFFERED", "1")
        with out, err:
            proc = subprocess.Popen(
                [python, "-m", "ray_tpu.core.worker_main"],
                env=env, stdout=out, stderr=err)
        self._spawned[proc.pid] = proc

    def _on_log_batch(self, entries: List[dict]) -> None:
        """Freshly tailed worker-log lines (local monitor thread or a node
        daemon's push): retain in the ring and stream to every connected
        driver, where they print — a remote task's print() is visible at
        the submitting terminal by default (reference log_monitor →
        pubsub → driver print_logs path)."""
        from ray_tpu.core.worker_logs import RING_LINES

        tags = {w.log_tag: w.pid for w in self.workers.values()
                if getattr(w, "log_tag", None)}
        for e in entries:
            stem = e["file"].rsplit(".", 1)[0]
            pid = tags.get(stem[len("worker-"):]) if \
                stem.startswith("worker-") else None
            if pid is not None:
                e["pid"] = pid
            ring = self.log_ring.get(e["file"])
            if ring is None:
                ring = self.log_ring[e["file"]] = deque(maxlen=RING_LINES)
                from ray_tpu.core.worker_logs import MAX_LOG_FILES_RETAINED

                while len(self.log_ring) > MAX_LOG_FILES_RETAINED:
                    self.log_ring.popitem(last=False)
            else:
                self.log_ring.move_to_end(e["file"])
            ring.extend(e["lines"])
        for w in self.workers.values():
            if w.is_driver and w.conn is not None and not w.conn.closed:
                try:
                    w.conn.push("log_lines", entries=entries)
                except Exception:
                    pass

    def _on_worker_disconnect(self, w: WorkerInfo) -> None:
        # a dead process holds nothing: release its ref interest and any
        # borrow pins it opened that were never committed (payloads it
        # serialized but nobody ever deserialized)
        for token in list(self.worker_borrows.pop(w.worker_id, set())):
            ent = self.borrow_pins.pop(token, None)
            if ent is not None:
                self._drop_borrow(token, ent)
        for oid in self.worker_holds.pop(w.worker_id, set()):
            hs = self.obj_holders.get(oid)
            if hs is not None:
                hs.discard(w.worker_id)
                if not hs:
                    self.obj_holders.pop(oid, None)
                    self._maybe_evict(oid)
        # newborn sweep: objects this process owned whose first inc never
        # flushed (it died inside the flush window) would otherwise defer
        # eviction forever — its death IS the interest event
        for oid, meta in list(self.objects.items()):
            if meta.owner == w.worker_id and oid not in self.obj_interest_seen:
                self.obj_interest_seen.add(oid)
                self._maybe_evict(oid)
        # a dead client's leased workers go back to the pool
        for lw in self.workers.values():
            if lw.leased_to == w.worker_id:
                lw.leased_to = None
                self.notify_task_done(lw)
        if w.pooled:
            # tell the owning daemon its pooled worker died so it drops
            # the pool entry (the resource carve-out was released above
            # via _release once the loop below runs)
            node_ = self.nodes.get(w.node_id)
            if node_ is not None and node_.conn is not None \
                    and not node_.conn.closed:
                try:
                    node_.conn.push("pool_worker_died",
                                    worker_id=w.worker_id.binary())
                except Exception:
                    pass
        self.workers.pop(w.worker_id, None)
        # a dead process's metrics snapshot must stop being scraped — the
        # pre-fix behavior left proc:<id> keys in the _metrics namespace
        # forever, so /metrics reported gauges of processes long gone
        mkey = f"proc:{w.worker_id.hex()}".encode()
        self.kv.pop(("_metrics", mkey), None)
        self._metrics_parsed.pop(mkey, None)
        node = self.nodes.get(w.node_id)
        if node is not None:
            node.workers.discard(w.worker_id)
            if w in node.idle:
                node.idle.remove(w)
            node.unadopted.discard(w)
        self._release(w)
        rec = getattr(w, "current_record", None)
        if rec is not None and w.running_task is not None:
            if rec.cancelled:
                self._fail_task(rec, "task was cancelled", cancelled=True)
            elif rec.retries_left > 0:
                rec.retries_left -= 1
                rec.pending_deps = set()
                self._enqueue(rec)
            else:
                self._fail_task(rec, f"worker {w.worker_id} died (pid {w.pid})")
        if w.actor_id is not None:
            info = self.actors.get(w.actor_id)
            if info is not None and info.state != "DEAD":
                info.worker = None
                info.address = None
                if info.restarts_left != 0:
                    if info.restarts_left > 0:
                        info.restarts_left -= 1
                    info.state = "RESTARTING"
                    info.ready_event = asyncio.Event()
                    self._publish("actor_state", {"actor_id": w.actor_id.binary(),
                                                  "state": "RESTARTING"})
                    self._schedule_actor(info)
                else:
                    self._mark_actor_dead(info, f"worker died (pid {w.pid})")
        if w.is_driver:
            pass  # job cleanup: objects are session-scoped in round 1
        self._kick()

    def _purge_stale_worker(self, w: WorkerInfo) -> None:
        """A superseded WorkerInfo's connection closed after a
        re-registration replaced it in `self.workers`: drop the stale
        object from idle/parked lists, return its resources, and retry
        its in-flight task — WITHOUT the full disconnect teardown (the
        worker id is alive under a fresh WorkerInfo)."""
        node = self.nodes.get(w.node_id)
        if node is not None:
            if w in node.idle:
                node.idle.remove(w)
            node.unadopted.discard(w)
        self._release(w)
        rec = getattr(w, "current_record", None)
        if rec is not None and w.running_task is not None:
            if rec.cancelled:
                self._fail_task(rec, "task was cancelled", cancelled=True)
            elif rec.retries_left > 0:
                rec.retries_left -= 1
                rec.pending_deps = set()
                self._enqueue(rec)
            else:
                self._fail_task(
                    rec, f"worker {w.worker_id} died (pid {w.pid})")
        self._kick()

    def _maybe_reconstruct(self, oid: ObjectID) -> None:
        """Re-run the producing task of a lost object (lineage
        reconstruction, reference `object_recovery_manager.cc`): first seal
        wins, so racing consumers are safe."""
        if oid in self.objects or oid in self._reconstructing:
            return
        entry = self.lineage.get(oid)
        if entry is None:
            if oid in self._lost_pending:
                # lost with lineage, but the entry was cap-evicted before a
                # consumer asked: fail loudly instead of hanging
                self._lost_pending.discard(oid)
                self._seal_lost(oid, "object lost and its lineage entry was "
                                     "evicted before reconstruction")
            return
        if oid not in entry["produced"]:
            # not produced yet → the original task is still in flight; a
            # spurious resubmission here would race it (duplicate writes)
            return
        self._lost_pending.discard(oid)
        spec = entry["spec"]
        if entry["recon_left"] <= 0:
            # reconstruction budget exhausted (flapping node / poisoned
            # task): fail consumers instead of resubmitting forever
            self._seal_lost(oid, "object lost; reconstruction attempts "
                                 "exhausted")
            return
        entry["recon_left"] -= 1
        for rid in spec["return_ids"]:
            self._reconstructing.add(ObjectID(rid))
        self._task_event(spec["task_id"], spec["options"].get("name", "task"),
                         "PENDING_RECONSTRUCTION")
        self.lease_events.append({
            "ts": time.time(), "kind": "object_reconstruct",
            "object_id": oid.hex()[:16],
            "task": spec["options"].get("name", "task"),
            "data_stage": bool(spec["options"].get("data_stage"))})
        self._enqueue(TaskRecord(spec, None))

    @staticmethod
    def _spec_bytes(spec: dict) -> int:
        args = spec.get("args")
        n = 256
        if isinstance(args, (bytes, bytearray, memoryview)):
            n += len(args)
        elif isinstance(args, (list, tuple)):
            n += sum(len(a) for a in args
                     if isinstance(a, (bytes, bytearray, memoryview)))
        return n

    def _node_alive(self, node_id: NodeID) -> bool:
        n = self.nodes.get(node_id)
        return n is not None and n.alive

    def _handle_lost_object(self, oid: ObjectID, where: str) -> None:
        """Every reachable copy of a produced object is gone: drop the
        meta and either reconstruct from lineage or seal an
        ObjectLostError for parked/future consumers. Shared by direct
        node death and last-replica loss (a replica-backed object whose
        primary died earlier loses its final copy later — eviction of
        the replica, or the replica node dying too)."""
        meta = self.objects.pop(oid, None)
        if meta is None:
            return
        self._evict_due.pop(oid, None)
        for b in (meta.contained or []):
            self._unpin(ObjectID(b))
        try:
            # unlink the dead copy's storage now: the meta is the only
            # handle to the arena entry / shm segment, and nothing can
            # free it once replaced by an error or a rebuilt copy
            self.store.free(meta)
        except Exception:
            pass
        entry = self.lineage.get(oid)
        if entry is None or oid not in entry["produced"]:
            # no lineage (ray.put / evicted entry): cannot rebuild —
            # mark lost now so parked AND future consumers raise
            # ObjectLostError instead of hanging forever
            self._seal_lost(
                oid, f"object {oid.hex()} lost with {where} "
                     f"and has no lineage")
        elif oid in self.object_waiters:
            self._maybe_reconstruct(oid)
        else:
            self._lost_pending.add(oid)

    def _seal_lost(self, oid: ObjectID, cause: str) -> None:
        """Seal an error object so parked and future consumers raise
        ObjectLostError instead of hanging forever."""
        from ray_tpu.core import serialization
        from ray_tpu.core.exceptions import ObjectLostError

        err = serialization.serialize(ObjectLostError(cause))
        meta = ObjectMeta(oid, err.frame_bytes, "inline",
                          inline=err.to_bytes(), error=True)
        self._seal(meta)

    def _on_node_disconnect(self, node: NodeInfo) -> None:
        """Node daemon lost: the reference's GcsHealthCheckManager dead-node
        path (node table update + pubsub + per-worker failure handling)."""
        node.alive = False
        self.nodes.pop(node.node_id, None)
        mkey = f"proc:node-{node.node_id.hex()[:12]}".encode()
        self.kv.pop(("_metrics", mkey), None)
        self._metrics_parsed.pop(mkey, None)
        self.lease_events.append({"ts": time.time(), "kind": "node_dead",
                                  "node_id": node.node_id.hex()})
        # its primaries and replicas are unreachable: purge every cached
        # directory's knowledge of them (lost primaries additionally go
        # through _seal_lost/reconstruction below)
        self._dir_announce(objdir.node_dead_record(node.node_id.hex()))
        # objects whose data lived on that node are gone; drop their metas
        # and lazily reconstruct from lineage when next requested (waiters
        # already parked get kicked now)
        lost = [oid for oid, m in self.objects.items()
                if m.node_id == node.node_id
                and m.kind in ("shm", "arena", "device")]
        dead_hex = node.node_id.hex()
        for oid in lost:
            meta = self.objects[oid]
            if meta.kind in ("shm", "arena") and any(
                    h != dead_hex
                    for h in self.object_dir.locations(oid)):
                # a pulled replica on a surviving node still serves the
                # bytes (the node_dead announcement above kept the entry
                # for exactly this case): no loss, no reconstruction
                continue
            self._handle_lost_object(oid, f"node {dead_hex}")
        # objects whose PRIMARY died earlier and that this node carried
        # the LAST replica of just lost their final copy too
        for oid in [o for o, m in self.objects.items()
                    if m.kind in ("shm", "arena")
                    and m.node_id is not None
                    and m.node_id != node.node_id
                    and not self._node_alive(m.node_id)
                    and not self.object_dir.locations(o)]:
            self._handle_lost_object(oid, f"last replica on {dead_hex}")
        self._publish("node_state", {"node_id": node.node_id.binary(),
                                     "state": "DEAD"})
        # PG bundles on that node lose their reservation; re-reserve
        for pg in self.pgs.values():
            if any(b.node_id == node.node_id for b in pg.bundles):
                pg.state = "PENDING"
                pg.ready_event = asyncio.Event()
                for b in pg.bundles:
                    surviving = self.nodes.get(b.node_id)
                    if surviving is not None and b.node_id != node.node_id:
                        for r, amt in b.available.items():
                            surviving.available[r] = surviving.available.get(r, 0) + amt
                    b.node_id = None
                    b.available = {}
                self._try_reserve_pg(pg)
        # workers on the node: their conns will close; handle proactively so
        # retries don't wait on TCP timeouts
        for wid in list(node.workers):
            w = self.workers.get(wid)
            if w is not None and not w.conn.closed:
                asyncio.ensure_future(w.conn.close())
        self._kick()
        self._view_changed()

    def _mark_actor_dead(self, info: ActorInfo, cause: str) -> None:
        info.state = "DEAD"
        info.death_cause = cause
        info.ready_event.set()
        # no further restart will deserialize the creation args: release
        # the borrow pins their pickled refs opened (idempotent)
        self._release_spec_borrows(info.spec)
        self._publish("actor_state", {"actor_id": info.actor_id.binary(),
                                      "state": "DEAD", "cause": cause})

    def _release_spec_borrows(self, spec: dict) -> None:
        for b, token in spec.get("borrows") or []:
            self._borrow_commit(ObjectID(b), token)

    def _terminate_worker(self, w: WorkerInfo) -> None:
        if w.proc is not None:
            try:
                w.proc.kill()
                return
            except ProcessLookupError:
                return
        node = self.nodes.get(w.node_id)
        if node is not None and node.conn is not None and not node.conn.closed:
            node.conn.push("kill_worker", pid=w.pid)
            return
        try:
            os.kill(w.pid, 9)
        except ProcessLookupError:
            pass

    def _fail_task(self, rec: TaskRecord, cause: str,
                   cancelled: bool = False) -> None:
        self._unpin_task(rec)
        from ray_tpu.core import serialization
        from ray_tpu.core.exceptions import (TaskCancelledError,
                                             WorkerCrashedError)

        self._task_event(rec.task_id, rec.spec["options"].get("name", "task"),
                         "FAILED", error=cause)

        exc = (TaskCancelledError(cause) if cancelled
               else WorkerCrashedError(cause))
        err = serialization.serialize(exc)
        for rid in rec.spec["return_ids"]:
            meta = self.store.put_serialized(ObjectID(rid), err)
            meta.error = True
            self._seal(meta)
        self._release_spec_borrows(rec.spec)

    # ---------------------------------------------------- resource view
    def _view_changed(self) -> None:
        """Request an immediate (still coalesced) cluster-view broadcast."""
        if self._view_wake is not None:
            self._view_wake.set()

    # ------------------------------------------------- object directory
    def _dir_announce(self, rec: dict) -> None:
        """Apply a directory record locally and queue it for the next
        cluster_view broadcast. Deliberately does NOT wake the broadcast
        loop: object churn (a put storm) coalesces into one delta list
        per `view_broadcast_s` tick instead of one push per object."""
        if not _config.get("object_directory"):
            return
        self.object_dir.apply_record(rec)
        self._dir_seq += 1
        if len(self._dir_pending) >= 8192:
            # overflow: consumers get a wholesale resync instead of a
            # silently truncated delta stream
            self._dir_pending.clear()
            self._dir_full_resync = True
        else:
            self._dir_pending.append(rec)

    def _serve_loads_payload(self) -> Optional[list]:
        """Changed-only serve-replica load rows for the cluster_view
        broadcast: [{key, ts, stats}] drawn from the same merged
        `__workloads__` telemetry `list_serve_stats` serves. Returns None
        when nothing changed since the last broadcast (idle serve plane
        costs the broadcast nothing)."""
        rows = [{"key": r.get("key"), "ts": r.get("ts"),
                 "stats": r.get("stats")}
                for r in self._workload_rows()
                if r.get("kind") == "serve_replica"]
        rows.sort(key=lambda r: r.get("key") or "")
        if rows == self._last_serve_rows:
            return None
        self._last_serve_rows = rows
        return rows

    def _dir_payload(self) -> Optional[dict]:
        """Drain pending directory records into one broadcast payload."""
        if self._dir_full_resync:
            self._dir_full_resync = False
            self._dir_pending.clear()
            return self.object_dir.full_payload(self._dir_seq)
        if not self._dir_pending:
            return None
        delta, self._dir_pending = self._dir_pending, []
        return {"v": self._dir_seq, "delta": delta}

    def _build_view_snapshot(self) -> dict:
        from ray_tpu.core import resource_view as rv

        nodes = []
        for n in self.nodes.values():
            if not n.alive:
                continue
            # per-node object-store pressure rides the view entries so
            # data-plane producers (the streaming executor's admission)
            # can shed load with zero extra RPCs; daemons gossip
            # store_used/store_cap in their stats, the head reads its own
            frac = None
            if n.is_head:
                cap = getattr(self.store, "capacity", 0)
                if cap:
                    frac = self.store.used / cap
            else:
                st = n.sched_stats or {}
                cap = st.get("store_cap") or 0
                if cap:
                    frac = st.get("store_used", 0) / cap
            nodes.append(rv.make_entry(
                n.node_id.hex(), version=n.view_version, free=n.available,
                total=n.resources, labels=n.labels,
                idle_workers=n.pool_idle, sched_addr=n.sched_addr,
                data_addr=n.data_addr, is_head=n.is_head,
                store_frac=round(frac, 4) if frac is not None else None,
                pool_shapes=n.pool_shapes))
        return {"version": self._view_seq, "nodes": nodes,
                "epoch": self.cluster_epoch}

    async def _resolve_pull_sources(self, meta: ObjectMeta) -> list:
        """Pull-source addresses for the head's own pull manager: the
        authoritative directory's locations, primary first."""
        def addr_of(node_hex: str):
            try:
                n = self.nodes.get(NodeID.from_hex(node_hex))
            except Exception:
                return None
            return n.data_addr if n is not None and n.alive else None

        return objdir.resolve_addrs(self.object_dir, meta, addr_of,
                                    "127.0.0.1",
                                    exclude=self.node_id.hex())

    # ------------------------------------------- sharded view plane
    def _make_view_sub(self, interest, nid) -> Optional[dict]:
        """Resolve a subscriber's declared interest into scoped-send
        state. None (legacy full-fanout) when sharding is off or the
        subscriber declared none; "auto" scopes a node to its own shard
        — the shard carrying its entry and its neighborhood."""
        nshards = int(_config.get("view_shards"))
        if interest is None or nshards <= 1:
            return None
        from ray_tpu.core.resource_view import shard_of

        if interest == "auto":
            if nid is None:
                return None
            scope = [shard_of(nid.hex(), nshards)]
        else:
            scope = sorted({int(s) % nshards for s in interest})
        return {"interest": scope, "sent": {}, "digest_ts": 0.0}

    def _note_shard_changes(self, prev: Optional[dict], cur: dict,
                            nshards: int) -> None:
        """Bump the version of every shard whose node set changed between
        two view snapshots — the delta-compaction cursor scoped
        subscribers are diffed against."""
        from ray_tpu.core.resource_view import shard_of

        prev_by = {e["node_id"]: e for e in (prev or {}).get("nodes", ())}
        cur_by = {e["node_id"]: e for e in cur["nodes"]}
        dirty = set()
        for h, e in cur_by.items():
            if prev_by.get(h) != e:
                dirty.add(shard_of(h, nshards))
        for h in prev_by:
            if h not in cur_by:
                dirty.add(shard_of(h, nshards))
        for sid in dirty:
            self._shard_vs[sid] = self._shard_vs.get(sid, 0) + 1

    def _build_view_digest(self, snap: dict, nshards: int) -> dict:
        """Compact cluster-wide summary shipped with every scoped
        payload: the spillback-candidate rows (top warm pools, what a
        daemon needs to pick a peer outside its interest shards) and the
        total node count — O(digest_k), independent of cluster size."""
        k = max(int(_config.get("view_digest_k")), 1)
        cands = [e for e in snap["nodes"] if e.get("sched_addr")]
        cands.sort(key=lambda e: e.get("idle_workers", 0), reverse=True)
        return {"nshards": nshards, "total_nodes": len(snap["nodes"]),
                "candidates": [
                    {"node_id": e["node_id"],
                     "sched_addr": tuple(e["sched_addr"]),
                     "idle_workers": e.get("idle_workers", 0),
                     "labels": e.get("labels") or {},
                     "pool_shapes": e.get("pool_shapes")}
                    for e in cands[:k]]}

    def _dir_record_scope(self, rec: dict, nshards: int):
        """Shard set a directory record is relevant to, or None for
        global records (frees/node-death are small removal facts every
        consumer needs; a record for a node outside a subscriber's
        interest is skipped — that subscriber cold-misses into the
        locate_object fallback, which is the documented semantics)."""
        from ray_tpu.core.resource_view import shard_of

        op = rec.get("op")
        if op in ("seal", "spill"):
            nid = rec["meta"].node_id
            return {shard_of(nid.hex(), nshards)} if nid is not None \
                else None
        if op in ("replica", "replica_gone"):
            sids = {shard_of(rec["node"], nshards)}
            ent = self.object_dir.entries.get(ObjectID(rec["oid"]))
            if ent is not None and ent.meta.node_id is not None:
                sids.add(shard_of(ent.meta.node_id.hex(), nshards))
            return sids
        return None  # free / node_dead: global

    def _scope_dir_payload(self, payload: Optional[dict], interest,
                           nshards: int,
                           scopes: Optional[list] = None) -> Optional[dict]:
        """Filter one directory broadcast payload to a subscriber's
        interest shards. `scopes` carries the per-record scope sets
        precomputed once per tick for delta payloads."""
        if payload is None or interest is None:
            return payload
        want = set(interest)
        if payload.get("full") is not None:
            from ray_tpu.core.resource_view import shard_of

            kept = []
            for ent in payload["full"]:
                nid = ent["meta"].node_id
                sids = set()
                if nid is not None:
                    sids.add(shard_of(nid.hex(), nshards))
                sids.update(shard_of(h, nshards)
                            for h in ent.get("replicas") or ())
                if not sids or sids & want:
                    kept.append(ent)
            # prefix bindings are global facts (any decode node may need
            # any prefix) — they ride every scoped resync uncut
            return {"v": payload["v"], "full": kept,
                    "prefixes": payload.get("prefixes") or []}
        delta = payload.get("delta") or ()
        if scopes is None:
            scopes = [self._dir_record_scope(r, nshards) for r in delta]
        kept = [r for r, sids in zip(delta, scopes)
                if sids is None or sids & want]
        if not kept:
            return None
        return {"v": payload["v"], "delta": kept}

    def _scoped_view_payload(self, sub: dict, snap: dict, nshards: int,
                             digest: dict, shard_entries: dict,
                             dir_payload, dir_scopes, serve_payload,
                             now: float, refresh_s: float) -> Optional[dict]:
        """Build one scoped subscriber's payload for this tick: only its
        interest shards whose version moved past what it was last sent
        (each as a wholesale shard snapshot — replace semantics need no
        tombstones), its scoped slice of the directory delta, and the
        digest. None when it owes nothing this tick (digest refreshes
        ride a slower cadence than the broadcast loop)."""
        shards = []
        for sid in sub["interest"]:
            v = self._shard_vs.get(sid, 0)
            if v > sub["sent"].get(sid, -1):
                shards.append({"sid": sid, "v": v,
                               "nodes": shard_entries.get(sid, [])})
        objects = self._scope_dir_payload(dir_payload, sub["interest"],
                                          nshards, scopes=dir_scopes)
        if (not shards and objects is None and serve_payload is None
                and now - sub["digest_ts"] < refresh_s):
            return None
        for b in shards:
            sub["sent"][b["sid"]] = b["v"]
        sub["digest_ts"] = now
        payload = {"version": snap["version"], "epoch": self.cluster_epoch,
                   "nshards": nshards, "shards": shards, "digest": digest}
        if objects is not None:
            payload["objects"] = objects
        if serve_payload is not None:
            payload["workloads"] = serve_payload
        return payload

    def _push_full_view(self, conn, pubsub: bool = False,
                        sub: Optional[dict] = None) -> None:
        """Push the current view with a WHOLESALE object-directory payload
        to one connection (a late subscriber or a (re)registered daemon):
        delta broadcasts only carry changes since the last tick, and a
        joiner that missed history must not cold-miss on every object.
        Daemons take the raw `cluster_view` push; drivers/workers get the
        pubsub-wrapped flavor their subscription expects. A scoped
        subscriber (`sub`) gets ALL its interest shards as snapshots at
        their current versions plus the digest — never the full list."""
        snap = dict(self._last_view_snap or self._build_view_snapshot())
        snap.setdefault("version", self._view_seq)
        dir_on = _config.get("object_directory")
        if sub is not None:
            from ray_tpu.core.resource_view import shard_of

            nshards = int(_config.get("view_shards"))
            shard_entries: Dict[int, list] = {}
            for e in snap["nodes"]:
                shard_entries.setdefault(
                    shard_of(e["node_id"], nshards), []).append(e)
            # reset the send cursor so _scoped_view_payload emits EVERY
            # interest shard as a fresh snapshot (one format owner for
            # registration-time and broadcast-tick scoped payloads)
            sub["sent"] = {}
            sub["digest_ts"] = 0.0
            snap = self._scoped_view_payload(
                sub, snap, nshards,
                self._build_view_digest(snap, nshards), shard_entries,
                (self.object_dir.full_payload(self._dir_seq)
                 if dir_on else None), None,
                self._last_serve_rows or None, time.monotonic(),
                refresh_s=0.0)
        else:
            if dir_on:
                snap["objects"] = self.object_dir.full_payload(self._dir_seq)
            if self._last_serve_rows:
                # late joiners get the current serve-load rows immediately
                # instead of waiting for the next row change
                snap["workloads"] = self._last_serve_rows
        try:
            if pubsub:
                conn.push("pubsub", channel="cluster_view", msg=snap)
            else:
                conn.push("cluster_view", snap=snap)
        except Exception:
            pass

    async def _view_broadcast_loop(self) -> None:
        """Debounced push of the compacted cluster view to every node
        daemon and every subscribed driver (the head half of the
        ray_syncer role). Broadcasts only when the view actually changed;
        `_view_changed` wakes it early (node join/death, gossip delta).

        With `view_shards` > 1 the fan-out is interest-scoped: scoped
        subscribers receive only their changed interest shards (as shard
        snapshots versioned per shard) plus the compact digest, so a
        single node's pool churn costs O(shard size × interested
        subscribers), not O(nodes × subscribers) — the full-fanout
        broadcast that capped the gossip smoke at ~200 virtual nodes."""
        interval = _config.get("view_broadcast_s")
        if interval <= 0:
            return
        self._view_wake = asyncio.Event()
        while not self._shutdown:
            try:
                await asyncio.wait_for(self._view_wake.wait(), interval)
            except asyncio.TimeoutError:
                pass
            self._view_wake.clear()
            nshards = int(_config.get("view_shards"))
            sharding = nshards > 1
            snap = self._build_view_snapshot()
            nodes_changed = (self._last_view_snap is None
                             or snap["nodes"] != self._last_view_snap["nodes"])
            dir_payload = self._dir_payload()
            serve_payload = self._serve_loads_payload()
            refresh_s = float(_config.get("view_digest_refresh_s"))
            now_m = time.monotonic()
            digest_due = sharding and (
                any((now_m - n.view_sub["digest_ts"]) >= refresh_s
                    for n in self.nodes.values()
                    if n.view_sub is not None and n.alive)
                or any((now_m - s["digest_ts"]) >= refresh_s
                       for s in self._sub_views.values()))
            if (not nodes_changed and dir_payload is None
                    and serve_payload is None and not digest_due):
                continue
            if nodes_changed:
                self._view_seq += 1
                snap["version"] = self._view_seq
                if sharding:
                    self._note_shard_changes(self._last_view_snap, snap,
                                             nshards)
                self._last_view_snap = snap
            else:
                # object-directory-only tick: reuse the current view body
                # (version unchanged — consumers' version bookkeeping is
                # for the NODE entries; directory ordering rides dir v)
                snap = dict(self._last_view_snap)
            full_snap = snap
            if dir_payload is not None:
                full_snap = dict(full_snap)
                full_snap["objects"] = dir_payload
            if serve_payload is not None:
                full_snap = dict(full_snap)
                full_snap["workloads"] = serve_payload
            digest = shard_entries = dir_scopes = None
            if sharding:
                from ray_tpu.core.resource_view import shard_of

                digest = self._build_view_digest(snap, nshards)
                shard_entries = {}
                for e in snap["nodes"]:
                    shard_entries.setdefault(
                        shard_of(e["node_id"], nshards), []).append(e)
                if dir_payload is not None and dir_payload.get("delta"):
                    dir_scopes = [self._dir_record_scope(r, nshards)
                                  for r in dir_payload["delta"]]
            now = time.monotonic()
            for node in self.nodes.values():
                if node.conn is None or not node.alive or node.conn.closed:
                    continue
                if sharding and node.view_sub is not None:
                    payload = self._scoped_view_payload(
                        node.view_sub, snap, nshards, digest,
                        shard_entries, dir_payload, dir_scopes,
                        serve_payload, now, refresh_s)
                    if payload is None:
                        continue
                    try:
                        node.conn.push("cluster_view", snap=payload)
                    except Exception:
                        pass
                    continue
                try:
                    node.conn.push("cluster_view", snap=full_snap)
                except Exception:
                    pass
            if sharding and self._sub_views:
                # scoped pubsub subscribers (pruned with their conns)
                for conn in [c for c in self._sub_views if c.closed]:
                    del self._sub_views[conn]
                for conn, sub in self._sub_views.items():
                    payload = self._scoped_view_payload(
                        sub, snap, nshards, digest, shard_entries,
                        dir_payload, dir_scopes, serve_payload, now,
                        refresh_s)
                    if payload is not None:
                        try:
                            conn.push("pubsub", channel="cluster_view",
                                      msg=payload)
                        except Exception:
                            pass
            conns = self.subscribers.get("cluster_view")
            if conns:
                live = [c for c in conns if not c.closed]
                if len(live) != len(conns):
                    self.subscribers["cluster_view"] = live  # prune dead
                scoped = ({id(c) for c in self._sub_views}
                          if sharding else ())
                for conn in live:
                    if id(conn) in scoped:
                        continue  # already served a scoped payload above
                    conn.push("pubsub", channel="cluster_view",
                              msg=full_snap)

    async def _pool_reclaim_loop(self) -> None:
        """Anti-starvation reclaim: daemon pools borrow ledger capacity,
        and nothing used to force it back before pool_idle_s — so a
        head-queued task whose only feasible nodes are fully pooled
        starved for the whole idle window. When dep-free queued tasks
        can't fit anywhere but a feasible node gossips idle POOL
        workers, push a pool_trim: the daemon releases one matching
        worker through the normal ack-tracked path and the queue drains
        within a tick instead of a pool-idle period."""
        while not self._shutdown:
            await asyncio.sleep(1.0)
            if not self.queue or self._shutdown:
                continue
            now = time.monotonic()
            needed = []
            for rec in self.queue:
                if rec.pending_deps:
                    continue
                needed.append(
                    (rec.spec["options"].get("resources") or {"CPU": 1},
                     rec.spec["options"].get("label_selector")))
                if len(needed) >= 8:
                    break
            for res, sel in needed:
                if any(n.alive and n.matches_labels(sel) and n.fits(res)
                       for n in self.nodes.values()):
                    continue  # schedulable: the normal kick will place it
                for node in self.nodes.values():
                    if (node.alive and node.conn is not None
                            and not node.conn.closed
                            and node.pool_idle > 0
                            and node.matches_labels(sel)
                            and node.could_ever_fit(res)
                            and not node.fits(res)
                            and now - getattr(node, "_last_trim_ts", 0.0)
                            > 2.0):
                        node._last_trim_ts = now
                        self.lease_events.append(
                            {"ts": time.time(), "kind": "pool_trim",
                             "node_id": node.node_id.hex()})
                        try:
                            node.conn.push("pool_trim", resources=res)
                        except Exception:
                            pass
                        break

    def _publish(self, channel: str, msg: dict) -> None:
        conns = self.subscribers.get(channel)
        if not conns:
            return
        live = [c for c in conns if not c.closed]
        if len(live) != len(conns):
            self.subscribers[channel] = live   # prune dead subscribers
        for conn in live:
            conn.push("pubsub", channel=channel, msg=msg)

    # ------------------------------------------------------------------ pgs
    def _retry_pending_pgs(self) -> None:
        for pg in self.pgs.values():
            if pg.state == "PENDING":
                self._try_reserve_pg(pg)

    def _try_reserve_pg(self, pg: PlacementGroupInfo) -> None:
        """Strategy-aware bundle placement with all-or-nothing commit
        (semantics of GcsPlacementGroupScheduler's 2-phase protocol collapsed
        into the head's single ledger view)."""
        nodes = self._alive_nodes()
        if not nodes:
            return
        scratch = {n.node_id: dict(n.available) for n in nodes}
        assignment: List[Optional[NodeID]] = []
        strategy = pg.strategy
        if strategy in ("PACK", "STRICT_PACK"):
            # try single-node packing first (required for STRICT_PACK)
            packed = None
            for n in nodes:
                trial = dict(scratch[n.node_id])
                ok = True
                for b in pg.bundles:
                    if all(trial.get(r, 0) >= amt - 1e-9 for r, amt in b.resources.items()):
                        for r, amt in b.resources.items():
                            trial[r] = trial.get(r, 0) - amt
                    else:
                        ok = False
                        break
                if ok:
                    packed = n.node_id
                    break
            if packed is not None:
                assignment = [packed] * len(pg.bundles)
            elif strategy == "STRICT_PACK":
                return  # stays PENDING
            else:  # PACK falls back to best-effort spread
                assignment = self._greedy_assign(pg, nodes, scratch, distinct=False)
        elif strategy == "STRICT_SPREAD":
            assignment = self._greedy_assign(pg, nodes, scratch, distinct=True)
        else:  # SPREAD: best-effort distinct, fall back to reuse
            assignment = (self._greedy_assign(pg, nodes, scratch, distinct=True)
                          or self._greedy_assign(pg, nodes, scratch, distinct=False))
        if not assignment or any(a is None for a in assignment):
            return  # stays PENDING
        # commit
        for b, nid in zip(pg.bundles, assignment):
            node = self.nodes[nid]
            for r, amt in b.resources.items():
                node.available[r] = node.available.get(r, 0) - amt
            b.node_id = nid
            b.available = dict(b.resources)
        pg.state = "CREATED"
        pg.ready_event.set()

    def _greedy_assign(self, pg: PlacementGroupInfo, nodes: List[NodeInfo],
                       scratch: dict, distinct: bool) -> Optional[List[NodeID]]:
        avail = {nid: dict(v) for nid, v in scratch.items()}
        used: Set[NodeID] = set()
        out: List[Optional[NodeID]] = []
        for b in pg.bundles:
            placed = None
            for n in sorted(nodes, key=lambda n: n.utilization()):
                if distinct and n.node_id in used:
                    continue
                a = avail[n.node_id]
                if all(a.get(r, 0) >= amt - 1e-9 for r, amt in b.resources.items()):
                    for r, amt in b.resources.items():
                        a[r] = a.get(r, 0) - amt
                    placed = n.node_id
                    used.add(n.node_id)
                    break
            if placed is None:
                return None
            out.append(placed)
        return out

    # ---------------------------------------------------------------- state
    # ------------------------------------------------------ fault tolerance
    def snapshot_path(self) -> str:
        from ray_tpu.utils.platform import STATE_DIR

        return os.path.join(STATE_DIR, self.session, "head_snapshot.bin")

    def save_snapshot(self) -> None:
        """Persist durable control-plane state (reference: Redis-backed GCS
        tables, `src/ray/gcs/store_client/redis_store_client`): the KV
        (incl. exported function/class defs), detached-actor specs, named
        registrations, PG specs, and terminal job views. Worker/actor
        processes are NOT durable — detached actors are re-created from
        their specs on restore, matching GcsActorManager restart semantics."""
        import pickle

        detached = {a.binary(): i.spec for a, i in self.actors.items()
                    if i.spec["options"].get("lifetime") == "detached"
                    and i.state != "DEAD"}
        jobs = {}
        if getattr(self, "job_manager", None) is not None:
            jobs = {j["job_id"]: j for j in self.job_manager.list()
                    if j["status"] in ("SUCCEEDED", "FAILED", "STOPPED")}
        # _runtime_env blobs (up to GiBs of content-addressed zips) are
        # immutable: persist each once as its own file instead of
        # re-pickling them into every 2 s snapshot cycle.
        self._persist_runtime_env_blobs()
        snap = {
            "session": self.session,
            # identity is durable: metas/labels stamped with the head's
            # node id must stay valid across a restart, or every replayed
            # shm object looks like it came from a dead node
            "node_id": self.node_id.binary(),
            "kv": {k: v for k, v in self.kv.items()
                   if k[0] not in ("_metrics", "_runtime_env")},
            "detached_actors": detached,
            "named_actors": {ns_name: a.binary() for ns_name, a in
                             self.named_actors.items()},
            "pgs": {p.binary(): {"bundles": [b.resources for b in g.bundles],
                                 "strategy": g.strategy, "name": g.name}
                    for p, g in self.pgs.items() if g.state != "REMOVED"},
            "jobs": jobs,
            "job_counter": self.job_counter,
            "epoch": self.cluster_epoch,
            # freed-object tombstones: the reconcile fence that stops a
            # daemon's post-restart inventory re-advertisement from
            # resurrecting an object freed just before the head died
            # (bounded at 100k ids, ~1.6 MB worst case)
            "tombstones": [o.binary() for o in self._tombstones],
        }
        self._write_snapshot(snap)

    def _persist_runtime_env_blobs(self) -> None:
        """Write each content-addressed _runtime_env blob to its own file
        under <state>/<session>/runtime_env/ exactly once (they never
        change), so snapshots stay small and fast."""
        blobs = [(k, v) for k, v in self.kv.items() if k[0] == "_runtime_env"]
        if not blobs:
            return
        # NB: dedicated subdir — STATE_DIR/<session>/runtime_env/ is where
        # workers EXTRACT packages (runtime_env.py _fetch_extract); mixing
        # the head's blob mirror into it would make restore trip over
        # extraction directories.
        d = os.path.join(os.path.dirname(self.snapshot_path()),
                         "runtime_env_blobs")
        os.makedirs(d, exist_ok=True)
        for (_, key), value in blobs:
            if not isinstance(key, bytes):
                continue  # internal producers always use bytes keys; a
                # str key is untrusted client input — never a filename
            path = os.path.join(d, key.hex())
            if os.path.exists(path):
                continue
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(value if isinstance(value, bytes) else bytes(value))
            os.replace(tmp, path)

    def _restore_runtime_env_blobs(self) -> None:
        d = os.path.join(os.path.dirname(self.snapshot_path()),
                         "runtime_env_blobs")
        if not os.path.isdir(d):
            return
        # oldest-first (mtime) so the repopulated KV keeps the
        # insertion-order-is-age property _bound_runtime_env_cache evicts by
        def _mtime(n):
            try:
                return os.path.getmtime(os.path.join(d, n))
            except OSError:
                return 0.0

        for name in sorted(os.listdir(d), key=_mtime):
            path = os.path.join(d, name)
            if name.endswith(".tmp") or not os.path.isfile(path):
                continue
            try:
                # keys in this namespace are always bytes (uri.encode());
                # skip anything that isn't our own hex naming
                key = bytes.fromhex(name)
            except ValueError:
                continue
            if ("_runtime_env", key) in self.kv:
                continue
            with open(path, "rb") as f:
                self.kv[("_runtime_env", key)] = f.read()
        # the cap is normally enforced on kv_put; re-apply after bulk load
        self._bound_runtime_env_cache(0)

    def _write_snapshot(self, snap: dict) -> None:
        import pickle

        path = self.snapshot_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(snap, f)
        os.replace(tmp, path)

    def restore_snapshot(self) -> bool:
        """Reload durable state after a head restart; detached actors are
        re-registered PENDING and reschedule as workers come up."""
        import pickle

        path = self.snapshot_path()
        if not os.path.exists(path):
            return False
        with open(path, "rb") as f:
            snap = pickle.load(f)
        if snap.get("node_id"):
            # adopt the predecessor's node identity (see save_snapshot)
            new_id = NodeID(snap["node_id"])
            if new_id != self.node_id:
                old_id = self.node_id
                self.nodes[new_id] = self.nodes.pop(self.node_id)
                self.node_id = new_id
                self.head_node.node_id = new_id
                if self.store.namespace == old_id.hex()[:8]:
                    # isolation mode derived the store namespace from the
                    # pre-adoption id: rebuild under the adopted id or no
                    # client (they resolve by the ADOPTED id) can map our
                    # arena — and replayed metas couldn't be opened here
                    cap = self.store.capacity
                    # keep spill files: surviving daemons/processes may
                    # still re-advertise objects spilled under them
                    self.store.shutdown(sweep_spill=False)
                    self.store = SharedMemoryStore(
                        self.session, capacity_bytes=cap, create_arena=True,
                        namespace=new_id.hex()[:8])
        # epoch fencing across the restart: strictly above the snapshot's
        # epoch even if the wall clock went backwards, so every pre-restart
        # grant/carve-out tag is verifiably stale
        self.cluster_epoch = max(self.cluster_epoch,
                                 int(snap.get("epoch", 0)) + 1)
        for oid_b in snap.get("tombstones") or ():
            # restore the freed-object fence so daemon inventory
            # re-advertisement can't resurrect a pre-restart free
            self._tombstones[ObjectID(oid_b)] = None
        self.kv.update(snap["kv"])
        # metrics snapshots are per-process and every pre-restart process's
        # connection died with the old head: restoring them would scrape
        # ghosts (the exact leak the disconnect expiry fixes); live
        # processes re-push within one metrics interval of reconnecting
        for k in [k for k in self.kv if k[0] == "_metrics"]:
            del self.kv[k]
        self._metrics_parsed.clear()
        self._restore_runtime_env_blobs()
        self.job_counter = snap.get("job_counter", 0)
        # PGs first: restored actors may be bound to a PG bundle — without
        # the PG entry, _schedule_actor would mark them DEAD on arrival
        for pg_b, view in snap.get("pgs", {}).items():
            pgid = PlacementGroupID(pg_b)
            if pgid not in self.pgs:
                pg = PlacementGroupInfo(pgid, view["bundles"],
                                        view["strategy"],
                                        view.get("name", ""))
                self.pgs[pgid] = pg
                self._try_reserve_pg(pg)
        for aid_b, spec in snap["detached_actors"].items():
            aid = ActorID(aid_b)
            info = ActorInfo(aid, spec)
            self.actors[aid] = info
            self._schedule_actor(info)
        for ns_name, aid_b in snap["named_actors"].items():
            aid = ActorID(aid_b)
            if aid in self.actors:
                self.named_actors[tuple(ns_name)] = aid
        if getattr(self, "job_manager", None) is not None:
            from ray_tpu.core.job_manager import JobInfo

            for jid, view in snap["jobs"].items():
                info = JobInfo(jid, view["entrypoint"], view["metadata"])
                info.status = view["status"]
                info.message = view["message"]
                info.start_time = view["start_time"]
                info.end_time = view["end_time"]
                info.log_path = view["log_path"]
                self.job_manager.jobs[jid] = info
        self._spawn_for_demand()
        return True

    async def _snapshot_loop(self, interval_s: float = 2.0) -> None:
        failures = 0
        while not self._shutdown:
            await asyncio.sleep(interval_s)
            try:
                # state collection is quick and runs on the loop; the
                # multi-MB pickle+write runs in a thread so head RPCs
                # (submits, heartbeats) never stall behind disk IO
                await asyncio.to_thread(self.save_snapshot)
                failures = 0
            except Exception as e:
                failures += 1
                if failures in (1, 10) or failures % 100 == 0:
                    # silent persistence failure = fault tolerance silently
                    # off; log with backoff instead of spamming
                    print(f"[ray_tpu] head snapshot failed x{failures}: "
                          f"{e!r}", file=sys.stderr, flush=True)

    def _bound_runtime_env_cache(self, incoming: int) -> None:
        """Evict oldest runtime_env packages beyond the byte cap (no URI
        refcounting — workers keep extracted copies, so only a cold worker
        after eviction would refetch-and-fail, matching a bounded cache)."""
        cap = _config.get("runtime_env_cache_bytes")
        entries = [(k, v) for k, v in self.kv.items()
                   if k[0] == "_runtime_env"]
        total = sum(len(v) for _, v in entries) + incoming
        for k, v in entries:  # dict order = insertion order = oldest first
            if total <= cap:
                break
            del self.kv[k]
            self._drop_runtime_env_blob_file(k[1])
            total -= len(v)

    def _drop_runtime_env_blob_file(self, key) -> None:
        """Keep the on-disk blob mirror in lockstep with KV eviction —
        otherwise restore resurrects evicted packages and disk grows
        unboundedly across the session."""
        if not isinstance(key, bytes):
            return  # hex() naming only ever mirrors bytes keys; a str key
            # must not become a path component (traversal risk)
        path = os.path.join(os.path.dirname(self.snapshot_path()),
                            "runtime_env_blobs", key.hex())
        try:
            os.unlink(path)
        except OSError:
            pass

    def _list_state(self, kind: str):
        if kind == "actors":
            return [{"actor_id": a.hex(), "state": i.state,
                     "name": i.spec["options"].get("name"),
                     "node_id": (i.worker.node_id.hex() if i.worker else None),
                     "restarts_left": i.restarts_left}
                    for a, i in self.actors.items()]
        if kind == "workers":
            return [{"worker_id": w.hex(), "pid": i.pid, "is_driver": i.is_driver,
                     "node_id": i.node_id.hex(),
                     "log_tag": getattr(i, "log_tag", None),
                     "actor": i.actor_id.hex() if i.actor_id else None,
                     "task": i.running_task.hex() if i.running_task else None}
                    for w, i in self.workers.items()]
        if kind == "objects":
            return [{"object_id": o.hex(), "size": m.size, "kind": m.kind}
                    for o, m in self.objects.items()]
        if kind == "tasks":
            return [{"task_id": r.task_id.hex(),
                     "name": r.spec["options"].get("name"),
                     "pending_deps": len(r.pending_deps)} for r in self.queue]
        if kind == "task_events":
            return list(self.task_events)
        if kind == "lease_events":
            return list(self.lease_events)
        if kind == "scheduler_stats":
            return self._scheduler_stats()
        if kind == "trace_spans":
            return list(self.trace_spans.values())
        if kind == "workload_stats":
            return self._workload_rows()
        if kind == "serve_stats":
            return [r for r in self._workload_rows()
                    if str(r.get("kind", "")).startswith("serve")]
        if kind == "nodes":
            return [{"node_id": n.node_id.hex(), "resources": n.resources,
                     "available": n.available, "labels": n.labels,
                     "is_head": n.is_head, "alive": n.alive}
                    for n in self.nodes.values()]
        if kind == "placement_groups":
            return [{"pg_id": p.hex(), "state": g.state, "strategy": g.strategy,
                     "bundles": [{"resources": b.resources,
                                  "node_id": b.node_id.hex() if b.node_id else None}
                                 for b in g.bundles]}
                    for p, g in self.pgs.items()]
        raise ValueError(f"unknown state kind {kind}")

    def _scheduler_stats(self) -> List[dict]:
        """Per-node two-level-scheduler telemetry rows (flight recorder):
        the head's view-sync bookkeeping + each daemon's gossiped lifetime
        counters and gossip health, plus one row for the head itself."""
        now = time.time()
        rows = []
        for n in self.nodes.values():
            if n.is_head:
                continue
            rows.append({
                "node_id": n.node_id.hex(), "alive": n.alive,
                "is_head": False, "idle_workers": n.pool_idle,
                "leased_workers": n.pool_leased,
                # head-side carve-out view vs the daemon's gossiped pool:
                # after reconciliation these must agree (no double-grant,
                # no leaked carve-out)
                "pooled_workers": sum(
                    1 for w in self.workers.values()
                    if w.node_id == n.node_id and w.pooled),
                "reconciled": n.reconciled,
                "pending_pool": len(n.pending_pool),
                "view_version": n.view_version,
                "staleness_s": round(now - n.last_delta_ts, 3),
                "gossip": dict(n.gossip_health),
                "local_grants": 0, "spillbacks": 0,  # until first delta
                **{k: v for k, v in n.sched_stats.items()},
            })
        rows.append({
            "node_id": self.node_id.hex(), "alive": True, "is_head": True,
            "view_version": self._view_seq,
            "epoch": self.cluster_epoch,
            "staleness_s": 0.0, "gossip": {},
            "lease_events_buffered": len(self.lease_events),
            **{k: v for k, v in self.sched_totals.items()},
        })
        return rows

    # ------------------------------------------- workload flight recorder
    def _adopt_spans(self, spans, proc: str, node: Optional[str]) -> None:
        cap = max(int(_config.get("tracing_head_spans")), 2)
        for s in spans:
            sid = s.get("span_id")
            if not sid:
                continue
            self.trace_spans[sid] = {**s, "proc": proc,
                                     "node": node or proc}
        while len(self.trace_spans) > cap:
            self.trace_spans.popitem(last=False)

    def _parsed_snapshots(self):
        """(key, parsed payload) for every live _metrics KV entry, via
        the decode-once cache (cold entries — e.g. restored from disk —
        are parsed and cached on first read)."""
        import json as _json

        for (ns, key), value in list(self.kv.items()):
            if ns != "_metrics":
                continue
            payload = self._metrics_parsed.get(key)
            if payload is None:
                try:
                    payload = _json.loads(value)
                except Exception:
                    continue
                self._metrics_parsed[key] = payload
            yield key, payload

    def _workload_rows(self) -> List[dict]:
        """Live-load telemetry merged from every process's pushed/gossiped
        `__workloads__` family (serve replicas, proxies, train workers)."""
        rows: List[dict] = []
        for key, payload in self._parsed_snapshots():
            for fam in payload:
                if fam.get("name") != "__workloads__":
                    continue
                for row in fam.get("series") or ():
                    rows.append({**row, "proc": key.decode()})
        return rows

    def _metric_families(self) -> Dict[str, list]:
        """{metric_name: [(proc, series_dict), ...]} across every pushed
        snapshot plus the head's own registry — the watchdog's histogram
        source."""
        from ray_tpu.util import metrics as _metrics

        fams: Dict[str, list] = {}
        snapshots = [("head", _metrics.snapshot_all())]
        snapshots.extend((key.decode(), payload)
                         for key, payload in self._parsed_snapshots())
        for proc, payload in snapshots:
            for fam in payload:
                name = fam.get("name", "")
                if name.startswith("__"):
                    continue
                for s in fam.get("series") or ():
                    fams.setdefault(name, []).append((proc, s))
        return fams

    async def _workload_watchdog_loop(self) -> None:
        """Flag slow pulls / train-step stragglers / p99-over-SLO routes /
        sustained admission-control shedding / hot-path drift (compiled
        ring stall ratios, chain p99, fused-step phase stragglers) from
        the merged telemetry — flight-recorder events plus
        `workload_anomalies_total{kind}` (see core/workload_watchdog)."""
        from ray_tpu.core import workload_watchdog

        interval = float(_config.get("workload_watchdog_interval_s"))
        if interval <= 0:
            return
        while not self._shutdown:
            await asyncio.sleep(interval)
            try:
                anomalies, self._watchdog_state = workload_watchdog.scan(
                    self._workload_rows(), self._metric_families(),
                    time.time(),
                    slow_pull_s=float(_config.get("workload_slow_pull_s")),
                    straggler_factor=float(
                        _config.get("workload_straggler_factor")),
                    p99_slo_s=float(_config.get("serve_p99_slo_s")),
                    hotpath_drift=float(
                        _config.get("workload_hotpath_drift")),
                    state=self._watchdog_state)
            except Exception:
                continue
            for a in anomalies:
                self.lease_events.append(
                    {"ts": time.time(), "kind": "workload_anomaly", **a})
                self._count_anomaly(a.get("anomaly", "?"))

    def _count_anomaly(self, kind: str) -> None:
        try:
            if self._anomaly_counter is None:
                from ray_tpu.util import metrics as _metrics

                self._anomaly_counter = _metrics.Counter(
                    "workload_anomalies_total",
                    "Workload anomalies flagged by the head watchdog "
                    "(slow_pull | train_straggler | slo_route | "
                    "serve_shedding | hotpath_regression)",
                    tag_keys=("kind",))
            self._anomaly_counter.inc(tags={"kind": kind})
        except Exception:
            pass

    # --------------------------------------------------------------- server
    async def start(self, port: int = 0) -> int:
        def on_connect(conn: protocol.Connection):
            conn_state = {"conn": conn}
            conn.handlers.update(self._handlers(conn_state))
            orig_close = conn.on_close

            def on_close(c):
                if orig_close:
                    orig_close(c)
                w = conn_state.get("worker")
                if w is not None:
                    if self.workers.get(w.worker_id) is w:
                        self._on_worker_disconnect(w)
                    else:
                        # superseded by a re-registration: don't tear the
                        # live registration down, but the stale object
                        # must leave the scheduling structures and its
                        # in-flight task must retry
                        self._purge_stale_worker(w)
                node = conn_state.get("node")
                # a stale transport closing after a re-registration
                # swapped in a fresh one must not tear the node down
                if node is not None and node.conn is conn_state["conn"]:
                    self._on_node_disconnect(node)

            conn.on_close = on_close

        # handlers installed per-connection (they close over conn_state)
        from ray_tpu.core import flight_recorder

        flight_recorder.install("head")
        bind = _config.get("bind_host")
        self._server = protocol.Server({}, on_connect=on_connect, name="head")
        self.port = await self._server.start(host=bind, port=port)
        # head-node object data server (worker nodes run theirs in the node
        # daemon): serves chunked reads of this node's store for cross-node
        # pulls (reference object_manager over gRPC)
        from ray_tpu.core import object_transfer

        # head-node pull manager: local workers route remote pulls through
        # it (`pull_object` RPC) so an object crosses the network once per
        # node — the daemon-side manager's twin for the head's own node
        self.pull_manager = object_transfer.PullManager(
            lambda: self.store, role="head",
            resolve=self._resolve_pull_sources)
        self._data_server = protocol.Server(
            object_transfer.make_data_handlers(lambda: self.store,
                                               lambda: self.pull_manager),
            name="head-data")
        self.data_port = await self._data_server.start(host=bind)
        self.head_node.data_addr = (None, self.data_port)
        asyncio.ensure_future(self._evict_loop())
        asyncio.ensure_future(self._health_loop())
        asyncio.ensure_future(self._view_broadcast_loop())
        asyncio.ensure_future(self._workload_watchdog_loop())
        asyncio.ensure_future(self._pool_reclaim_loop())
        from ray_tpu.core.job_manager import JobManager

        self.job_manager = JobManager(self.session, self.port)
        # tail this node's worker log files; batches land on the loop via
        # _on_log_batch (ring + fan-out to drivers)
        from ray_tpu.core import worker_logs

        loop = asyncio.get_running_loop()
        self._log_monitor = worker_logs.LogMonitor(
            worker_logs.session_log_dir(self.session),
            emit=lambda batch: loop.call_soon_threadsafe(
                self._on_log_batch, batch))
        self._log_monitor.start()
        return self.port

    async def _health_loop(self) -> None:
        """Application-level liveness probes (reference
        `gcs_health_check_manager.h:45`): TCP-disconnect reaping misses a
        hung-but-connected process (SIGSTOP, deadlocked GIL, wedged PJRT
        call) — its socket stays open while callers stall forever. Probe
        every worker and node daemon on a cadence; after
        `health_check_misses` consecutive timeouts, close its socket,
        which drives the NORMAL failure path (actor restart per
        max_restarts, lease revocation, task retry)."""
        interval = _config.get("health_check_interval_s")
        timeout = _config.get("health_check_timeout_s")
        budget = max(1, _config.get("health_check_misses"))
        if interval <= 0:
            return
        misses: Dict[bytes, int] = {}

        async def probe(key: bytes, conn) -> None:
            try:
                await asyncio.wait_for(conn.request("health_ping"), timeout)
                misses.pop(key, None)
            except asyncio.TimeoutError:
                m = misses.get(key, 0) + 1
                misses[key] = m
                if m >= budget:
                    misses.pop(key, None)
                    print(f"[ray_tpu] health: {budget} missed probes, "
                          f"declaring process dead", flush=True)
                    await conn.close()   # reap via the on_close path
            except Exception:
                misses.pop(key, None)   # disconnects reap themselves

        while not self._shutdown:
            await asyncio.sleep(interval)
            probes = []
            for w in list(self.workers.values()):
                # drivers are probed too — a wedged driver holds leases
                # and refs; its reap path already handles driver death
                if w.conn is not None and not w.conn.closed:
                    probes.append(probe(w.worker_id.binary(), w.conn))
            for node in list(self.nodes.values()):
                if node is self.head_node:
                    continue
                if node.conn is not None and not node.conn.closed:
                    probes.append(probe(node.node_id.binary(), node.conn))
            if probes:
                await asyncio.gather(*probes, return_exceptions=True)

    def notify_task_done(self, w: WorkerInfo) -> None:
        if w.current_record is not None:
            self._unpin_task(w.current_record)
        w.running_task = None
        w.current_record = None
        self._release(w)
        node = self.nodes.get(w.node_id)
        if (not w.is_driver and w.actor_id is None and not w.retiring
                and w.leased_to is None and not w.pooled
                and node is not None and w not in node.idle):
            node.idle.append(w)
            # waiting lease requests outrank the head-path queue: the
            # lease turns EVERY future same-shape task of that client
            # into a direct push, draining the queue's source
            self._grant_lease_waiters(node)
        self._kick()

    def _grant_lease_waiters(self, node: "NodeInfo") -> None:
        """Serve queued lease/pool waiters from a node that freed a worker.

        Each waiter carries its full scheduling shape: a TPU-slice-affine
        lease (label_selector) or a pip-isolated one (venv_key) must NOT
        be granted a worker on a non-matching node — skip it and keep
        scanning so an eligible later waiter still gets the worker."""
        if not self._lease_waiters or not node.idle:
            return
        remaining = []
        for ent in self._lease_waiters:
            if ent["fut"].done():
                continue  # timed out / cancelled
            if (not node.idle
                    or (ent.get("node_id") is not None
                        and ent["node_id"] != node.node_id)
                    or not node.matches_labels(ent.get("selector"))
                    or any(node.available.get(r, 0) < v
                           for r, v in ent["resources"].items())):
                remaining.append(ent)
                continue
            lw = self._idle_worker_on(node, ent.get("venv_key"))
            if lw is None:
                remaining.append(ent)
                continue
            self._acquire(lw, ent["resources"])
            ent["fut"].set_result(lw)
        self._lease_waiters[:] = remaining

    # ------------------------------------------- epoch / pool reconciliation
    def _stale_epoch(self, method: str, node: Optional[NodeInfo]) -> None:
        """Count + record a rejected stale-epoch operation and route its
        sender into the reconciliation handshake."""
        self.sched_totals["stale_epoch_rejects"] += 1
        self.lease_events.append(
            {"ts": time.time(), "kind": "stale_epoch", "method": method,
             "node_id": node.node_id.hex() if node is not None else None,
             "epoch": self.cluster_epoch})
        if node is not None and node.conn is not None and not node.conn.closed:
            try:
                node.conn.push("reconcile_request")
            except Exception:
                pass

    def _adopt_pooled(self, node: NodeInfo, w: WorkerInfo,
                      item: dict) -> None:
        """Restore a daemon-reported pool carve-out onto `w`: re-home the
        worker to the reporting node if a head restart parked it elsewhere
        (register_worker falls back to the head node when the daemon has
        not re-registered yet), debit the ledger once, and remember the
        carve-out generation for idempotent release."""
        old = self.nodes.get(w.node_id)
        if old is not None and old is not node:
            old.workers.discard(w.worker_id)
            if w in old.idle:
                old.idle.remove(w)
            old.unadopted.discard(w)
            w.node_id = node.node_id
            node.workers.add(w.worker_id)
        if w in node.idle:
            node.idle.remove(w)
        node.unadopted.discard(w)
        if not w.pooled:
            self._acquire(w, item.get("resources") or {})
            w.pooled = True
        w.leased_to = None
        if item.get("venv_key") is not None:
            w.venv_key = item["venv_key"]
        seq = item.get("seq")
        if seq is None:
            self._pool_seq += 1
            seq = self._pool_seq
        else:
            self._pool_seq = max(self._pool_seq, seq)
        w.pool_grant_seq = seq

    def _promote_unadopted(self, node: NodeInfo, w: WorkerInfo) -> None:
        """A parked reconnecting worker the daemon's reconcile did not
        claim (or whose daemon never reported in time): expose it to
        normal head dispatch."""
        if w not in node.unadopted or self.workers.get(w.worker_id) is not w:
            return
        node.unadopted.discard(w)
        if (not w.pooled and w.conn is not None and not w.conn.closed
                and w not in node.idle):
            node.idle.append(w)
            self._grant_lease_waiters(node)
            self._kick()

    def notify_actor_ready(self, info: ActorInfo, address) -> None:
        info.state = "ALIVE"
        info.address = tuple(address)
        info.ready_event.set()
        self._publish("actor_state", {"actor_id": info.actor_id.binary(),
                                      "state": "ALIVE"})

    async def stop(self) -> None:
        self._shutdown = True
        if self._log_monitor is not None:
            self._log_monitor.stop()
        for node in self.nodes.values():
            if node.conn is not None and not node.conn.closed:
                node.conn.push("shutdown_node")
        for w in list(self.workers.values()):
            if not w.is_driver:
                self._terminate_worker(w)
        if self._server:
            await self._server.stop()
        if getattr(self, "_data_server", None):
            await self._data_server.stop()
        if getattr(self, "pull_manager", None) is not None:
            await self.pull_manager.close()
        self.store.shutdown()
