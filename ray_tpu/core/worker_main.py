"""Worker process entry: executes tasks and hosts actors.

Counterpart of the reference's default_worker.py + task-execution path
(`python/ray/_private/workers/default_worker.py`, `_raylet.pyx:2141
execute_task_with_cancellation_handler`): receives pushed task specs from the
head, runs user code on executor threads, stores results, serves direct
actor calls on its own port.
"""

from __future__ import annotations

import asyncio
import os
import sys
import threading
import traceback
from concurrent.futures import ThreadPoolExecutor

from ray_tpu.core import serialization
from ray_tpu.core.client import CoreClient
from ray_tpu.core.exceptions import TaskError
from ray_tpu.core.ids import ActorID, ObjectID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.serialization import SerializedObject


class WorkerRuntime:
    def __init__(self, head_host: str, head_port: int, session: str):
        self.client = CoreClient(head_host, head_port, session, is_driver=False,
                                 handlers={
                                     "exec_task": self._on_exec_task,
                                     "start_actor": self._on_start_actor,
                                 })
        self.task_executor = ThreadPoolExecutor(max_workers=1,
                                                thread_name_prefix="task")
        self.actor_executor = None
        self.actor_instance = None
        self.actor_id = None
        self.shutdown_event = threading.Event()

    # ------------------------------------------------------------ plumbing
    def start(self):
        # Attach the global API client BEFORE registering with the head:
        # registration makes this worker eligible for task dispatch, and a
        # task using the ray_tpu API (nested .remote/get) must never observe
        # an unset global client.
        import ray_tpu.core.api as api

        api._attach_existing_client(self.client)
        self.client.on_disconnect = lambda: self.shutdown_event.set()
        self.client.on_registered = self._apply_sys_path
        self.client.start(direct_handlers={"actor_call": self._on_actor_call})
        if "driver_sys_path" not in (self.client.node_info or {}):
            self._extend_sys_path()

    @staticmethod
    def _adopt_sys_path(blob) -> None:
        import json

        if not blob:
            return
        try:
            for p in json.loads(blob):
                if p not in sys.path and os.path.isdir(p):
                    sys.path.append(p)
        except Exception:
            pass

    def _apply_sys_path(self, node_info: dict) -> None:
        """Adopt the driver's import roots before any task can be dispatched
        to us (same-machine runtime-env lite); the head ships them in the
        registration ack."""
        self._adopt_sys_path(node_info.get("driver_sys_path"))

    def _extend_sys_path(self):
        """Fallback for workers registered before any driver connected."""
        try:
            self._adopt_sys_path(self.client.kv_get("cluster", b"driver_sys_path"))
        except Exception:
            pass

    def _resolve_args(self, payload) -> tuple:
        if "inline" in payload:
            ser = SerializedObject.from_view(memoryview(payload["inline"]))
        else:
            meta = payload["meta"]
            self.client.local_metas[meta.object_id] = meta
            ser = self.client.store.get_serialized(meta)
        args, kwargs = serialization.deserialize(ser)
        args = [self.client.get([a])[0] if isinstance(a, ObjectRef) else a
                for a in args]
        kwargs = {k: (self.client.get([v])[0] if isinstance(v, ObjectRef) else v)
                  for k, v in kwargs.items()}
        return args, kwargs

    # -------------------------------------------------------------- tasks
    async def _on_exec_task(self, spec):
        loop = asyncio.get_running_loop()
        loop.run_in_executor(self.task_executor, self._run_task, spec)
        return True

    def _run_task(self, spec):
        return_ids = [ObjectID(b) for b in spec["return_ids"]]
        try:
            fn = self.client.fn_manager.load(spec["fn_key"])
            args, kwargs = self._resolve_args(spec["args"])
            result = fn(*args, **kwargs)
            results = [result] if len(return_ids) == 1 else list(result)
            if len(results) != len(return_ids):
                raise ValueError(
                    f"task returned {len(results)} values, expected {len(return_ids)}")
            for rid, val in zip(return_ids, results):
                self.client.store_result(rid, val, register=True)
        except BaseException as e:  # noqa: BLE001 - all failures become error objects
            err = e if isinstance(e, TaskError) else TaskError(
                repr(e), traceback.format_exc())
            for rid in return_ids:
                try:
                    self.client.store_result(rid, err, register=True, is_error=True)
                except Exception:
                    pass
        finally:
            try:
                self.client.head_request("task_done", task_id=spec["task_id"].binary())
            except Exception:
                pass

    # ------------------------------------------------------------- actors
    async def _on_start_actor(self, spec):
        loop = asyncio.get_running_loop()
        max_conc = spec["options"].get("max_concurrency", 1)
        self.actor_executor = ThreadPoolExecutor(max_workers=max_conc,
                                                 thread_name_prefix="actor")
        self.actor_id = ActorID(spec["actor_id"])
        self.client.current_actor_id = self.actor_id

        def _init():
            cls = self.client.fn_manager.load(spec["cls_key"])
            args, kwargs = self._resolve_args(spec["args"])
            self.actor_instance = cls(*args, **kwargs)

        try:
            await loop.run_in_executor(self.actor_executor, _init)
            await self.client.conn.request(
                "actor_ready", actor_id=spec["actor_id"],
                address=("127.0.0.1", self.client.direct_port))
        except Exception:
            try:
                await self.client.conn.request(
                    "actor_creation_failed", actor_id=spec["actor_id"],
                    cause=traceback.format_exc())
            except Exception:
                pass
        return True

    async def _on_actor_call(self, actor_id, method, args, deps, return_id):
        loop = asyncio.get_running_loop()

        def _run():
            rid = ObjectID(return_id)
            try:
                fn = getattr(self.actor_instance, method)
                a, kw = self._resolve_args(args)
                result = fn(*a, **kw)
                return self.client.store_result(rid, result, register=False)
            except BaseException as e:  # noqa: BLE001
                err = e if isinstance(e, TaskError) else TaskError(
                    repr(e), traceback.format_exc())
                return self.client.store_result(rid, err, register=False,
                                                is_error=True)

        meta = await loop.run_in_executor(self.actor_executor, _run)
        return {"meta": meta}

    # ---------------------------------------------------------------- run
    def run_forever(self):
        self.shutdown_event.wait()
        self.client.shutdown()


def main():
    head_host = os.environ.get("RAY_TPU_HEAD_HOST", "127.0.0.1")
    head_port = int(os.environ["RAY_TPU_HEAD_PORT"])
    session = os.environ["RAY_TPU_SESSION"]
    rt = WorkerRuntime(head_host, head_port, session)
    try:
        rt.start()
    except (ConnectionRefusedError, OSError, TimeoutError):
        sys.exit(0)  # head already gone: racing a cluster shutdown
    rt.run_forever()


if __name__ == "__main__":
    main()
