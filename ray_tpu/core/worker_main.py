"""Worker process entry: executes tasks and hosts actors.

Counterpart of the reference's default_worker.py + task-execution path
(`python/ray/_private/workers/default_worker.py`, `_raylet.pyx:2141
execute_task_with_cancellation_handler`): receives pushed task specs from the
head, runs user code on executor threads, stores results, serves direct
actor calls on its own port. Also implements:

- streaming generators (`num_returns="streaming"`): yields become objects
  reported incrementally with head-enforced backpressure (reference
  `_generator_backpressure_num_objects`, SURVEY §2.12b);
- cancellation: `cancel_task` async-raises TaskCancelledError into the task
  thread (the CPython equivalent of the reference's interrupt path);
- `max_calls`: worker retires after N executions of a task's function;
- async actors: `async def` methods run on the event loop under a
  per-concurrency-group semaphore; sync methods run on per-group thread
  pools (reference fiber/concurrency-group semantics,
  `task_execution/concurrency_group_manager.*`).
"""

from __future__ import annotations

import asyncio
import ctypes
import inspect
import os
import sys
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

from ray_tpu.core import serialization
from ray_tpu.core.client import CoreClient
from ray_tpu.core.exceptions import (ObjectLostError, TaskCancelledError,
                                     TaskError)
from ray_tpu.core.ids import ActorID, ObjectID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.serialization import SerializedObject

DEFAULT_GROUP = "_default"

_EMPTY_ARGS_BLOB: Optional[bytes] = None


def _empty_args_blob() -> bytes:
    """The constant serialized form of ((), {}) — zero-arg calls (the
    actor hot path) ship exactly these bytes (client.py caches the same
    constant), so matching them skips the per-call deserialize."""
    global _EMPTY_ARGS_BLOB
    if _EMPTY_ARGS_BLOB is None:
        _EMPTY_ARGS_BLOB = serialization.serialize(((), {})).to_bytes()
    return _EMPTY_ARGS_BLOB


class WorkerRuntime:
    def __init__(self, head_host: str, head_port: int, session: str):
        self.client = CoreClient(head_host, head_port, session, is_driver=False,
                                 handlers={
                                     "exec_task": self._on_exec_task,
                                     "start_actor": self._on_start_actor,
                                     "cancel_task": self._on_cancel_task,
                                     # liveness probe: answered on the event
                                     # loop, so it proves the PROCESS is
                                     # scheduled (tasks run on executor
                                     # threads) — a SIGSTOP/GIL-wedged
                                     # worker times out (reference
                                     # gcs_health_check_manager.h)
                                     "health_ping": self._on_health_ping,
                                 })
        self.task_executor = ThreadPoolExecutor(max_workers=1,
                                                thread_name_prefix="task")
        self.actor_executors: Dict[str, ThreadPoolExecutor] = {}
        self.actor_semaphores: Dict[str, asyncio.Semaphore] = {}
        self.actor_method_groups: Dict[str, str] = {}
        self.actor_method_transport: Dict[str, str] = {}
        self.actor_instance = None
        self.actor_id = None
        self.shutdown_event = threading.Event()
        self._task_threads: Dict[bytes, int] = {}    # task_id -> thread ident
        self._fn_calls: Dict[bytes, int] = {}
        self._retiring = False
        self._method_is_coro: Dict[str, bool] = {}   # per-call inspect is hot

    # ------------------------------------------------------------ plumbing
    def start(self):
        # Attach the global API client BEFORE registering with the head:
        # registration makes this worker eligible for task dispatch, and a
        # task using the ray_tpu API (nested .remote/get) must never observe
        # an unset global client.
        import ray_tpu.core.api as api

        api._attach_existing_client(self.client)
        self.client.on_disconnect = lambda: self.shutdown_event.set()
        self.client.on_registered = self._apply_sys_path
        self.client.start(direct_handlers={
            "actor_call": self._on_actor_call,
            "lease_exec": self._on_lease_exec,
        })
        if "driver_sys_path" not in (self.client.node_info or {}):
            self._extend_sys_path()

    @staticmethod
    def _adopt_sys_path(blob) -> None:
        import json

        if not blob:
            return
        try:
            for p in json.loads(blob):
                if p not in sys.path and os.path.isdir(p):
                    sys.path.append(p)
        except Exception:
            pass

    def _apply_sys_path(self, node_info: dict) -> None:
        """Adopt the driver's import roots before any task can be dispatched
        to us (same-machine runtime-env lite); the head ships them in the
        registration ack."""
        self._adopt_sys_path(node_info.get("driver_sys_path"))

    def _extend_sys_path(self):
        """Fallback for workers registered before any driver connected."""
        try:
            self._adopt_sys_path(self.client.kv_get("cluster", b"driver_sys_path"))
        except Exception:
            pass

    def _adopt_dep_metas(self, spec) -> None:
        """Dep metas shipped with a task spec (lease push or head
        dispatch of data-stage tasks): adopt them so argument resolution
        pulls straight through the node PullManager instead of paying a
        get_meta round trip per dependency. A meta we already hold wins
        (it may be a fresher pulled copy); a stale shipped meta falls
        back to locate_object inside the pull path."""
        for m in spec.get("dep_metas") or ():
            self.client.local_metas.setdefault(m.object_id, m)

    def _resolve_args(self, payload) -> tuple:
        if "inline" in payload:
            if payload["inline"] == _empty_args_blob():
                return (), {}
            ser = SerializedObject.from_view(memoryview(payload["inline"]))
        else:
            meta = payload["meta"]
            self.client.local_metas[meta.object_id] = meta
            ser = self.client.read_serialized(meta)  # pulls if cross-node
        args, kwargs = serialization.deserialize(ser)
        args = [self.client.get([a])[0] if isinstance(a, ObjectRef) else a
                for a in args]
        kwargs = {k: (self.client.get([v])[0] if isinstance(v, ObjectRef) else v)
                  for k, v in kwargs.items()}
        return args, kwargs

    async def _resolve_args_async(self, payload) -> tuple:
        """Event-loop-safe variant (async actor methods run on the loop; the
        sync path would deadlock calling back into it)."""
        if "inline" in payload:
            if payload["inline"] == _empty_args_blob():
                return (), {}
            ser = SerializedObject.from_view(memoryview(payload["inline"]))
        else:
            meta = payload["meta"]
            self.client.local_metas[meta.object_id] = meta
            ser = await self.client.read_serialized_async(meta)
        args, kwargs = serialization.deserialize(ser)
        out_args = []
        for a in args:
            out_args.append(await self.client.get_async([a])
                            if isinstance(a, ObjectRef) else a)
        out_kwargs = {}
        for k, v in kwargs.items():
            out_kwargs[k] = (await self.client.get_async([v])
                             if isinstance(v, ObjectRef) else v)
        return tuple(out_args), out_kwargs

    # -------------------------------------------------------------- tasks
    async def _on_exec_task(self, spec):
        loop = asyncio.get_running_loop()
        loop.run_in_executor(self.task_executor, self._run_task, spec)
        return True

    async def _on_lease_exec(self, spec):
        """Direct task push from a lease-holding client (reference
        PushNormalTask, `normal_task_submitter.cc:515`): executes on the
        task thread and replies with the result meta — the head is not on
        this path at all."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(self.task_executor,
                                          self._run_lease_task, spec)

    def _run_lease_task(self, spec):
        rid = ObjectID(spec["return_ids"][0])
        opts = spec.get("options", {})
        task_key = spec["task_id"].binary()
        self._task_threads[task_key] = threading.get_ident()
        # run-phase timing for the submitter's flight recorder: the head
        # never sees lease-path tasks, so the execution window rides the
        # reply (only when the driver traces — the carrier's presence).
        # Opened AFTER function load + argument resolution so dependency
        # fetches land in the dispatch phase, not in "run".
        prof = None
        try:
            from ray_tpu.util import tracing

            fn = self.client.fn_manager.load(spec["fn_key"],
                                 blob=spec.get("fn_blob"))
            self._adopt_dep_metas(spec)
            # dependency fetches land in the dispatch phase (outside the
            # run span) but still carry the task's trace context, so
            # object-pull spans parent to the submitting trace
            with tracing.adopt_context(opts.get("trace_ctx")):
                args, kwargs = self._resolve_args(spec["args"])
            if opts.get("trace_ctx"):
                prof = {"start": time.time()}
            with tracing.execute_span(opts.get("name", "task"),
                                      opts.get("trace_ctx")):
                result = fn(*args, **kwargs)
            if prof is not None:
                prof["end"] = time.time()
            meta = self.client.store_result(rid, result, register=False)
        except BaseException as e:  # noqa: BLE001 - failures become error objects
            # ObjectLostError passes unwrapped (retryable input loss)
            err = e if isinstance(
                e, (TaskError, TaskCancelledError, ObjectLostError)) else \
                TaskError(repr(e), traceback.format_exc())
            meta = self.client.store_result(rid, err, register=False,
                                            is_error=True)
        finally:
            self._task_threads.pop(task_key, None)
            max_calls = opts.get("max_calls")
            if max_calls:
                fn_key = spec["fn_key"]
                self._fn_calls[fn_key] = self._fn_calls.get(fn_key, 0) + 1
                if self._fn_calls[fn_key] >= max_calls:
                    self._retiring = True
                    try:
                        self.client.head_push("worker_retiring")
                    except Exception:
                        pass
        rep = {"meta": meta, "retired": self._retiring}
        if prof is not None:
            prof.setdefault("end", time.time())  # error path: fn raised
            rep["prof"] = prof
        return rep

    async def _on_health_ping(self):
        return True

    async def _on_cancel_task(self, task_id):
        ident = self._task_threads.get(task_id)
        if ident is not None:
            # CPython async-raise into the task thread: the closest
            # single-process analog of the reference's cancellation interrupt
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(ident), ctypes.py_object(TaskCancelledError))
        return ident is not None

    def _run_task(self, spec):
        return_ids = [ObjectID(b) for b in spec["return_ids"]]
        opts = spec.get("options", {})
        task_key = spec["task_id"].binary()
        self._task_threads[task_key] = threading.get_ident()
        streaming = opts.get("num_returns") == "streaming"
        applied = None
        try:
            if opts.get("runtime_env"):
                from ray_tpu.core.runtime_env import AppliedEnv

                applied = AppliedEnv(self.client, opts["runtime_env"])
            from ray_tpu.util import tracing

            fn = self.client.fn_manager.load(spec["fn_key"],
                                 blob=spec.get("fn_blob"))
            self._adopt_dep_metas(spec)
            with tracing.adopt_context(opts.get("trace_ctx")):
                args, kwargs = self._resolve_args(spec["args"])
            with tracing.execute_span(opts.get("name", "task"),
                                      opts.get("trace_ctx")):
                result = fn(*args, **kwargs)
                if streaming:
                    # generators do their real work during the drain — the
                    # span must cover it, not just the immediate call
                    self._drain_generator(return_ids[0], result, opts)
                else:
                    results = ([result] if len(return_ids) == 1
                               else list(result))
                    if len(results) != len(return_ids):
                        raise ValueError(
                            f"task returned {len(results)} values, "
                            f"expected {len(return_ids)}")
                    for rid, val in zip(return_ids, results):
                        self.client.store_result(rid, val, register=True)
        except BaseException as e:  # noqa: BLE001 - all failures become error objects
            err = e if isinstance(e, TaskError) else TaskError(
                repr(e), traceback.format_exc())
            if isinstance(e, (TaskCancelledError, ObjectLostError)):
                # ObjectLostError stays unwrapped: a consumer whose INPUT
                # went lost (vs. its own code failing) is retryable by
                # the submitting executor once the input reconstructs
                err = e
            for rid in return_ids:
                try:
                    self.client.store_result(rid, err, register=True, is_error=True)
                except Exception:
                    pass
        finally:
            if applied is not None:
                applied.restore()
            self._task_threads.pop(task_key, None)
            retire = False
            max_calls = opts.get("max_calls")
            if max_calls:
                fn_key = spec["fn_key"]
                self._fn_calls[fn_key] = self._fn_calls.get(fn_key, 0) + 1
                retire = self._fn_calls[fn_key] >= max_calls
            try:
                if retire:
                    self.client.head_push("worker_retiring")
                # push: the completion signal needs no reply, and a blocking
                # round trip here caps pipelined task throughput
                self.client.head_push("task_done",
                                      task_id=spec["task_id"].binary())
            except Exception:
                pass
            if retire:
                self._retiring = True
                self.shutdown_event.set()

    def _drain_generator(self, gen_id: ObjectID, result, opts) -> None:
        """Stream yielded values to the head as they materialize."""
        backpressure = opts.get("_generator_backpressure_num_objects") or 0
        count = 0
        for item in result:
            oid = ObjectID.generate()
            # via_head: generator_yield seals this meta at the head itself
            meta = self.client.store_result(oid, item, register=False,
                                            via_head=True)
            # the head seals the meta; the reply is delayed for backpressure
            self.client.head_request("generator_yield", gen_id=gen_id.binary(),
                                     meta=meta, backpressure=backpressure)
            count += 1
        self.client.head_request("generator_done", gen_id=gen_id.binary())

    # ------------------------------------------------------------- actors
    async def _on_start_actor(self, spec):
        loop = asyncio.get_running_loop()
        opts = spec["options"]
        max_conc = opts.get("max_concurrency", 1)
        groups = dict(opts.get("concurrency_groups") or {})
        self.actor_executors = {
            DEFAULT_GROUP: ThreadPoolExecutor(max_conc,
                                              thread_name_prefix="actor")}
        self.actor_semaphores = {DEFAULT_GROUP: asyncio.Semaphore(max_conc)}
        for gname, n in groups.items():
            self.actor_executors[gname] = ThreadPoolExecutor(
                int(n), thread_name_prefix=f"actor-{gname}")
            self.actor_semaphores[gname] = asyncio.Semaphore(int(n))
        self.actor_method_groups = {
            m: meta.get("concurrency_group") for m, meta in
            spec.get("methods", {}).items() if meta.get("concurrency_group")}
        self.actor_method_transport = {
            m: meta.get("tensor_transport") for m, meta in
            spec.get("methods", {}).items() if meta.get("tensor_transport")}
        self.actor_id = ActorID(spec["actor_id"])
        self.client.current_actor_id = self.actor_id

        def _init():
            if opts.get("runtime_env"):
                from ray_tpu.core.runtime_env import AppliedEnv

                # actors keep their env for life (dedicated-worker model);
                # never restored — the worker exits with the actor
                AppliedEnv(self.client, opts["runtime_env"])
            cls = self.client.fn_manager.load(spec["cls_key"])
            args, kwargs = self._resolve_args(spec["args"])
            self.actor_instance = cls(*args, **kwargs)

        try:
            await loop.run_in_executor(self.actor_executors[DEFAULT_GROUP], _init)
            await self.client.conn.request(
                "actor_ready", actor_id=spec["actor_id"],
                address=("127.0.0.1", self.client.direct_port))
        except Exception:
            try:
                await self.client.conn.request(
                    "actor_creation_failed", actor_id=spec["actor_id"],
                    cause=traceback.format_exc())
            except Exception:
                pass
        return True

    async def _on_actor_call(self, actor_id, method, args, deps, return_id,
                             group=None, trace=None):
        loop = asyncio.get_running_loop()
        rid = ObjectID(return_id)
        gname = group or self.actor_method_groups.get(method) or DEFAULT_GROUP
        fn = getattr(self.actor_instance, method, None)
        from ray_tpu.util import tracing

        span_name = f"{type(self.actor_instance).__name__}.{method}"

        is_coro = self._method_is_coro.get(method)
        if is_coro is None:
            is_coro = self._method_is_coro[method] = (
                fn is not None and inspect.iscoroutinefunction(fn))
        if is_coro:
            # async actor method: runs on this event loop under the group's
            # semaphore (reference asyncio-actor / fiber semantics)
            sem = self.actor_semaphores.get(gname) or \
                self.actor_semaphores[DEFAULT_GROUP]
            async with sem:
                try:
                    with tracing.execute_span(span_name, trace):
                        a, kw = await self._resolve_args_async(args)
                        result = await fn(*a, **kw)
                    if self.actor_method_transport.get(method) == "device":
                        meta = self.client.store_device_result(rid, result)
                    else:
                        meta = self.client.store_result(rid, result,
                                                        register=False)
                except BaseException as e:  # noqa: BLE001
                    err = e if isinstance(e, TaskError) else TaskError(
                        repr(e), traceback.format_exc())
                    meta = self.client.store_result(rid, err, register=False,
                                                    is_error=True)
            return {"meta": meta}

        def _run():
            try:
                if method == "__rtpu_dag_exec_loop__":
                    # injected compiled-DAG loop (reference __ray_call__ +
                    # do_exec_tasks): runs against the hosted instance
                    import functools

                    from ray_tpu.dag.runtime import exec_dag_loop

                    f = functools.partial(exec_dag_loop, self.actor_instance)
                else:
                    f = getattr(self.actor_instance, method)
                with tracing.execute_span(span_name, trace):
                    a, kw = self._resolve_args(args)
                    result = f(*a, **kw)
                if self.actor_method_transport.get(method) == "device":
                    # result stays on-device in this process; only the
                    # meta rides the reply (RDT tensor_transport)
                    return self.client.store_device_result(rid, result)
                return self.client.store_result(rid, result, register=False)
            except BaseException as e:  # noqa: BLE001
                err = e if isinstance(e, TaskError) else TaskError(
                    repr(e), traceback.format_exc())
                return self.client.store_result(rid, err, register=False,
                                                is_error=True)

        executor = self.actor_executors.get(gname) or \
            self.actor_executors[DEFAULT_GROUP]
        meta = await loop.run_in_executor(executor, _run)
        return {"meta": meta}

    # ---------------------------------------------------------------- run
    def run_forever(self):
        self.shutdown_event.wait()
        self.client.shutdown()


def main():
    from ray_tpu.core import config as _config

    head_host = _config.get("head_host")
    head_port = int(os.environ["RAY_TPU_HEAD_PORT"])
    session = os.environ["RAY_TPU_SESSION"]
    rt = WorkerRuntime(head_host, head_port, session)
    try:
        rt.start()
    except (ConnectionRefusedError, OSError, TimeoutError):
        sys.exit(0)  # head already gone: racing a cluster shutdown
    rt.run_forever()


if __name__ == "__main__":
    main()
