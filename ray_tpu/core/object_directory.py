"""Gossiped object directory: object → serving-node resolution, head-free.

Reference: `src/ray/object_manager/ownership_object_directory.cc` — every
consumer of an object must learn which node can serve its bytes. PRs 1-3
decentralized the control plane, but object location lookups remained a
head round trip (`locate_object`) and the head's directory died with it.

This module piggybacks object-location announcements on the gossip plane
that already exists (zero new RPC channels, the flight-recorder pattern):

- the **head** stays the authority: every seal/spill/free of a non-inline
  object appends a small delta record, and the records ride the next
  `cluster_view` broadcast (debounced by `view_broadcast_s`, so a put
  storm costs one list per tick, not one push per object);
- **node daemons** and **drivers** apply the records into a cached
  `ObjectDirectory`; a warm `get()` of a remote object resolves the
  serving node (and its data-server address, now carried in the view
  entries) entirely from cache — zero head RPCs;
- **pulled replicas** (a daemon's pull-manager cache) are announced back
  to the head on `resource_view_delta` gossip and rebroadcast, giving
  every consumer multi-source failover;
- on daemon (re)connect the directory entries for the daemon's OWN node
  are re-advertised through the `pool_reconcile` handshake, so a
  restarted head rebuilds the directory from daemon truth — the PR 3
  ledger pattern applied to data (shm objects now survive head restarts).

Record shapes (plain dicts, pickled inside the existing frames):
  {"op": "seal",  "meta": ObjectMeta}              # new/updated primary
  {"op": "spill", "meta": ObjectMeta}              # retargeted to disk
  {"op": "free",  "oid": bytes}                    # object gone
  {"op": "replica", "oid": bytes, "node": hex}     # extra pull source
  {"op": "replica_gone", "oid": bytes, "node": hex}
  {"op": "node_dead", "node": hex}                 # purge its locations
  {"op": "prefix", "mk": str, "ph": bytes, "oid": bytes,
   "n": int, "bs": int}                            # content-addressed KV
  {"op": "prefix_gone", "mk": str, "ph": bytes}    # binding withdrawn
  {"op": "weights", "wid": str, "oid": bytes}      # weights_id -> manifest
  {"op": "weights_gone", "wid": str}               # weights withdrawn

Weights rows are the serve plane's model-fleet index (PR 20,
serve/weight_store.py): a weights identity (checkpoint path, preset@seed,
or adapter key) bound to the object id of a published weight-manifest
blob. A cold replica resolves `weights_id -> manifest` from its cached
directory with zero head RPCs and streams the manifest's chunk objects
from peers instead of re-reading the checkpoint from a central path.
Like prefix rows, a binding dies with its blob.

Prefix rows are the serve plane's cluster-wide KV cache index: a rolling
content hash of a token prefix (serve/prefix_store.py) bound to the
object id of an exported paged-KV blob. They ride the same broadcast as
location records, so ANY replica resolves "who already computed this
prefix" from cache — zero head RPCs on the warm path. A binding dies
with its blob: free/node-death records purge the rows of objects whose
bytes are gone, so a lookup never returns an unreachable prefix.

Broadcast payloads:
  {"v": seq, "delta": [records...]}                # normal tick
  {"v": seq, "full": [ {"meta": m, "replicas": [hex...]} ... ]}
Gaps are harmless: records are absolute facts, and a consumer that
missed a batch simply cold-misses into the head fallback.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Set

from ray_tpu.core.ids import ObjectID

# kinds a data server can actually serve bytes for; device objects live
# in their owner process and inline ones ride the control plane whole
PULLABLE_KINDS = ("shm", "arena", "spilled")


def seal_record(meta) -> dict:
    return {"op": "seal", "meta": meta}


def spill_record(meta) -> dict:
    return {"op": "spill", "meta": meta}


def free_record(oid: ObjectID) -> dict:
    return {"op": "free", "oid": oid.binary()}


def replica_record(oid: ObjectID, node_hex: str) -> dict:
    return {"op": "replica", "oid": oid.binary(), "node": node_hex}


def replica_gone_record(oid: ObjectID, node_hex: str) -> dict:
    return {"op": "replica_gone", "oid": oid.binary(), "node": node_hex}


def node_dead_record(node_hex: str) -> dict:
    return {"op": "node_dead", "node": node_hex}


def prefix_record(model_key: str, phash: bytes, oid: ObjectID,
                  n_tokens: int, block_size: int) -> dict:
    return {"op": "prefix", "mk": model_key, "ph": phash,
            "oid": oid.binary(), "n": int(n_tokens), "bs": int(block_size)}


def prefix_gone_record(model_key: str, phash: bytes) -> dict:
    return {"op": "prefix_gone", "mk": model_key, "ph": phash}


def weights_record(weights_id: str, oid: ObjectID) -> dict:
    return {"op": "weights", "wid": weights_id, "oid": oid.binary()}


def weights_gone_record(weights_id: str) -> dict:
    return {"op": "weights_gone", "wid": weights_id}


def resolve_addrs(directory: "ObjectDirectory", meta, addr_of,
                  default_host: str, exclude: Optional[str] = None) -> list:
    """Shared pull-source resolution: the directory's locations for the
    object (primary first, replicas after; the meta's own node stamp as
    the cold fallback) mapped to data-server addresses through `addr_of`
    (a node-hex → (host, port)|None lookup — the cached cluster view for
    clients/daemons, the node table for the head). A None host means
    "the head's host" and is substituted with `default_host`; `exclude`
    skips the caller's own node (never pull from yourself). Every party
    (client, node daemon, head) resolves through this one helper so
    ordering and host-substitution semantics cannot drift."""
    if meta.kind not in PULLABLE_KINDS:
        return []
    node_hexes = directory.locations(meta.object_id)
    if not node_hexes and meta.node_id is not None:
        node_hexes = [meta.node_id.hex()]
    out = []
    for h in node_hexes:
        if exclude is not None and h == exclude:
            continue
        addr = addr_of(h)
        if addr:
            out.append((addr[0] or default_host, addr[1]))
    return out


class _Entry:
    __slots__ = ("meta", "replicas", "primary_dead")

    def __init__(self, meta, replicas: Optional[Set[str]] = None):
        self.meta = meta
        self.replicas = replicas or set()
        # primary node died but a replica survived: the entry lives on
        # (replicas serve by object-id translation), and dies with the
        # last replica
        self.primary_dead = False


class ObjectDirectory:
    """One party's view of where object bytes live.

    The head holds the authoritative copy (fed by `apply_record` as it
    seals/spills/frees); daemons and drivers hold cached copies fed by
    broadcast payloads. Entries keep the full ObjectMeta — that is what
    makes daemon re-advertisement after a head restart possible, and what
    lets a driver `get()` an object it never held a meta for without
    asking the head."""

    def __init__(self):
        self.entries: Dict[ObjectID, _Entry] = {}
        # content-addressed KV prefix index: model_key -> prefix chain
        # hash -> {"oid", "n", "bs"}; _prefix_by_oid is the reverse index
        # that lets free/node-death records purge bindings in O(1)
        self.prefixes: Dict[str, Dict[bytes, dict]] = {}
        self._prefix_by_oid: Dict[ObjectID, Set[tuple]] = {}
        # content-addressed weight index: weights_id -> {"oid"} of the
        # published manifest blob; _weights_by_oid mirrors the prefix
        # reverse index so free/node-death purges bindings in O(1)
        self.weights: Dict[str, dict] = {}
        self._weights_by_oid: Dict[ObjectID, Set[str]] = {}
        self.last_v = 0           # highest broadcast version applied
        self.adopted_ts = 0.0     # monotonic ts of the last applied payload
        self.applied_records = 0  # lifetime counter (tests/diagnostics)

    def __len__(self) -> int:
        return len(self.entries)

    # -------------------------------------------------------------- reads
    def lookup_meta(self, oid: ObjectID):
        ent = self.entries.get(oid)
        return ent.meta if ent is not None else None

    def locations(self, oid: ObjectID) -> List[str]:
        """Node hexes that can serve the object, primary first."""
        ent = self.entries.get(oid)
        if ent is None:
            return []
        out = []
        if ent.meta.node_id is not None and not ent.primary_dead:
            out.append(ent.meta.node_id.hex())
        out.extend(h for h in sorted(ent.replicas) if h not in out)
        return out

    def metas_on(self, node_hex: str) -> List[object]:
        """Primary metas living on one node (daemon re-advertisement)."""
        return [ent.meta for ent in self.entries.values()
                if ent.meta.node_id is not None
                and ent.meta.node_id.hex() == node_hex]

    def replicas_on(self, node_hex: str) -> List[ObjectID]:
        return [oid for oid, ent in self.entries.items()
                if node_hex in ent.replicas]

    def staleness_s(self) -> float:
        """Seconds since the last applied broadcast; -1 = never."""
        if not self.adopted_ts:
            return -1.0
        return time.monotonic() - self.adopted_ts

    def longest_prefix(self, model_key: str, chain) -> Optional[dict]:
        """Longest announced prefix binding covering a prompt, entirely
        from cache. `chain` is the prompt's rolling chain hashes in
        prefix order (block 1..k, serve/prefix_store.chain_hashes);
        walked longest-first, the first binding whose blob is still
        RESIDENT somewhere (its oid resolves in the location entries)
        wins — a binding that outlived its bytes is skipped, never
        returned as a warm hit. Returns {"ph", "oid", "n", "bs"}."""
        rows = self.prefixes.get(model_key)
        if not rows:
            return None
        for phash in reversed([h for h, _n in chain]):
            ent = rows.get(phash)
            if ent is None:
                continue
            if ObjectID(ent["oid"]) in self.entries:
                return {"ph": phash, **ent}
        return None

    def prefix_count(self) -> int:
        return sum(len(rows) for rows in self.prefixes.values())

    def weights_binding(self, weights_id: str) -> Optional[dict]:
        """Resident manifest binding for a weights identity, entirely
        from cache. Residency-checked like `longest_prefix`: a binding
        whose manifest blob is gone everywhere is never returned — the
        caller falls back to the checkpoint-path read instead of chasing
        an unreachable object. Returns {"oid"} or None."""
        ent = self.weights.get(weights_id)
        if ent is None:
            return None
        if ObjectID(ent["oid"]) not in self.entries:
            return None
        return dict(ent)

    def weights_count(self) -> int:
        return len(self.weights)

    def _drop_weights(self, weights_id: str) -> None:
        ent = self.weights.pop(weights_id, None)
        if ent is None:
            return
        oid = ObjectID(ent["oid"])
        wids = self._weights_by_oid.get(oid)
        if wids is not None:
            wids.discard(weights_id)
            if not wids:
                self._weights_by_oid.pop(oid, None)

    def _purge_weights_for(self, oid: ObjectID) -> None:
        """The manifest blob's bytes are gone everywhere: its weights
        bindings must not linger as phantom warm starts."""
        for wid in list(self._weights_by_oid.pop(oid, ())):
            self.weights.pop(wid, None)

    def _drop_prefix(self, model_key: str, phash: bytes) -> None:
        rows = self.prefixes.get(model_key)
        ent = rows.pop(phash, None) if rows else None
        if ent is None:
            return
        if not rows:
            self.prefixes.pop(model_key, None)
        oid = ObjectID(ent["oid"])
        keys = self._prefix_by_oid.get(oid)
        if keys is not None:
            keys.discard((model_key, phash))
            if not keys:
                self._prefix_by_oid.pop(oid, None)

    def _purge_prefixes_for(self, oid: ObjectID) -> None:
        """The blob's bytes are gone everywhere: its bindings must not
        linger as phantom warm hits."""
        for model_key, phash in list(self._prefix_by_oid.pop(oid, ())):
            rows = self.prefixes.get(model_key)
            if rows is not None:
                rows.pop(phash, None)
                if not rows:
                    self.prefixes.pop(model_key, None)

    # ------------------------------------------------------------- writes
    def apply_record(self, rec: dict) -> None:
        op = rec.get("op")
        if op in ("seal", "spill"):
            meta = rec["meta"]
            if meta.kind not in PULLABLE_KINDS:
                return
            ent = self.entries.get(meta.object_id)
            if ent is None:
                self.entries[meta.object_id] = _Entry(meta)
            else:
                # spill retarget / re-seal keeps replica knowledge
                ent.meta = meta
        elif op == "free":
            oid = ObjectID(rec["oid"])
            self.entries.pop(oid, None)
            self._purge_prefixes_for(oid)
            self._purge_weights_for(oid)
        elif op == "replica":
            ent = self.entries.get(ObjectID(rec["oid"]))
            if ent is not None:
                ent.replicas.add(rec["node"])
        elif op == "replica_gone":
            oid = ObjectID(rec["oid"])
            ent = self.entries.get(oid)
            if ent is not None:
                ent.replicas.discard(rec["node"])
                if ent.primary_dead and not ent.replicas:
                    # that was the last copy anywhere: a primary-dead
                    # entry must not linger unreachable forever
                    del self.entries[oid]
                    self._purge_prefixes_for(oid)
                    self._purge_weights_for(oid)
        elif op == "node_dead":
            dead = rec["node"]
            for oid in list(self.entries):
                ent = self.entries[oid]
                ent.replicas.discard(dead)
                if ent.meta.node_id is not None \
                        and ent.meta.node_id.hex() == dead:
                    ent.primary_dead = True
                if ent.primary_dead and not ent.replicas:
                    # nobody holds the bytes anymore. While a replica
                    # survives the entry stays: pulls fail over to it
                    # (its data server translates the canonical meta to
                    # its local copy by object id) — losing the primary
                    # is exactly when replica knowledge matters most
                    del self.entries[oid]
                    self._purge_prefixes_for(oid)
                    self._purge_weights_for(oid)
        elif op == "prefix":
            mk, phash = rec["mk"], rec["ph"]
            self._drop_prefix(mk, phash)   # rebind: retire the old oid
            self.prefixes.setdefault(mk, {})[phash] = {
                "oid": rec["oid"], "n": rec["n"], "bs": rec["bs"]}
            self._prefix_by_oid.setdefault(
                ObjectID(rec["oid"]), set()).add((mk, phash))
        elif op == "prefix_gone":
            self._drop_prefix(rec["mk"], rec["ph"])
        elif op == "weights":
            wid = rec["wid"]
            self._drop_weights(wid)    # rebind: retire the old oid
            self.weights[wid] = {"oid": rec["oid"]}
            self._weights_by_oid.setdefault(
                ObjectID(rec["oid"]), set()).add(wid)
        elif op == "weights_gone":
            self._drop_weights(rec["wid"])
        self.applied_records += 1

    def apply(self, payload: Optional[dict]) -> bool:
        """Apply one broadcast payload (delta or full). Stale payloads
        (version at or below what we already applied) are dropped —
        except `full`, which is a wholesale resync and always wins."""
        if not payload:
            return False
        v = payload.get("v", 0)
        full = payload.get("full")
        if full is not None:
            self.entries = {
                e["meta"].object_id: _Entry(e["meta"],
                                            set(e.get("replicas") or ()))
                for e in full if e["meta"].kind in PULLABLE_KINDS}
            self.prefixes = {}
            self._prefix_by_oid = {}
            self.weights = {}
            self._weights_by_oid = {}
            for rec in payload.get("prefixes") or ():
                self.apply_record(rec)
            for rec in payload.get("weights") or ():
                self.apply_record(rec)
            self.last_v = v
            self.adopted_ts = time.monotonic()
            self.applied_records += 1
            return True
        if v and v <= self.last_v:
            return False
        for rec in payload.get("delta") or ():
            self.apply_record(rec)
        self.last_v = max(self.last_v, v)
        self.adopted_ts = time.monotonic()
        return True

    def full_payload(self, v: int) -> dict:
        """Wholesale snapshot for late joiners / (re)registered daemons."""
        return {"v": v,
                "full": [{"meta": ent.meta,
                          "replicas": sorted(ent.replicas)}
                         for ent in self.entries.values()],
                "prefixes": [
                    {"op": "prefix", "mk": mk, "ph": ph, "oid": e["oid"],
                     "n": e["n"], "bs": e["bs"]}
                    for mk, rows in self.prefixes.items()
                    for ph, e in rows.items()],
                "weights": [
                    {"op": "weights", "wid": wid, "oid": e["oid"]}
                    for wid, e in self.weights.items()]}
