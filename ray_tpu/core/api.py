"""Public task/actor API: init, @remote, get/put/wait, actors, kill.

Surface parity with the reference's Python API
(`python/ray/_private/worker.py` ray.init/get/put/wait/kill,
`python/ray/remote_function.py`, `python/ray/actor.py`) on a new runtime.
"""

from __future__ import annotations

import atexit
import functools
import json
import os
from ray_tpu.core import config as _config
import subprocess
import sys
import threading
import time
import uuid
from typing import Any, List, Optional, Sequence, Tuple, Union

from ray_tpu.core.client import CoreClient
from ray_tpu.core.exceptions import RayTpuError
from ray_tpu.core.ids import ActorID
from ray_tpu.core.object_ref import ObjectRef

_client: Optional[CoreClient] = None
_head_proc: Optional[subprocess.Popen] = None
_lock = threading.RLock()

DEFAULT_TASK_OPTIONS = {
    "num_cpus": 1.0, "num_tpu_chips": 0, "resources": None, "max_retries": 3,
    "num_returns": 1, "name": None, "placement_group": None,
}
DEFAULT_ACTOR_OPTIONS = {
    "num_cpus": 0.0, "num_tpu_chips": 0, "resources": None, "max_restarts": 0,
    "max_concurrency": 1, "name": None, "namespace": "default",
    "lifetime": None, "get_if_exists": False, "placement_group": None,
}


def _global_client() -> CoreClient:
    if _client is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _client


def _attach_existing_client(client: CoreClient) -> None:
    """Used by worker processes so user code can call the API inside tasks."""
    global _client
    _client = client


def is_initialized() -> bool:
    return _client is not None


def init(address: Optional[str] = None, *, num_cpus: Optional[float] = None,
         num_tpu_chips: Optional[int] = None, resources: Optional[dict] = None,
         object_store_bytes: Optional[int] = None, max_workers: Optional[int] = None,
         namespace: str = "default",
         runtime_env: Optional[dict] = None) -> dict:
    """Start (or join) a cluster and connect this process as the driver.

    `runtime_env`: driver-level default applied to every task/actor this
    driver submits (reference `ray.init(runtime_env=...)`); per-task
    runtime_env keys override the driver's key-by-key."""
    global _client, _head_proc, _driver_runtime_env
    _driver_runtime_env = dict(runtime_env or {}) or None
    with _lock:
        if _client is not None:
            return _client.node_info
        if address is None and (cfg_addr := _config.get("address")):
            address = cfg_addr
        if address is not None and address.startswith("ray-tpu://"):
            # remote-driver mode (reference Ray Client, `ray://host:port`):
            # everything rides one multiplexed connection to the head-side
            # proxy — no reachability to workers/data servers/shm needed
            from ray_tpu.client_proxy.client import (ProxyClient,
                                                     parse_proxy_address)

            host, port = parse_proxy_address(address)
            client = ProxyClient(host, port)
            client.start()
            _client = client
            atexit.register(shutdown)
            return client.node_info
        if address is None:
            session = f"s{uuid.uuid4().hex[:12]}"
            cmd = [sys.executable, "-m", "ray_tpu.core.head_main",
                   "--session", session,
                   "--object-store-bytes",
                   str(object_store_bytes
                       if object_store_bytes is not None else -1)]
            if num_cpus is not None:
                cmd += ["--num-cpus", str(num_cpus)]
            if num_tpu_chips is not None:
                cmd += ["--num-tpu-chips", str(num_tpu_chips)]
            if resources is not None:
                cmd += ["--resources", json.dumps(resources)]
            if max_workers is not None:
                cmd += ["--max-workers", str(max_workers)]
            from ray_tpu.core.resources import strip_device_env

            head_env = strip_device_env(dict(os.environ))
            # the head still advertises the node's TPU resources; detection is
            # env-based and does not need the device env
            if num_tpu_chips is None and os.environ.get(
                    "JAX_PLATFORMS", "").startswith(("tpu", "axon")):
                head_env.setdefault("RAY_TPU_NUM_CHIPS", "1")
            _head_proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                          stderr=None, text=True, env=head_env)
            line = _head_proc.stdout.readline()
            if not line.startswith("RAY_TPU_HEAD_PORT="):
                raise RuntimeError(f"head failed to start: {line!r}")
            port = int(line.split("=", 1)[1])
            host = "127.0.0.1"
        else:
            host, port_s = address.rsplit(":", 1)
            port = int(port_s)
            session = None
        client = CoreClient(host, port, session or "joined", is_driver=True)
        client.start()
        if session is None:
            client.store.session = client.node_info["session"]
            client.store._arena = None  # re-derive arena name from the session
        _client = client
        atexit.register(shutdown)
        return client.node_info


def shutdown() -> None:
    global _client, _head_proc
    # stop the metrics pusher FIRST: its next tick would race the closing
    # head connection (and pre-fix it spun forever after shutdown)
    from ray_tpu.util import metrics as _metrics

    _metrics.stop_pusher()
    with _lock:
        if _client is not None:
            _client.shutdown()
            _client = None
        if _head_proc is not None:
            _head_proc.terminate()
            try:
                _head_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                _head_proc.kill()
            _head_proc = None
    try:
        atexit.unregister(shutdown)
    except Exception:
        pass


def _auto_init():
    if _client is None:
        init()


# ----------------------------------------------------------------- objects
def put(value: Any) -> ObjectRef:
    _auto_init()
    return _global_client().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        timeout: Optional[float] = None) -> Any:
    # no auto-init: a ref can only come from a live cluster; auto-starting a
    # fresh one here would block forever on a foreign ref
    single = isinstance(refs, ObjectRef)
    out = _global_client().get([refs] if single else list(refs), timeout=timeout)
    return out[0] if single else out


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    return _global_client().wait(list(refs), num_returns=num_returns,
                                 timeout=timeout)


def free(refs: Sequence[ObjectRef]) -> None:
    _global_client().free(list(refs))


_driver_runtime_env: Optional[dict] = None


# ------------------------------------------------------------------- tasks
def _package_renv_cached(holder, client, opts: dict):
    """Package runtime_env once per (holder, client): re-zipping the tree on
    every .remote() call would re-walk and re-hash it per submission."""
    renv = opts.get("runtime_env")
    if _driver_runtime_env:
        # driver default under per-task overrides (reference init-level
        # runtime_env merge: job config < task config, key-by-key)
        renv = {**_driver_runtime_env, **(renv or {})}
    if not renv:
        return None
    key = id(client)
    cache = getattr(holder, "_renv_cache", None)
    if cache is not None and cache[0] == key:
        return cache[1]
    from ray_tpu.core.runtime_env import package_runtime_env

    packaged = package_runtime_env(client, renv)
    holder._renv_cache = (key, packaged)
    return packaged


def _build_resources(opts: dict) -> dict:
    res = {"CPU": float(opts.get("num_cpus", 1.0) or 0.0)}
    if opts.get("num_tpu_chips"):
        res["TPU"] = float(opts["num_tpu_chips"])
    if opts.get("resources"):
        res.update(opts["resources"])
    return {k: v for k, v in res.items() if v}


class RemoteFunction:
    def __init__(self, fn, options: dict):
        self._fn = fn
        self._options = options
        self._fn_key = None
        self._client = None
        functools.update_wrapper(self, fn)

    def _ensure_exported(self):
        client = _global_client()
        if self._fn_key is None or self._client is not client:
            self._fn_key = client.fn_manager.export(self._fn)
            self._client = client
        return self._fn_key

    def remote(self, *args, **kwargs):
        _auto_init()
        fn_key = self._ensure_exported()
        opts = dict(self._options)
        pg = opts.get("placement_group")
        num_returns = opts.get("num_returns", 1)
        from ray_tpu.util import tracing

        task_opts = {"runtime_env": _package_renv_cached(
                         self, _global_client(), opts),
                     "resources": _build_resources(opts),
                     "max_retries": opts.get("max_retries", 3),
                     "max_calls": opts.get("max_calls"),
                     "num_returns": num_returns,
                     "_generator_backpressure_num_objects": opts.get(
                         "_generator_backpressure_num_objects"),
                     "placement_group": pg.id.binary() if pg is not None else None,
                     "placement_group_bundle_index": opts.get(
                         "placement_group_bundle_index"),
                     "label_selector": opts.get("label_selector"),
                     "scheduling_strategy": opts.get("scheduling_strategy", "hybrid"),
                     "name": opts.get("name") or getattr(self._fn, "__name__", "task")}
        for k in ("lineage", "data_stage"):
            # lineage: lease-path dispatches ALSO register the spec in the
            # head's lineage ledger (reconstructable on node loss);
            # data_stage: counts reconstructions into
            # data_blocks_reconstructed_total. Set by the data library.
            if opts.get(k):
                task_opts[k] = True
        with tracing.submit_span(task_opts["name"]):
            # inject INSIDE the span so the worker's execution span parents
            # to the submission span, not to its parent
            task_opts["trace_ctx"] = tracing.inject_context()
            refs = _global_client().submit_task(
                fn_key, args, kwargs, task_opts,
                num_returns=1 if num_returns == "streaming" else num_returns)
        if num_returns == "streaming":
            from ray_tpu.core.object_ref import ObjectRefGenerator

            return ObjectRefGenerator(refs[0].id)
        return refs[0] if num_returns == 1 else refs

    def options(self, **overrides) -> "RemoteFunction":
        rf = RemoteFunction(self._fn, {**self._options, **overrides})
        return rf

    def bind(self, *args, **kwargs):
        """Lazy DAG node (reference dag API: fn.bind())."""
        from ray_tpu.dag.nodes import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self.__name__} cannot be called directly; "
            "use .remote()")

    def __reduce__(self):
        # ship only the definition; the export cache is rebuilt per-process
        return (RemoteFunction, (self._fn, self._options))


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str,
                 call_options: Optional[dict] = None):
        self._handle = handle
        self._name = name
        self._call_options = call_options or {}

    def remote(self, *args, **kwargs) -> ObjectRef:
        return self._handle._call(self._name, args, kwargs,
                                  group=self._call_options.get("concurrency_group"))

    def options(self, **overrides):
        return ActorMethod(self._handle, self._name,
                           {**self._call_options, **overrides})

    def bind(self, *args, **kwargs):
        """Lazy DAG node (reference dag API: actor.method.bind())."""
        from ray_tpu.dag.nodes import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args, kwargs)


class ActorHandle:
    def __init__(self, actor_id: ActorID, methods: dict):
        self._actor_id = actor_id
        self._methods = methods

    def _call(self, method: str, args, kwargs, group=None) -> ObjectRef:
        return _global_client().call_actor(self._actor_id, method, args, kwargs,
                                           group=group)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._methods:
            raise AttributeError(f"actor has no method {name!r}")
        # cache on the instance: `h.ping.remote()` in a hot loop must not
        # allocate a fresh ActorMethod per call (__getattr__ only fires
        # for missing attributes, so this self-memoizes)
        m = ActorMethod(self, name)
        object.__setattr__(self, name, m)
        return m

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._methods))

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:12]})"


class ActorClass:
    def __init__(self, cls, options: dict):
        self._cls = cls
        self._options = options
        self._cls_key = None
        self._client = None

    def _methods_meta(self) -> dict:
        meta = {}
        for name in dir(self._cls):
            fn = getattr(self._cls, name, None)
            if not callable(fn) or name.startswith("__"):
                continue
            meta[name] = dict(getattr(fn, "_ray_tpu_method_options", {}))
        return meta

    def remote(self, *args, **kwargs) -> ActorHandle:
        _auto_init()
        client = _global_client()
        if self._cls_key is None or self._client is not client:
            self._cls_key = client.fn_manager.export(self._cls)
            self._client = client
        opts = dict(self._options)
        pg = opts.get("placement_group")
        actor_opts = {"runtime_env": _package_renv_cached(self, client, opts),
                      "resources": _build_resources({**opts, "num_cpus": opts.get("num_cpus", 0.0)}),
                      "placement_group": pg.id.binary() if pg is not None else None,
                      "placement_group_bundle_index": opts.get(
                          "placement_group_bundle_index"),
                      "label_selector": opts.get("label_selector"),
                      "scheduling_strategy": opts.get("scheduling_strategy", "hybrid"),
                      "max_restarts": opts.get("max_restarts", 0),
                      "max_concurrency": opts.get("max_concurrency", 1),
                      "concurrency_groups": opts.get("concurrency_groups"),
                      "name": opts.get("name"),
                      "namespace": opts.get("namespace", "default"),
                      "lifetime": opts.get("lifetime"),
                      "get_if_exists": opts.get("get_if_exists", False)}
        actor_id = client.create_actor(self._cls_key, args, kwargs, actor_opts,
                                       self._methods_meta())
        return ActorHandle(actor_id, self._methods_meta())

    def options(self, **overrides) -> "ActorClass":
        return ActorClass(self._cls, {**self._options, **overrides})

    def __call__(self, *args, **kwargs):
        raise TypeError("actor class cannot be instantiated directly; "
                        "use .remote()")

    def __reduce__(self):
        return (ActorClass, (self._cls, self._options))


def remote(*args, **options):
    """@remote decorator for functions and classes (with or without options)."""

    def wrap(obj):
        if isinstance(obj, type):
            return ActorClass(obj, options)
        return RemoteFunction(obj, options)

    if len(args) == 1 and callable(args[0]) and not options:
        return wrap(args[0])
    return wrap


def put_device(value) -> ObjectRef:
    """Store a device-resident value (e.g. a jax.Array) in THIS process's
    device object store — zero-copy for same-process consumers, host-staged
    transfer for remote ones (reference RDT `tensor_transport` design,
    `gpu_object_manager.py:22-56`)."""
    _auto_init()
    return _global_client().put_device(value)


def method(**options):
    def deco(fn):
        fn._ray_tpu_method_options = options
        return fn

    return deco


def kill(handle: ActorHandle, *, no_restart: bool = True) -> None:
    _global_client().kill_actor(handle._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False) -> str:
    """Cancel the task producing `ref`: queued tasks are dropped; running
    tasks get TaskCancelledError raised in their thread (force kills the
    worker). `get(ref)` then raises TaskCancelledError."""
    return _global_client().head_request(
        "cancel_task", return_id=ref.id.binary(), force=force)


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    _auto_init()
    meta = _global_client().head_request("get_named_actor", name=name,
                                         namespace=namespace)
    if meta is None:
        raise ValueError(f"no actor named {name!r}")
    return ActorHandle(ActorID(meta["actor_id"]), meta["methods"])


# ------------------------------------------------------------------- state
def nodes() -> list:
    return _global_client().head_request("list_state", kind="nodes")


def cluster_resources() -> dict:
    return _global_client().head_request("cluster_info")["total_resources"]


def available_resources() -> dict:
    return _global_client().head_request("cluster_info")["available_resources"]


class RuntimeContext:
    def __init__(self, client: CoreClient):
        self._client = client

    @property
    def worker_id(self):
        return self._client.worker_id

    @property
    def node_id(self):
        return self._client.node_info.get("node_id")

    @property
    def session(self):
        return self._client.session


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_global_client())


actor = remote  # alias
