"""Cross-node object data plane: chunked pulls + the node pull manager.

Capability parity with the reference object manager
(`src/ray/object_manager/object_manager.h`, `pull_manager.h:49`
admission-controlled pulls, `push_manager.h:27`, chunking in
`chunk_object_reader.cc`), re-designed for this runtime: every node (the
head in-process, worker nodes in their node daemon) runs a tiny data
server that serves `fetch_chunk` reads straight out of the node-local shm
store; a consumer that misses locally resolves serving nodes from the
gossiped object directory (head `locate_object` on cold miss), pulls
chunks with a pipelined window, and seals a local cached copy.

Pull-based only: the scheduler already co-locates most consumers with
producers, and a pull is self-admitting (the puller bounds its own
concurrency) where pushes would need receiver-side flow control.

The **PullManager** is the grown-up version of the original single-source
helper: one in-flight pull per object id with shared waiters, multi-source
failover across advertised replicas, per-chunk retry/backoff riding the
chaos plane, an LRU replica cache whose contents are announced back into
the object directory, and bandwidth/latency accounting
(`object_pull_bytes_total`, `object_pull_seconds` on `/metrics`). Node
daemons own one and serve `pull_object` to their local workers, so each
object crosses the network once per node; the head runs one for its own
node's workers; drivers embed one for direct pulls.
"""

from __future__ import annotations

import asyncio
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.core.store import ObjectMeta, SharedMemoryStore
from ray_tpu.core.ids import ObjectID

from ray_tpu.core import config as _config
from ray_tpu.core import protocol


def CHUNK() -> int:
    return _config.get("transfer_chunk_bytes")


def WINDOW() -> int:
    return _config.get("transfer_window")


def SERVER_CONCURRENCY() -> int:
    return _config.get("transfer_server_reads")


# ------------------------------------------------------------------ metrics
_metrics = None


def _get_metrics():
    """Lazy data-plane series (one registry per process; daemon registries
    ride gossip to the head's /metrics, drivers/workers use the pusher)."""
    global _metrics
    if _metrics is None:
        from ray_tpu.util import metrics as m

        _metrics = {
            "bytes": m.Counter(
                "object_pull_bytes_total",
                "Bytes pulled over the object data plane",
                tag_keys=("role",)),
            "pulls": m.Counter(
                "object_pulls_total",
                "Completed cross-node object pulls",
                tag_keys=("role",)),
            "retries": m.Counter(
                "object_pull_retries_total",
                "Chunk retries + source failovers during pulls",
                tag_keys=("role",)),
            "seconds": m.Histogram(
                "object_pull_seconds",
                "Wall time of completed object pulls",
                boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5,
                            1.0, 2.5, 5.0, 10.0, 30.0],
                tag_keys=("role",)),
        }
    return _metrics


def make_data_handlers(get_store: Callable[[], Optional[SharedMemoryStore]],
                       get_pull_manager: Callable[[], Optional["PullManager"]]
                       = lambda: None):
    """Handler table for a node's data server. `get_store` is a thunk so
    the daemon can start serving before its store exists (registration
    assigns the session first); `get_pull_manager` likewise exposes the
    node's pull manager to local workers (`pull_object` RPC) once it is
    wired up."""
    sems: Dict[int, asyncio.Semaphore] = {}

    def _sem() -> asyncio.Semaphore:
        # one semaphore per event loop (handlers may serve from the head
        # loop in-process and from tests' loops)
        key = id(asyncio.get_running_loop())
        if key not in sems:
            sems[key] = asyncio.Semaphore(SERVER_CONCURRENCY())
        return sems[key]

    async def fetch_chunk(meta: ObjectMeta, offset: int, length: int):
        import pickle

        async with _sem():
            store = get_store()
            if store is None:
                raise FileNotFoundError("store not initialized")
            try:
                view, release = store.get_raw(meta, offset, length)
            except FileNotFoundError:
                # the requester resolved US from a replica announcement:
                # its meta describes the PRIMARY's storage (a segment
                # that only exists there) — translate by object id to
                # our pull manager's local replica copy
                manager = get_pull_manager()
                local = (manager.cached(meta.object_id)
                         if manager is not None else None)
                if local is None or local.size != meta.size:
                    raise
                view, release = store.get_raw(local, offset, length)
            if len(view) != length:
                if release is not None:
                    view.release()
                    release()
                raise FileNotFoundError(
                    f"object {meta.object_id} short read at {offset}: "
                    f"{len(view)} != {length}")
            if release is not None:
                # pinned (arena) read: copy before unpinning — the mapping
                # could be reused by a new allocation once unpinned
                try:
                    return bytes(view)
                finally:
                    view.release()
                    release()
            # shm/spill/inline: ship the slice out-of-band with no copy
            # (the segment mapping stays alive via the store's cache)
            return pickle.PickleBuffer(view)

    async def pull_object_rpc(meta: ObjectMeta, sources=None, trace=None):
        """Node-level pull on behalf of a co-located worker: the daemon's
        pull manager fetches the object into the NODE store once (in-flight
        dedup + replica cache), and every local worker maps the same copy —
        each object crosses the network once per node. `trace` carries the
        consuming task's W3C context so the daemon-side pull span joins
        that trace."""
        manager = get_pull_manager()
        if manager is None:
            raise FileNotFoundError("no pull manager on this node")
        store = get_store()
        if store is not None and store.readable(meta):
            try:  # already local (producer lives here / raced another pull)
                view, rel = store.get_raw(meta, 0, 0)
                view.release()
                if rel is not None:
                    rel()
                return meta
            except FileNotFoundError:
                pass
        from ray_tpu.util import tracing

        with tracing.adopt_context(trace):
            local = await manager.pull(
                meta, sources=[tuple(s) for s in sources or ()])
        return local

    async def data_ping() -> bool:
        return True

    return {"fetch_chunk": fetch_chunk, "pull_object": pull_object_rpc,
            "data_ping": data_ping}


async def pull_object(conn, meta: ObjectMeta, store: SharedMemoryStore,
                      role: str = "client") -> ObjectMeta:
    """Pull one object over an established data connection into the local
    store. Chunks are requested with a pipelined window of WINDOW in
    flight (the admission-control role of the reference PullManager's
    chunked gets); a failed chunk is retried with backoff while the
    connection is alive (injected chaos drops/delays on the data edge are
    absorbed here). Returns the local cached-copy meta."""
    pending = store.allocate_raw(meta.object_id, meta.size)
    retries = max(int(_config.get("transfer_chunk_retries")), 0)
    backoff = float(_config.get("transfer_retry_backoff_s"))

    def _permanent(e: BaseException) -> bool:
        """Not-found style failures are deterministic — the object is not
        (or no longer) at this source; retrying the chunk only delays the
        caller's failover to the next advertised source. Retry is for the
        transient class: injected drops, lost frames, timeouts."""
        return isinstance(e, FileNotFoundError) or (
            isinstance(e, protocol.RemoteError)
            and "FileNotFoundError" in str(e))

    async def _fetch(o: int, ln: int, attempt: int):
        """Fetch one chunk; retry backoff sleeps INSIDE this task, so a
        failing chunk never head-of-line-blocks the rest of the window.
        A chaos `drop` raises ConnectionLost at send time while the
        connection stays alive — normalized into the same failure path
        as a dropped reply."""
        if attempt:
            _get_metrics()["retries"].inc(tags={"role": role})
            await asyncio.sleep(min(backoff * (2 ** (attempt - 1)), 1.0))
            if conn.closed:
                raise protocol.ConnectionLost(
                    f"connection {conn.name} closed")
        try:
            fut = conn.request_future("fetch_chunk", meta=meta,
                                      offset=o, length=ln)
        except protocol.ConnectionLost:
            if conn.closed:
                raise
            raise protocol.ConnectionLost("injected drop at send")
        data = await fut
        got = memoryview(data).nbytes if data is not None else 0
        if got != ln:
            # a silently short chunk would seal a zero-padded buffer
            # that deserializes to corrupt data downstream
            raise FileNotFoundError(
                f"short chunk for {meta.object_id} at {o}: {got} != {ln}")
        return data

    tasks: Dict[asyncio.Task, Tuple[int, int, int]] = {}
    try:
        chunk = CHUNK()
        offsets = list(range(0, meta.size, chunk)) or [0]
        idx = 0
        while idx < len(offsets) or tasks:
            while idx < len(offsets) and len(tasks) < WINDOW():
                o = offsets[idx]
                idx += 1
                ln = min(chunk, meta.size - o)
                t = asyncio.ensure_future(_fetch(o, ln, 0))
                tasks[t] = (o, ln, 0)
            done, _ = await asyncio.wait(
                tasks, return_when=asyncio.FIRST_COMPLETED)
            for t in done:
                o, ln, attempt = tasks.pop(t)
                try:
                    data = t.result()
                except (protocol.RpcError, FileNotFoundError) as e:
                    if _permanent(e) or attempt >= retries or (
                            isinstance(e, protocol.ConnectionLost)
                            and conn.closed):
                        raise
                    # chunk-level retry: dropped/failed fetches (chaos
                    # plane, transient server errors) re-request with
                    # backoff instead of abandoning the whole pull
                    nt = asyncio.ensure_future(_fetch(o, ln, attempt + 1))
                    tasks[nt] = (o, ln, attempt + 1)
                    continue
                if ln:
                    pending.write(o, data)
        local = pending.seal()
    except BaseException:
        for t in tasks:
            t.cancel()
        pending.abort()
        raise
    local.error = meta.error
    local.owner = meta.owner
    return local


class PullManager:
    """Admission-controlled, deduplicated, failover-capable object pulls
    for one process (reference `pull_manager.h:49`).

    - one in-flight pull per object id; concurrent requesters share it
      (shielded, so one canceled waiter doesn't kill the transfer);
    - multi-source failover: sources beyond the first are tried in order
      when a pull attempt fails (node died, object moved, chaos);
    - `resolve(meta)` (optional, async) supplies sources when the caller
      has none — the daemon resolves from its cached object directory and
      cluster view, falling back to the head;
    - completed pulls land in an LRU replica cache bounded by
      `cache_bytes`; evicted replicas are unlinked and `on_replica_gone`
      fires so the directory forgets them.
    """

    def __init__(self, get_store: Callable[[], Optional[SharedMemoryStore]],
                 *, role: str = "node",
                 resolve: Optional[Callable] = None,
                 cache_bytes: Optional[int] = None,
                 on_replica: Optional[Callable[[ObjectMeta], None]] = None,
                 on_replica_gone: Optional[Callable[[ObjectID], None]] = None,
                 max_concurrent: int = 4):
        self.get_store = get_store
        self.role = role
        self.resolve = resolve
        self.cache_bytes = (cache_bytes if cache_bytes is not None
                            else _config.get("replica_cache_bytes"))
        self.on_replica = on_replica
        self.on_replica_gone = on_replica_gone
        self._tasks: Dict[ObjectID, asyncio.Task] = {}
        self._conns: Dict[Tuple[str, int], protocol.Connection] = {}
        self._connecting: Dict[Tuple[str, int], asyncio.Task] = {}
        self._sem = asyncio.Semaphore(max_concurrent)
        self._replicas: "OrderedDict[ObjectID, ObjectMeta]" = OrderedDict()
        self._replica_bytes = 0
        # lifetime counters, gossiped in sched_stats (observable without
        # scraping /metrics)
        self.stats = {"object_pulls": 0, "object_pull_bytes": 0,
                      "object_pull_failovers": 0}

    # ------------------------------------------------------------- cache
    def cached(self, oid: ObjectID) -> Optional[ObjectMeta]:
        local = self._replicas.get(oid)
        if local is not None:
            self._replicas.move_to_end(oid)
        return local

    def replica_ids(self) -> List[ObjectID]:
        return list(self._replicas)

    def replica_count(self) -> int:
        return len(self._replicas)

    def drop(self, oid: ObjectID, announce: bool = False) -> None:
        """Forget (and unlink) a cached replica — the canonical object was
        freed, or the cache evicted it."""
        local = self._replicas.pop(oid, None)
        if local is None:
            return
        self._replica_bytes -= local.size
        store = self.get_store()
        if store is not None:
            try:
                store.free(local)
            except Exception:
                pass
        if announce and self.on_replica_gone is not None:
            self.on_replica_gone(oid)

    def _note_replica(self, local: ObjectMeta) -> None:
        old = self._replicas.pop(local.object_id, None)
        if old is not None:
            self._replica_bytes -= old.size
        self._replicas[local.object_id] = local
        self._replica_bytes += local.size
        while self._replica_bytes > self.cache_bytes and len(self._replicas) > 1:
            evict_oid = next(iter(self._replicas))
            self.drop(evict_oid, announce=True)
        if old is None and self.on_replica is not None:
            self.on_replica(local)

    # -------------------------------------------------------------- pulls
    async def pull(self, meta: ObjectMeta,
                   sources: Optional[List[Tuple[str, int]]] = None
                   ) -> ObjectMeta:
        """Produce a locally-readable meta for `meta`, pulling at most
        once per object id regardless of concurrent callers."""
        oid = meta.object_id
        local = self.cached(oid)
        if local is not None:
            return local
        task = self._tasks.get(oid)
        if task is None:
            task = asyncio.ensure_future(self._pull_once(meta, sources))
            self._tasks[oid] = task
            task.add_done_callback(
                lambda t, o=oid: self._tasks.pop(o, None))
        return await asyncio.shield(task)

    async def _conn_to(self, addr: Tuple[str, int]) -> protocol.Connection:
        conn = self._conns.get(addr)
        if conn is not None and not conn.closed:
            return conn
        # connect-once per address: a cold burst of concurrent pulls to
        # one source must share a single connection attempt, not race N
        # connects and leak the N-1 that lose the dict write
        pending = self._connecting.get(addr)
        if pending is None:
            pending = asyncio.ensure_future(protocol.connect(
                addr[0], addr[1], name=f"data-{addr[1]}"))
            self._connecting[addr] = pending
            pending.add_done_callback(
                lambda t, a=addr: self._connecting.pop(a, None))
        conn = await asyncio.shield(pending)
        self._conns[addr] = conn
        return conn

    async def _pull_once(self, meta: ObjectMeta,
                         sources: Optional[List[Tuple[str, int]]]
                         ) -> ObjectMeta:
        store = self.get_store()
        if store is None:
            raise FileNotFoundError("store not initialized")
        candidates = [tuple(s) for s in sources or ()]
        if not candidates and self.resolve is not None:
            candidates = [tuple(s) for s in await self.resolve(meta) or ()]
        if not candidates:
            raise FileNotFoundError(
                f"object {meta.object_id} has no known source")
        from ray_tpu.util import tracing

        with tracing.start_span(
                "object_pull",
                attributes={"ray_tpu.op": "object_pull",
                            "object_id": meta.object_id.hex()[:16],
                            "size": meta.size, "via": self.role}):
            return await self._pull_candidates(meta, store, candidates,
                                               sources)

    async def _pull_candidates(self, meta, store, candidates, sources):
        last_exc: Optional[BaseException] = None
        t0 = time.perf_counter()
        resolved_extra = False
        i = -1
        while i + 1 < len(candidates):
            i += 1
            addr = candidates[i]
            if i:
                self.stats["object_pull_failovers"] += 1
                _get_metrics()["retries"].inc(tags={"role": self.role})
            try:
                conn = await self._conn_to(addr)
                async with self._sem:  # pull admission control
                    local = await pull_object(conn, meta, store,
                                              role=self.role)
            except (protocol.RpcError, OSError, FileNotFoundError) as e:
                last_exc = e
                if (i + 1 == len(candidates) and sources
                        and not resolved_extra and self.resolve is not None):
                    # every caller-hinted source failed (stale view, node
                    # moved): one resolver pass may know fresher replicas
                    resolved_extra = True
                    for s in await self.resolve(meta) or ():
                        if tuple(s) not in candidates:
                            candidates.append(tuple(s))
                continue
            elapsed = time.perf_counter() - t0
            m = _get_metrics()
            m["bytes"].inc(local.size, tags={"role": self.role})
            m["pulls"].inc(tags={"role": self.role})
            m["seconds"].observe(elapsed, tags={"role": self.role})
            self.stats["object_pulls"] += 1
            self.stats["object_pull_bytes"] += local.size
            self._note_replica(local)
            return local
        raise last_exc if last_exc is not None else FileNotFoundError(
            f"object {meta.object_id} unreachable")

    async def close(self) -> None:
        for conn in list(self._conns.values()):
            try:
                await conn.close()
            except Exception:
                pass
        self._conns.clear()
