"""Cross-node object data plane: chunked pull of object bytes.

Capability parity with the reference object manager
(`src/ray/object_manager/object_manager.h`, `pull_manager.h:49`
admission-controlled pulls, `push_manager.h:27`, chunking in
`chunk_object_reader.cc`), re-designed for this runtime: every node (the
head in-process, worker nodes in their node daemon) runs a tiny data
server that serves `fetch_chunk` reads straight out of the node-local shm
store; a consumer that misses locally resolves the owner node's data
address (from the meta's node_id or the head's object directory), pulls
chunks with a pipelined window, and seals a process-local cached copy.

Pull-based only: the scheduler already co-locates most consumers with
producers, and a pull is self-admitting (the puller bounds its own
concurrency) where pushes would need receiver-side flow control.
"""

from __future__ import annotations

import asyncio
import os
from typing import Callable, Dict, Optional

from ray_tpu.core.store import ObjectMeta, SharedMemoryStore

from ray_tpu.core import config as _config


def CHUNK() -> int:
    return _config.get("transfer_chunk_bytes")


def WINDOW() -> int:
    return _config.get("transfer_window")


def SERVER_CONCURRENCY() -> int:
    return _config.get("transfer_server_reads")


def make_data_handlers(get_store: Callable[[], Optional[SharedMemoryStore]]):
    """Handler table for a node's data server. `get_store` is a thunk so
    the daemon can start serving before its store exists (registration
    assigns the session first)."""
    sems: Dict[int, asyncio.Semaphore] = {}

    def _sem() -> asyncio.Semaphore:
        # one semaphore per event loop (handlers may serve from the head
        # loop in-process and from tests' loops)
        key = id(asyncio.get_running_loop())
        if key not in sems:
            sems[key] = asyncio.Semaphore(SERVER_CONCURRENCY())
        return sems[key]

    async def fetch_chunk(meta: ObjectMeta, offset: int, length: int):
        import pickle

        async with _sem():
            store = get_store()
            if store is None:
                raise FileNotFoundError("store not initialized")
            view, release = store.get_raw(meta, offset, length)
            if len(view) != length:
                if release is not None:
                    view.release()
                    release()
                raise FileNotFoundError(
                    f"object {meta.object_id} short read at {offset}: "
                    f"{len(view)} != {length}")
            if release is not None:
                # pinned (arena) read: copy before unpinning — the mapping
                # could be reused by a new allocation once unpinned
                try:
                    return bytes(view)
                finally:
                    view.release()
                    release()
            # shm/spill/inline: ship the slice out-of-band with no copy
            # (the segment mapping stays alive via the store's cache)
            return pickle.PickleBuffer(view)

    async def data_ping() -> bool:
        return True

    return {"fetch_chunk": fetch_chunk, "data_ping": data_ping}


async def pull_object(conn, meta: ObjectMeta, store: SharedMemoryStore) -> ObjectMeta:
    """Pull one object over an established data connection into the local
    store. Chunks are requested with a pipelined window of WINDOW in
    flight (the admission-control role of the reference PullManager's
    chunked gets). Returns the local cached-copy meta."""
    pending = store.allocate_raw(meta.object_id, meta.size)
    try:
        chunk = CHUNK()
        offsets = list(range(0, meta.size, chunk)) or [0]
        idx = 0
        inflight: Dict[int, asyncio.Future] = {}
        while idx < len(offsets) or inflight:
            while idx < len(offsets) and len(inflight) < WINDOW():
                o = offsets[idx]
                idx += 1
                ln = min(chunk, meta.size - o)
                inflight[o] = conn.request_future(
                    "fetch_chunk", meta=meta, offset=o, length=ln)
            o = min(inflight)
            data = await inflight.pop(o)
            expected = min(chunk, meta.size - o)
            got = memoryview(data).nbytes if data is not None else 0
            if got != expected:
                # a silently short chunk would seal a zero-padded buffer
                # that deserializes to corrupt data downstream
                raise FileNotFoundError(
                    f"short chunk for {meta.object_id} at {o}: "
                    f"{got} != {expected}")
            if expected:
                pending.write(o, data)
        local = pending.seal()
    except BaseException:
        for fut in inflight.values():
            fut.cancel()
        pending.abort()
        raise
    local.error = meta.error
    local.owner = meta.owner
    return local
