"""Node-local shared-memory object store with spill-to-disk.

Capability-equivalent of the reference's plasma store + external storage
(`src/ray/object_manager/plasma/`, `python/ray/_private/external_storage.py`):
immutable sealed objects in node-shared memory, zero-copy reads from any
process on the node, LRU spill to disk under memory pressure.

Two backends:
- **native arena** (default when the C++ toolchain is present): one mmap'd
  shm segment per node managed by `ray_tpu/_native/arena_store.cc` — embedded
  allocator, object table, LRU, refcount pinning (the plasma equivalent).
  The node's head daemon creates it and drives watermark spilling; every
  other process attaches by name.
- **per-object segments** (fallback, and overflow path when the arena is
  full): Python `multiprocessing.shared_memory`, one segment per object.

Small objects stay inline and never touch shm (the reference's in-process
memory store fast path).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import OrderedDict
from multiprocessing import shared_memory
from typing import Dict, Optional

from ray_tpu.core import config as _config
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.serialization import SerializedObject
from ray_tpu.utils import fs as _fs

_fsopen = _fs.open  # spill files may live on fsspec storage (URIs)

from ray_tpu.utils.platform import STATE_DIR

INLINE_THRESHOLD = 100 * 1024  # small objects ride the control plane inline


def default_store_bytes() -> int:
    """Reference-parity sizing (`python/ray/_private/node.py:1409`
    determine_plasma_store_config): 30% of system memory, capped by what
    /dev/shm can actually hold. The old fixed 2 GiB default forced big
    put workloads through watermark spilling and fresh page-faulting
    overflow segments — the measured multi-client put regression."""
    try:
        ram = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError):
        ram = 8 << 30
    try:
        st = os.statvfs("/dev/shm")
        shm_free = st.f_bavail * st.f_frsize
    except OSError:
        shm_free = ram // 2
    return max(512 << 20, min(int(ram * 0.30), int(shm_free * 0.80)))
ARENA_HIGH_WATERMARK = 0.85    # head starts spilling above this fill ratio
ARENA_LOW_WATERMARK = 0.75     # ...down to this


@dataclasses.dataclass
class ObjectMeta:
    object_id: ObjectID
    size: int
    kind: str                      # "inline" | "shm" | "arena" | "spilled"
    segment: Optional[str] = None  # shm segment name (or arena name)
    inline: Optional[bytes] = None # inline payload (kind == "inline")
    spill_path: Optional[str] = None
    node_id: Optional[object] = None
    owner: Optional[object] = None  # WorkerID of owner
    error: bool = False             # payload is a serialized exception
    contained: Optional[list] = None  # ObjectIDs of refs nested inside


class PendingObject:
    """An allocated-but-unsealed local object being filled by a remote pull
    (plasma Create/Seal semantics, `src/ray/object_manager/plasma/store.h`)."""

    def __init__(self, store: "SharedMemoryStore", obj_id: ObjectID, size: int,
                 buf: Optional[bytearray] = None, shm=None,
                 segment: Optional[str] = None):
        self.store = store
        self.object_id = obj_id
        self.size = size
        self._buf = buf
        self._shm = shm
        self._segment = segment
        self.view = (memoryview(buf) if buf is not None
                     else memoryview(shm.buf)[:size])

    def write(self, offset: int, data) -> None:
        from ray_tpu.core.serialization import np_copy_into

        np_copy_into(self.view, offset, data)

    def seal(self) -> ObjectMeta:
        self.view.release()
        if self._buf is not None:
            return ObjectMeta(self.object_id, self.size, "inline",
                              inline=bytes(self._buf))
        meta = ObjectMeta(self.object_id, self.size, "shm",
                          segment=self._segment)
        self.store._meta_by_segment[self._segment] = meta
        return meta

    def abort(self) -> None:
        self.view.release()
        if self._shm is None:
            return
        with self.store._lock:
            self.store._segments.pop(self._segment, None)
            self.store.used -= self.size
        try:
            self._shm.close()
            self._shm.unlink()
        except (FileNotFoundError, BufferError):
            pass


def _unregister_tracker(shm: shared_memory.SharedMemory) -> None:
    """We manage segment lifetime explicitly; stop resource_tracker from
    unlinking segments when an attaching process exits."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:
        pass


class SharedMemoryStore:
    """Per-node store. The node's daemon owns creation/eviction; other
    processes attach read-only by segment name."""

    def __init__(self, session: str, capacity_bytes: int = 2 << 30,
                 spill_dir: Optional[str] = None, create_arena: bool = False,
                 namespace: Optional[str] = None):
        self.session = session
        self.capacity = capacity_bytes
        self.used = 0
        # Store namespace: scopes segment/arena names to one logical node.
        # With RAY_TPU_STORE_ISOLATION set, stores REFUSE to read objects
        # from other namespaces even though shm is machine-global — the
        # forced-remote-fetch test mode that makes single-machine clusters
        # behave like real multi-host slices (object data must then travel
        # through the node data servers, reference object_manager.cc).
        self.namespace = (namespace if namespace is not None
                          else _config.get("store_namespace"))
        self.isolated = _config.get("store_isolation")
        tag = f"{self.namespace}_" if self.namespace else ""
        self._seg_prefix = f"rtpu_{tag}{session[:8]}_"
        # RAY_TPU_SPILL_DIR may be an fsspec URI (s3://..., memory://) —
        # remote spill storage, reference external_storage.py:398
        # ExternalStorageSmartOpenImpl
        self.spill_dir = (spill_dir
                          or _config.get("spill_dir")
                          or os.path.join(
                              STATE_DIR, session,
                              f"spill_{self.namespace}" if self.namespace
                              else "spill"))
        # a config-provided dir (RAY_TPU_SPILL_DIR) is typically SHARED
        # across every node of the cluster (and may hold user data):
        # shutdown must never sweep it — only dirs this store derived (or
        # was handed) for itself are its to destroy
        self._sweepable_spill = bool(spill_dir) or not _config.get("spill_dir")
        self._segments: "OrderedDict[str, shared_memory.SharedMemory]" = OrderedDict()
        self._meta_by_segment: Dict[str, ObjectMeta] = {}
        self._pinned: Dict[str, int] = {}
        self._lock = threading.Lock()
        # invoked with the retargeted meta after a spill — lets a node
        # daemon tell the head to update the canonical directory entry
        self.on_spill = None
        # native arena backend (plasma equivalent); the head creates, others
        # lazily attach. None until first use; False = unavailable.
        self.owns_arena = create_arena
        self._arena = None
        self._arena_metas: Dict[bytes, ObjectMeta] = {}  # head-side, for spill
        if create_arena and not _config.get("disable_native_store"):
            from ray_tpu.core import native_store

            try:
                self._arena = native_store.Arena.create(
                    self._arena_name(), capacity_bytes)
            except Exception:
                self._arena = False

    def _arena_name(self) -> str:
        tag = f"{self.namespace}_" if self.namespace else ""
        return f"rtpu_arena_{tag}{self.session[:16]}"

    def readable(self, meta: ObjectMeta) -> bool:
        """Whether this store may read the object locally. Always true
        outside isolation mode (shm is machine-global); under isolation,
        only objects in our own namespace are local."""
        if not self.isolated or meta.kind == "inline":
            return True
        if meta.kind == "shm":
            return bool(meta.segment) and meta.segment.startswith(self._seg_prefix)
        if meta.kind == "arena":
            return meta.segment == self._arena_name()
        if meta.kind == "spilled":
            return bool(meta.spill_path) and meta.spill_path.startswith(self.spill_dir)
        return True

    def _get_arena(self):
        if self._arena is not None:
            return self._arena or None
        if _config.get("disable_native_store"):
            self._arena = False
            return None
        from ray_tpu.core import native_store

        try:
            self._arena = native_store.Arena.attach(self._arena_name())
        except Exception:
            self._arena = False  # no arena for this session; use segments
        return self._arena or None

    # -- creation ----------------------------------------------------------
    def put_serialized(self, obj_id: ObjectID, ser: SerializedObject) -> ObjectMeta:
        size = ser.frame_bytes
        if size <= INLINE_THRESHOLD:
            return ObjectMeta(obj_id, size, "inline", inline=ser.to_bytes())
        meta = self._try_put_arena(obj_id, ser, size)
        if meta is not None:
            return meta
        # random suffix: a retried task must not collide with a segment left
        # behind by a dead attempt for the same return object id
        name = f"{self._seg_prefix}{obj_id.hex()[:12]}_{os.urandom(3).hex()}"
        with self._lock:
            self._ensure_capacity(size)
            shm = shared_memory.SharedMemory(create=True, size=size, name=name)
            _unregister_tracker(shm)
            self._segments[name] = shm
            self.used += size
        ser.write_into(memoryview(shm.buf))
        meta = ObjectMeta(obj_id, size, "shm", segment=name)
        self._meta_by_segment[name] = meta
        return meta

    def _try_put_arena(self, obj_id: ObjectID, ser: SerializedObject,
                       size: int) -> Optional[ObjectMeta]:
        arena = self._get_arena()
        if arena is None:
            return None
        from ray_tpu.core.native_store import ArenaError, ObjectExistsError

        oid = obj_id.binary()
        try:
            try:
                buf = arena.create_buffer(oid, size)
            except ObjectExistsError:
                if arena.contains(oid):
                    # a racing duplicate execution (retry/reconstruction)
                    # already sealed this object — puts are idempotent by
                    # object id; NEVER delete the winner's data
                    return ObjectMeta(obj_id, size, "arena",
                                      segment=arena.name)
                # a dead attempt left an unsealed entry; reclaim it
                arena.delete(oid, force=True)
                buf = arena.create_buffer(oid, size)
            ser.write_into(buf)
            buf.release()
            arena.seal(oid)
        except ArenaError:
            # full (or unhealthy): overflow to a per-object segment; the head
            # spills arena objects at the watermark to make future room
            return None
        if self.owns_arena:
            self._maybe_spill_arena()
        return ObjectMeta(obj_id, size, "arena", segment=arena.name)

    def adopt(self, meta: ObjectMeta) -> bool:
        """Track an object created by another process on this node
        (accounting, LRU ordering, spill eligibility). Returns False when
        this store cannot see the object — the caller then forwards
        adoption to the node that can (isolation / real multi-host)."""
        if not self.readable(meta):
            return False  # another node's object: not ours to track
        if meta.kind == "arena":
            if self.owns_arena:
                self._arena_metas[meta.object_id.binary()] = meta
                self._maybe_spill_arena()
            return True
        if meta.kind != "shm" or meta.segment is None:
            return True
        with self._lock:
            if meta.segment in self._segments:
                self._meta_by_segment[meta.segment] = meta
                return True
            self._ensure_capacity(meta.size)
            try:
                shm = shared_memory.SharedMemory(name=meta.segment)
            except FileNotFoundError:
                return False
            _unregister_tracker(shm)
            self._segments[meta.segment] = shm
            self._meta_by_segment[meta.segment] = meta
            self.used += meta.size
        return True

    # -- reads -------------------------------------------------------------
    def get_serialized(self, meta: ObjectMeta) -> SerializedObject:
        if meta.kind == "inline":
            return SerializedObject.from_view(memoryview(meta.inline))
        if not self.readable(meta):
            # foreign namespace: surfaced identically to a missing segment
            # so callers fall into the remote-pull path
            raise FileNotFoundError(meta.segment or meta.spill_path)
        if meta.kind == "spilled":
            with _fsopen(meta.spill_path, "rb") as f:
                return SerializedObject.from_view(memoryview(f.read()))
        if meta.kind == "arena":
            arena = self._get_arena()
            if arena is None:
                raise FileNotFoundError(meta.segment)
            try:
                # pins the object (plasma semantics: zero-copy views stay
                # valid until release/free); raises KeyError when the head
                # evicted/spilled it — surfaced as FileNotFoundError so the
                # caller refreshes the meta and reads the spill file
                view = arena.get(meta.object_id.binary(), pin=True)
            except KeyError:
                raise FileNotFoundError(meta.segment) from None
            return SerializedObject.from_view(view)
        with self._lock:
            shm = self._segments.get(meta.segment)
            if shm is not None:
                self._segments.move_to_end(meta.segment)  # LRU touch
        if shm is None:
            shm = shared_memory.SharedMemory(name=meta.segment)
            _unregister_tracker(shm)
            with self._lock:
                self._segments.setdefault(meta.segment, shm)  # cache attachment
        # NOTE: the returned buffers alias shm memory; callers must copy or
        # finish deserializing before the object is freed.
        return SerializedObject.from_view(memoryview(shm.buf))

    def get_raw(self, meta: ObjectMeta, offset: int = 0,
                length: Optional[int] = None):
        """Raw frame bytes [offset, offset+length) of a local object, for
        the node data server's chunked reads.

        Returns (memoryview of the window, release_cb|None). The caller
        must invoke release_cb (if set) when done — arena reads pin the
        object against eviction while the view is alive."""
        end = meta.size if length is None else min(offset + length, meta.size)
        if meta.kind == "inline":
            return memoryview(meta.inline)[offset:end], None
        if not self.readable(meta):
            raise FileNotFoundError(meta.segment or meta.spill_path)
        if meta.kind == "spilled":
            # window read — a whole-file read per 4 MiB chunk would make
            # pulls of spilled objects O(size^2) in disk I/O
            with _fsopen(meta.spill_path, "rb") as f:
                f.seek(offset)
                return memoryview(f.read(end - offset)), None
        if meta.kind == "arena":
            arena = self._get_arena()
            if arena is None:
                raise FileNotFoundError(meta.segment)
            oid = meta.object_id.binary()
            try:
                view = arena.get(oid, pin=True)
            except KeyError:
                raise FileNotFoundError(meta.segment) from None
            return memoryview(view)[offset:end], lambda: arena.release(oid)
        with self._lock:
            shm = self._segments.get(meta.segment)
        if shm is None:
            shm = shared_memory.SharedMemory(name=meta.segment)
            _unregister_tracker(shm)
            with self._lock:
                self._segments.setdefault(meta.segment, shm)
        return memoryview(shm.buf)[offset:end], None

    def allocate_raw(self, obj_id: ObjectID, size: int) -> "PendingObject":
        """Writable destination for an incoming remote object (pull target).

        Deliberately bypasses the arena: pulled copies are process-managed
        caches the puller must be able to unlink itself, and foreign-created
        arena entries would be invisible to the arena owner's spill
        accounting."""
        if size <= INLINE_THRESHOLD:
            return PendingObject(self, obj_id, size, buf=bytearray(size))
        name = f"{self._seg_prefix}{obj_id.hex()[:12]}_p{os.urandom(3).hex()}"
        with self._lock:
            self._ensure_capacity(size)
            shm = shared_memory.SharedMemory(create=True, size=size, name=name)
            _unregister_tracker(shm)
            self._segments[name] = shm
            self.used += size
        return PendingObject(self, obj_id, size, shm=shm, segment=name)

    # -- lifetime ----------------------------------------------------------
    def pin(self, meta: ObjectMeta) -> None:
        with self._lock:
            if meta.segment:
                self._pinned[meta.segment] = self._pinned.get(meta.segment, 0) + 1

    def unpin(self, meta: ObjectMeta) -> None:
        with self._lock:
            if meta.segment and meta.segment in self._pinned:
                self._pinned[meta.segment] -= 1
                if self._pinned[meta.segment] <= 0:
                    del self._pinned[meta.segment]

    def release(self, meta: ObjectMeta) -> None:
        """Drop this process's mapping/pin without destroying the object
        (freeing is the owner node's job)."""
        if meta.kind == "arena":
            arena = self._get_arena()
            if arena is not None:
                arena.release(meta.object_id.binary())
            return
        if meta.kind != "shm" or not meta.segment:
            return
        with self._lock:
            shm = self._segments.pop(meta.segment, None)
            self._meta_by_segment.pop(meta.segment, None)
        if shm is not None:
            try:
                shm.close()
            except BufferError:
                pass  # live memoryviews still reference it; mapping stays

    def free(self, meta: ObjectMeta) -> None:
        if meta.kind == "arena":
            arena = self._get_arena()
            if arena is None:
                return
            arena.release(meta.object_id.binary())
            if self.owns_arena:
                self._arena_metas.pop(meta.object_id.binary(), None)
                arena.delete(meta.object_id.binary(), force=True)
            return
        if meta.kind == "shm" and meta.segment:
            with self._lock:
                shm = self._segments.pop(meta.segment, None)
                self._meta_by_segment.pop(meta.segment, None)
                if shm is not None:
                    self.used -= meta.size
            if shm is None:
                try:
                    shm = shared_memory.SharedMemory(name=meta.segment)
                except FileNotFoundError:
                    return
                _unregister_tracker(shm)
            shm.close()
            try:
                shm.unlink()
            except FileNotFoundError:
                pass
        elif meta.kind == "spilled" and meta.spill_path:
            try:
                _fs.rm(meta.spill_path)
            except OSError:
                pass

    # -- spilling ----------------------------------------------------------
    def _maybe_spill_arena(self) -> None:
        """Head-side watermark spilling (plasma eviction + external storage):
        above the high watermark, move LRU unpinned arena objects to disk and
        retarget their metas; readers with stale metas refresh via the head."""
        arena = self._get_arena()
        if arena is None or not self.owns_arena:
            return
        used, cap, _ = arena.stats()
        if used <= ARENA_HIGH_WATERMARK * cap:
            return
        needed = used - int(ARENA_LOW_WATERMARK * cap)
        _fs.makedirs(self.spill_dir)
        for oid in arena.evict_candidates(needed):
            meta = self._arena_metas.pop(oid, None)
            if meta is None:
                continue  # not yet adopted (registration in flight): skip
            try:
                view = arena.get(oid, pin=False)
            except KeyError:
                continue
            path = _fs.join(self.spill_dir, oid.hex())
            with _fsopen(path, "wb") as f:
                f.write(view)
            del view
            if not arena.delete(oid, force=False):
                # pinned between candidate selection and delete: keep it
                try:
                    _fs.rm(path)
                except OSError:
                    pass
                self._arena_metas[oid] = meta
                continue
            meta.kind = "spilled"
            meta.spill_path = path
            # segment name retained: readers go by kind/spill_path, and the
            # head uses it to tell a stale pre-spill re-registration (same
            # segment) from a retry's distinct duplicate copy (fresh name)
            if self.on_spill is not None:
                self.on_spill(meta)

    def _ensure_capacity(self, incoming: int) -> None:
        """Spill LRU unpinned segments until `incoming` fits. Lock held."""
        if self.used + incoming <= self.capacity:
            return
        _fs.makedirs(self.spill_dir)
        for name in list(self._segments):
            if self.used + incoming <= self.capacity:
                break
            if name in self._pinned:
                continue
            shm = self._segments.pop(name)
            meta = self._meta_by_segment.pop(name, None)
            path = _fs.join(self.spill_dir, name)
            with _fsopen(path, "wb") as f:
                f.write(shm.buf)
            self.used -= (meta.size if meta else shm.size)
            try:
                shm.close()
            except BufferError:
                pass  # exported views keep the mapping alive; data persists
            try:
                # independent of close(): a BufferError above must not leak
                # the /dev/shm file for the machine's lifetime
                shm.unlink()
            except FileNotFoundError:
                pass
            if meta is not None:
                # readers that already attached keep a valid mapping; new
                # readers see the updated meta and read the spill file
                meta.kind = "spilled"
                meta.spill_path = path
                meta.segment = None
                if self.on_spill is not None:
                    self.on_spill(meta)

    def shutdown(self, sweep_spill: bool = True) -> None:
        with self._lock:
            for shm in self._segments.values():
                shm.close()
                try:
                    shm.unlink()
                except FileNotFoundError:
                    pass
            self._segments.clear()
            self.used = 0
        if self._arena:
            try:
                self._arena.close(unlink=self.owns_arena)
            except Exception:
                pass
            self._arena = False
        if sweep_spill and self._sweepable_spill \
                and (self.owns_arena or self.namespace):
            # spill files are session-scoped storage this node owns: a
            # shut-down node must not leak them on disk forever. Callers
            # rebuilding a store mid-session (head snapshot restore) pass
            # sweep_spill=False — those files are the data being
            # restored. Only the node-owning store sweeps (the head's, or
            # a namespaced per-node store): without a namespace the dir
            # is SHARED across the session's processes, and a single
            # daemon's teardown must not delete its neighbors' files.
            try:
                _fs.rmtree(self.spill_dir)
            except OSError:
                pass
