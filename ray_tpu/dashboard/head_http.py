"""aiohttp app serving cluster state + metrics from inside the head process.

Runs on the head's event loop; all reads are against in-memory tables so no
locking is needed (single-threaded asyncio, like the reference's
GCS-backed StateHead).
"""

from __future__ import annotations

import json
import time
from typing import Optional

from aiohttp import web

_INDEX_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>body{font-family:monospace;margin:2em}table{border-collapse:collapse}
td,th{border:1px solid #ccc;padding:4px 8px;text-align:left}</style></head>
<body><h2>ray_tpu cluster</h2>
<p><a href="/workloads">scheduler &amp; workloads panel</a></p>
<div id="out">loading…</div>
<script>
// user-controlled strings (entrypoints, actor names) must never reach
// innerHTML raw — that's script injection into every dashboard viewer
function esc(v){ const d = document.createElement('div');
  d.textContent = String(v ?? ''); return d.innerHTML; }
async function refresh(){
  const [c, n, a, act, jobs] = await Promise.all(
    ['/api/cluster', '/api/nodes', '/api/summary', '/api/actors?limit=50',
     '/api/jobs/'].map(u => fetch(u).then(r => r.json())));
  let h = `<p>session <b>${esc(c.session)}</b> · uptime ${c.uptime.toFixed(0)}s ·
    ${c.num_nodes} nodes · ${c.num_workers} workers</p>`;
  h += '<h3>resources</h3><table><tr><th>resource</th><th>avail</th><th>total</th></tr>';
  for (const k of Object.keys(c.total_resources))
    h += `<tr><td>${esc(k)}</td><td>${c.available_resources[k]??0}</td><td>${c.total_resources[k]}</td></tr>`;
  h += '</table><h3>tasks</h3><pre>' + esc(JSON.stringify(a.tasks, null, 1)) + '</pre>';
  h += '<h3>nodes</h3><table><tr><th>node</th><th>alive</th><th>head</th><th>resources</th></tr>';
  for (const x of n) h += `<tr><td>${esc(x.node_id.slice(0,12))}</td><td>${x.alive}</td><td>${x.is_head}</td><td>${esc(JSON.stringify(x.resources))}</td></tr>`;
  h += '</table>';
  h += '<h3>actors</h3><table><tr><th>actor</th><th>state</th><th>name</th><th>restarts left</th></tr>';
  for (const x of act)
    h += `<tr><td>${esc(x.actor_id.slice(0,12))}</td><td>${esc(x.state)}</td><td>${esc(x.name)}</td><td>${x.restarts_left}</td></tr>`;
  h += '</table>';
  h += '<h3>jobs</h3><table><tr><th>job</th><th>status</th><th>entrypoint</th></tr>';
  for (const j of jobs.slice(0, 50))
    h += `<tr><td>${esc(j.job_id)}</td><td>${esc(j.status)}</td><td>${esc(j.entrypoint.slice(0, 60))}</td></tr>`;
  h += '</table>';
  document.getElementById('out').innerHTML = h;
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


_WORKLOADS_HTML = """<!doctype html>
<html><head><title>ray_tpu scheduler &amp; workloads</title>
<style>body{font-family:monospace;margin:2em}table{border-collapse:collapse;margin-bottom:1em}
td,th{border:1px solid #ccc;padding:4px 8px;text-align:left}
.anom{color:#b00}</style></head>
<body><h2>scheduler &amp; workloads</h2><p><a href="/">cluster</a></p>
<div id="out">loading…</div>
<script>
function esc(v){ const d = document.createElement('div');
  d.textContent = String(v ?? ''); return d.innerHTML; }
function table(title, rows, cols){
  if (!rows || !rows.length) return `<h3>${esc(title)}</h3><p>(none)</p>`;
  let h = `<h3>${esc(title)}</h3><table><tr>` +
    cols.map(c => `<th>${esc(c)}</th>`).join('') + '</tr>';
  for (const r of rows)
    h += '<tr>' + cols.map(c => `<td>${esc(
      typeof r[c] === 'object' ? JSON.stringify(r[c]) : r[c])}</td>`)
      .join('') + '</tr>';
  return h + '</table>';
}
async function refresh(){
  const [sched, wl, hp] = await Promise.all(
    ['/api/scheduler', '/api/workloads', '/api/hotpath'].map(
      u => fetch(u).then(r => r.json())));
  let h = table('scheduler (per-node two-level stats)',
    sched.stats.map(s => ({node: String(s.node_id).slice(0,12),
      head: s.is_head, alive: s.alive, idle: s.idle_workers,
      leased: s.leased_workers, local_grants: s.local_grants,
      spillbacks: s.spillbacks, staleness_s: s.staleness_s})),
    ['node','head','alive','idle','leased','local_grants','spillbacks',
     'staleness_s']);
  h += table('recent lease events', sched.recent_events.slice(-25).reverse()
    .map(e => ({ts: new Date(e.ts*1000).toISOString().slice(11,23),
      kind: e.kind, node: String(e.node_id ?? '').slice(0,12)})),
    ['ts','kind','node']);
  h += table('serve replicas (gossiped live load)',
    wl.serve.map(r => ({replica: r.key, ...r.stats,
      age_s: ((Date.now()/1000) - r.ts).toFixed(1)})),
    ['replica','deployment','queue_depth','inflight','ewma_latency_s',
     'total','age_s']);
  h += table('train workers (gossiped step telemetry)',
    wl.train.map(r => ({worker: r.key, ...r.stats,
      age_s: ((Date.now()/1000) - r.ts).toFixed(1)})),
    ['worker','run','rank','world_size','step','last_step_s',
     'ewma_step_s','steps_per_s','age_s']);
  h += table('compiled hot path (ring telemetry, stall-attributed)',
    hp.rings.map(r => ({ring: r.key, ...r.stats,
      age_s: ((Date.now()/1000) - r.ts).toFixed(1)})),
    ['ring','plane','lanes','depth','occupancy','writer_stall_s',
     'reader_stall_s','writes','reads','age_s']);
  h += table('compiled serve chains',
    hp.chains.map(r => ({chain: r.key, ...r.stats,
      age_s: ((Date.now()/1000) - r.ts).toFixed(1)})),
    ['chain','generation','compiled','dynamic_fallback','fenced',
     'entries','p99_s','age_s']);
  h += '<h3 class="anom">anomalies (watchdog)</h3>';
  h += table('', wl.anomalies.slice(-25).reverse().map(a => ({
      ts: new Date(a.ts*1000).toISOString().slice(11,23),
      anomaly: a.anomaly,
      detail: JSON.stringify(Object.fromEntries(Object.entries(a)
        .filter(([k]) => !['ts','kind','anomaly'].includes(k))))})),
    ['ts','anomaly','detail']);
  h += `<p>${wl.trace_spans_buffered} spans buffered for
    timeline(format="chrome")</p>`;
  document.getElementById('out').innerHTML = h;
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


def _core_metrics_snapshot(head) -> list:
    """Head-computed core gauges at scrape time (reference
    `src/ray/stats/metric_defs.cc`: tasks by state, object store usage,
    scheduler/actor/node counts — the dashboard's Grafana panels)."""
    def g(name, desc, value, tags=None):
        return {"name": name, "kind": "gauge", "description": desc,
                "series": [{"tags": tags or {}, "value": float(value)}]}

    out = [
        g("nodes_alive", "Alive nodes",
          sum(1 for n in head.nodes.values() if n.alive)),
        g("workers_total", "Registered worker processes",
          sum(1 for w in head.workers.values() if not w.is_driver)),
        g("drivers_total", "Connected drivers",
          sum(1 for w in head.workers.values() if w.is_driver)),
        g("tasks_queued", "Tasks waiting for dispatch", len(head.queue)),
        g("objects_total", "Objects in the cluster directory",
          len(head.objects)),
        g("objects_bytes", "Directory object bytes",
          sum(m.size for m in head.objects.values())),
        g("objects_evicted_total", "Objects evicted since head start",
          getattr(head, "objects_evicted", 0)),
        g("placement_groups", "Placement groups", len(head.pgs)),
    ]
    by_state: dict = {}
    for a in head.actors.values():
        by_state[a.state] = by_state.get(a.state, 0) + 1
    for state, n in sorted(by_state.items()):
        out.append(g("actors", "Actors by state", n, {"state": state}))
    total: dict = {}
    avail: dict = {}
    for node in head.nodes.values():
        if not node.alive:
            continue
        for r, v in node.resources.items():
            total[r] = total.get(r, 0) + v
        for r, v in node.available.items():
            avail[r] = avail.get(r, 0) + v
    for r in sorted(total):
        out.append(g("resource_total", "Cluster resource capacity",
                     total[r], {"resource": r}))
        out.append(g("resource_available", "Cluster resource available",
                     avail.get(r, 0), {"resource": r}))
    out.extend(_scheduler_metrics_snapshot(head))
    return out


def _scheduler_metrics_snapshot(head) -> list:
    """Two-level-scheduler flight-recorder series, computed at scrape time
    from the head's merged per-node telemetry (gossiped counters + delta
    arrival bookkeeping) — the observability the decentralized warm path
    took away from the head-centric stack."""
    import time as _time

    def series(name, kind, desc, rows):
        return {"name": name, "kind": kind, "description": desc,
                "series": [{"tags": t, "value": float(v)} for t, v in rows]}

    now = _time.time()
    local_grants, spillbacks, staleness, lag, pool_idle = [], [], [], [], []
    pool_leased, peer_spillbacks, peer_grants = [], [], []
    dir_staleness, node_pulls, node_pull_bytes, node_replicas = [], [], [], []
    store_frac = []
    for n in head.nodes.values():
        if n.is_head or not n.alive:
            continue
        tags = {"node_id": n.node_id.hex()[:12]}
        stats = n.sched_stats or {}
        local_grants.append((tags, stats.get("local_grants", 0)))
        spillbacks.append((tags, stats.get("spillbacks", 0)))
        peer_spillbacks.append((tags, stats.get("peer_spillbacks", 0)))
        peer_grants.append((tags, stats.get("peer_grants", 0)))
        staleness.append((tags, max(now - n.last_delta_ts, 0.0)))
        view_age = (n.gossip_health or {}).get("view_age_s", -1)
        if view_age is not None and view_age >= 0:
            lag.append((tags, view_age))
        dir_age = (n.gossip_health or {}).get("dir_age_s", -1)
        if dir_age is not None and dir_age >= 0:
            dir_staleness.append((tags, dir_age))
        node_pulls.append((tags, stats.get("object_pulls", 0)))
        node_pull_bytes.append((tags, stats.get("object_pull_bytes", 0)))
        node_replicas.append((tags, stats.get("replica_count", 0)))
        if stats.get("store_cap"):
            store_frac.append(
                (tags, stats.get("store_used", 0) / stats["store_cap"]))
        pool_idle.append((tags, n.pool_idle))
        pool_leased.append((tags, getattr(n, "pool_leased", 0)))
    head_tags = {"node_id": "head"}
    out = [
        series("cluster_epoch", "gauge",
               "Cluster epoch stamped into cluster_view and every "
               "grant/carve-out (bumps across head restarts; stale-epoch "
               "ops are rejected and reconciled)",
               [(head_tags, getattr(head, "cluster_epoch", 0))]),
        series("scheduler_stale_epoch_rejects_total", "counter",
               "Operations rejected for carrying a dead cluster epoch "
               "and routed into pool reconciliation",
               [(head_tags,
                 head.sched_totals.get("stale_epoch_rejects", 0))]),
        series("scheduler_pool_reconciles_total", "counter",
               "Pool-reconciliation handshakes completed (daemon "
               "inventory rebuilt the head ledger)",
               [(head_tags, head.sched_totals.get("reconciles", 0))]),
        series("lease_local_grants_total", "counter",
               "Leases granted daemon-locally (warm path, no head RPC)",
               local_grants or [(head_tags, 0)]),
        series("lease_spillbacks_total", "counter",
               "Lease requests a node daemon refused back to the head",
               spillbacks or [(head_tags, 0)]),
        series("lease_peer_spillbacks_total", "counter",
               "Cold lease requests a node daemon referred to a peer "
               "daemon's warm pool instead of the head (daemon-to-daemon "
               "spillback)", peer_spillbacks or [(head_tags, 0)]),
        series("lease_peer_grants_total", "counter",
               "Peer-referred leases each daemon granted from its warm "
               "pool (epoch-fenced, zero head RPCs)",
               peer_grants or [(head_tags, 0)]),
        series("lease_head_grants_total", "counter",
               "Leases granted by the head (cold path or spillback)",
               [(head_tags, head.sched_totals.get("head_grants", 0))]),
        series("objects_reconstructed_total", "counter",
               "Lost objects re-sealed by re-running their producing "
               "task from the lineage ledger",
               [(head_tags, head.sched_totals.get("reconstructs", 0))]),
        series("data_blocks_reconstructed_total", "counter",
               "Data-pipeline blocks (stage outputs / shuffle "
               "sub-blocks) rebuilt through lineage reconstruction "
               "after node loss",
               [(head_tags, head.sched_totals.get("data_reconstructs", 0))]),
        series("cluster_view_staleness_s", "gauge",
               "Age of the newest resource-view delta the head has from "
               "each node daemon", staleness or [(head_tags, 0.0)]),
        series("scheduler_pool_idle_workers", "gauge",
               "Warm lease-pool size gossiped by each node daemon",
               pool_idle or [(head_tags, 0)]),
        series("scheduler_pool_leased_workers", "gauge",
               "Live daemon-local leases gossiped by each node daemon",
               pool_leased or [(head_tags, 0)]),
    ]
    if lag:
        out.append(series(
            "gossip_lag_s", "gauge",
            "Each daemon's reported age of its cached head-broadcast "
            "cluster view", lag))
    # ---- object data plane (gossiped directory + node pull managers)
    out.append(series(
        "object_directory_entries", "gauge",
        "Objects the gossiped directory can resolve to a serving node",
        [(head_tags, len(getattr(head, "object_dir", ())))]))
    if dir_staleness:
        out.append(series(
            "object_directory_staleness_s", "gauge",
            "Each daemon's reported age of its cached gossiped object "
            "directory (how stale peer-to-peer location knowledge is)",
            dir_staleness))
    if node_pulls:
        out.append(series(
            "node_object_pulls_total", "counter",
            "Cross-node object pulls completed by each node daemon's "
            "pull manager (local workers share one network crossing)",
            node_pulls))
        out.append(series(
            "node_object_pull_bytes_total", "counter",
            "Bytes pulled by each node daemon's pull manager",
            node_pull_bytes))
        out.append(series(
            "node_object_replicas", "gauge",
            "Pulled replicas each node daemon caches and advertises as "
            "extra pull sources", node_replicas))
    if store_frac:
        out.append(series(
            "node_object_store_pressure", "gauge",
            "Each node daemon's object-store used/capacity fraction "
            "(the data plane's gossiped backpressure signal)",
            store_frac))
    return out


def _json(data) -> web.Response:
    return web.Response(text=json.dumps(data, default=str),
                        content_type="application/json")


def build_app(head) -> web.Application:
    app = web.Application()

    async def index(_req):
        return web.Response(text=_INDEX_HTML, content_type="text/html")

    async def cluster(_req):
        info = await head._handlers({})["cluster_info"]()
        info.pop("node_id", None)  # bytes; not JSON-friendly
        return _json(info)

    def state_route(kind):
        async def handler(req):
            limit = req.query.get("limit")
            rows = head._list_state(kind)
            return _json(rows[:int(limit)] if limit else rows)

        return handler

    async def summary(_req):
        from ray_tpu.util.state.api import (summarize_actor_rows,
                                            summarize_object_rows,
                                            summarize_task_rows)

        return _json({
            "tasks": summarize_task_rows(head._list_state("task_events")),
            "actors": summarize_actor_rows(head._list_state("actors")),
            "objects": summarize_object_rows(head._list_state("objects")),
        })

    async def metrics(_req):
        from ray_tpu.util.metrics import render_prometheus, snapshot_all

        snapshots = {key.decode(): payload
                     for key, payload in head._parsed_snapshots()}
        # the head's own registry (its flight-recorder RPC series) is
        # read in-process — the dashboard runs on the head's loop
        snapshots["head"] = _core_metrics_snapshot(head) + snapshot_all()
        return web.Response(text=render_prometheus(snapshots),
                            content_type="text/plain")

    async def scheduler(_req):
        """Two-level-scheduler flight recorder: per-node stats + the
        merged recent lease-lifecycle event stream."""
        return _json({"stats": head._list_state("scheduler_stats"),
                      "recent_events": list(head.lease_events)[-200:]})

    async def workloads(_req):
        """Workload flight recorder: live serve/train load merged from
        the gossiped/pushed telemetry + recent watchdog anomalies."""
        rows = head._workload_rows()
        kind = lambda r: str(r.get("kind", ""))  # noqa: E731
        return _json({
            "serve": [r for r in rows if kind(r).startswith("serve")],
            "train": [r for r in rows if kind(r) == "train_worker"],
            "other": [r for r in rows
                      if not kind(r).startswith(("serve", "train"))],
            "anomalies": [e for e in head.lease_events
                          if e.get("kind") == "workload_anomaly"][-100:],
            "trace_spans_buffered": len(head.trace_spans)})

    async def hotpath(_req):
        """Hot-path observatory: the compiled zero-RPC planes' golden
        signals — per-chain/pipeline ring telemetry (occupancy plus
        writer/reader stall attribution), compiled-chain health
        (generation, fallback/fence counts, gossiped p99), timed
        fused-step phase rows — with the watchdog's recent
        `hotpath_regression` flags and the chains' fence/failover
        flight-recorder events. One poll serves the `ray-tpu top` CLI
        and the dashboard panel."""
        rows = head._workload_rows()
        by = lambda k: [r for r in rows if r.get("kind") == k]  # noqa: E731
        return _json({
            "rings": by("hotpath"),
            "chains": by("serve_chain"),
            # the proxies' ingress chains (serve.run(compiled=True)):
            # same row shape as "chains", separate plane so stall
            # attribution covers the external-client edge on its own
            "proxy_chains": by("serve_proxy"),
            "train_phases": by("train_phase"),
            "anomalies": [e for e in head.lease_events
                          if e.get("kind") == "workload_anomaly"
                          and e.get("anomaly") == "hotpath_regression"
                          ][-50:],
            "fence_events": [e for e in head.lease_events
                             if e.get("kind") in ("chain_fence",
                                                  "chain_failover")][-50:]})

    async def workloads_page(_req):
        return web.Response(text=_WORKLOADS_HTML, content_type="text/html")

    app.router.add_get("/", index)
    app.router.add_get("/workloads", workloads_page)
    app.router.add_get("/api/cluster", cluster)
    app.router.add_get("/api/scheduler", scheduler)
    app.router.add_get("/api/workloads", workloads)
    app.router.add_get("/api/hotpath", hotpath)
    for kind in ("nodes", "actors", "workers", "tasks", "task_events",
                 "lease_events", "scheduler_stats", "trace_spans",
                 "workload_stats", "serve_stats",
                 "objects", "placement_groups"):
        app.router.add_get(f"/api/{kind}", state_route(kind))
    # ------------------------------------------------------ job REST API
    # (reference: dashboard/modules/job REST surface)
    async def jobs_post(req):
        body = await req.json()
        job_id = await head.job_manager.submit(
            body["entrypoint"], metadata=body.get("metadata"),
            env=(body.get("runtime_env") or {}).get("env_vars"),
            working_dir=(body.get("runtime_env") or {}).get("working_dir"),
            job_id=body.get("submission_id"))
        return _json({"job_id": job_id, "submission_id": job_id})

    async def jobs_list(_req):
        return _json(head.job_manager.list())

    async def job_get(req):
        info = head.job_manager.get(req.match_info["job_id"])
        if info is None:
            raise web.HTTPNotFound()
        return _json(info)

    async def job_logs(req):
        return web.Response(text=head.job_manager.logs(
            req.match_info["job_id"]), content_type="text/plain")

    async def job_stop(req):
        return _json({"stopped": head.job_manager.stop(
            req.match_info["job_id"])})

    app.router.add_post("/api/jobs/", jobs_post)
    app.router.add_get("/api/jobs/", jobs_list)
    app.router.add_get("/api/jobs/{job_id}", job_get)
    app.router.add_get("/api/jobs/{job_id}/logs", job_logs)
    app.router.add_post("/api/jobs/{job_id}/stop", job_stop)
    # ------------------------------------------- worker log surface
    # (reference: dashboard/modules/log REST endpoints)
    async def logs_list(_req):
        handlers = head._handlers({})
        return _json(await handlers["list_logs"]())

    async def log_get(req):
        handlers = head._handlers({})
        tail = req.query.get("tail")
        lines = await handlers["get_log"](
            filename=req.match_info["filename"],
            tail=int(tail) if tail else None)
        if lines is None:
            raise web.HTTPNotFound()
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")

    async def config_dump(_req):
        from ray_tpu.core import config as cfg

        return _json(cfg.dump())

    async def reporter(_req):
        handlers = head._handlers({})
        return _json(await handlers["reporter_stats"]())

    async def reporter_stacks(req):
        handlers = head._handlers({})
        try:
            wid = bytes.fromhex(req.match_info["worker_id"])
        except ValueError:
            raise web.HTTPNotFound()
        if len(wid) != 16:
            raise web.HTTPNotFound()
        text = await handlers["worker_stacks"](worker_id=wid)
        if text is None:
            raise web.HTTPNotFound()
        return web.Response(text=text, content_type="text/plain")

    app.router.add_get("/api/reporter", reporter)
    app.router.add_get("/api/reporter/stacks/{worker_id}", reporter_stacks)
    app.router.add_get("/api/config", config_dump)
    app.router.add_get("/api/logs", logs_list)
    app.router.add_get("/api/logs/{filename}", log_get)
    app.router.add_get("/api/summary", summary)
    app.router.add_get("/metrics", metrics)
    return app


async def start_dashboard(head, port: int = 0) -> int:
    """Start the dashboard on the running event loop; returns the bound port."""
    app = build_app(head)
    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", port)
    await site.start()
    bound = site._server.sockets[0].getsockname()[1]
    head.dashboard_port = bound
    head._dashboard_runner = runner
    return bound
