"""Dashboard: HTTP API + Prometheus metrics endpoint on the head node.

Parity (core subset) with `python/ray/dashboard/head.py` + its module
backends (node/state/metrics): REST endpoints over the head's live tables
and a `/metrics` Prometheus scrape target aggregating every process's
pushed snapshots (`ray_tpu.util.metrics`).
"""

from ray_tpu.dashboard.head_http import start_dashboard

__all__ = ["start_dashboard"]
