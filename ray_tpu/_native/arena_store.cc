// Node-local shared-memory object arena — the native core of the object
// store (capability-equivalent of the reference's plasma store:
// src/ray/object_manager/plasma/{store.h,plasma_allocator.*,eviction_policy.*},
// re-designed rather than ported: one mmap'd arena per node with an embedded
// boundary-tag allocator + open-addressing object table + LRU clock, fronted
// by ctypes instead of a socket protocol — every process on the node maps the
// same segment, so create/seal/get are pointer arithmetic, not IPC).
//
// Concurrency: one process-shared robust pthread mutex guards the header,
// table and allocator. Readers pin objects (refcount) so eviction never frees
// memory under a live zero-copy view.
//
// Layout of the shm segment:
//   [Header][Entry table][data region (boundary-tag heap)]
// All offsets are from the start of the segment.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055'41524e41ull;  // "RTPUARNA"
constexpr int kIdLen = 16;
constexpr uint32_t kStateFree = 0;
constexpr uint32_t kStateCreated = 1;
constexpr uint32_t kStateSealed = 2;
constexpr uint32_t kStateTombstone = 3;

struct Entry {
  uint8_t id[kIdLen];
  uint64_t offset;  // data offset of payload (arena-relative)
  uint64_t size;    // payload bytes
  uint32_t state;
  int32_t refcount;
  uint64_t lru;     // last-touch tick
};

// Free/used block header embedded in the data region (boundary tags).
struct Block {
  uint64_t size;       // total block bytes incl. header
  uint64_t prev_size;  // size of the physically preceding block (0 = first)
  uint32_t free;
  uint32_t _pad;
  // free blocks additionally store list links in the payload area:
  // uint64_t next_free, prev_free (arena-relative offsets; 0 = none)
};

struct Header {
  uint64_t magic;
  uint64_t total_size;    // whole segment bytes
  uint64_t table_off;
  uint64_t table_slots;
  uint64_t data_off;
  uint64_t data_size;
  uint64_t used;          // payload bytes currently allocated
  uint64_t lru_clock;
  uint64_t free_head;     // offset of first free block (0 = none)
  uint64_t num_objects;
  pthread_mutex_t mutex;
};

struct Handle {
  void* base;
  uint64_t total;
  int fd;
  bool owner;
  char name[128];
};

inline Header* hdr(Handle* h) { return reinterpret_cast<Header*>(h->base); }
inline uint8_t* at(Handle* h, uint64_t off) {
  return reinterpret_cast<uint8_t*>(h->base) + off;
}
inline Block* block_at(Handle* h, uint64_t off) {
  return reinterpret_cast<Block*>(at(h, off));
}
inline uint64_t* free_links(Handle* h, uint64_t off) {
  return reinterpret_cast<uint64_t*>(at(h, off + sizeof(Block)));
}
inline Entry* table(Handle* h) {
  return reinterpret_cast<Entry*>(at(h, hdr(h)->table_off));
}

constexpr uint64_t kAlign = 64;
inline uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }
constexpr uint64_t kMinBlock = sizeof(Block) + 2 * sizeof(uint64_t);

uint64_t hash_id(const uint8_t* id) {
  uint64_t h;
  memcpy(&h, id, 8);
  h ^= h >> 33;
  h *= 0xff51afd7ed558ccdull;
  h ^= h >> 33;
  return h;
}

int lock(Handle* h) {
  int rc = pthread_mutex_lock(&hdr(h)->mutex);
  if (rc == EOWNERDEAD) {
    // a process died holding the lock; state is still consistent enough for
    // our operations (all mutations are small and idempotent-ish)
    pthread_mutex_consistent(&hdr(h)->mutex);
    return 0;
  }
  return rc;
}
void unlock(Handle* h) { pthread_mutex_unlock(&hdr(h)->mutex); }

// ----------------------------------------------------------------- allocator

void freelist_remove(Handle* h, uint64_t off) {
  uint64_t* links = free_links(h, off);
  uint64_t next = links[0], prev = links[1];
  if (prev) free_links(h, prev)[0] = next;
  else hdr(h)->free_head = next;
  if (next) free_links(h, next)[1] = prev;
}

void freelist_push(Handle* h, uint64_t off) {
  uint64_t* links = free_links(h, off);
  links[0] = hdr(h)->free_head;
  links[1] = 0;
  if (hdr(h)->free_head) free_links(h, hdr(h)->free_head)[1] = off;
  hdr(h)->free_head = off;
}

// allocate a block with >= payload bytes of usable space; returns block
// offset or 0 on failure. Lock held.
uint64_t block_alloc(Handle* h, uint64_t payload) {
  uint64_t need = align_up(sizeof(Block) + payload);
  if (need < kMinBlock) need = kMinBlock;
  uint64_t off = hdr(h)->free_head;
  while (off) {
    Block* b = block_at(h, off);
    if (b->size >= need) {
      freelist_remove(h, off);
      if (b->size - need >= kMinBlock) {
        // split: tail becomes a new free block
        uint64_t tail_off = off + need;
        Block* tail = block_at(h, tail_off);
        tail->size = b->size - need;
        tail->prev_size = need;
        tail->free = 1;
        // fix prev_size of the block after the tail
        uint64_t after = off + b->size;
        if (after < hdr(h)->data_off + hdr(h)->data_size)
          block_at(h, after)->prev_size = tail->size;
        b->size = need;
        freelist_push(h, tail_off);
      }
      b->free = 0;
      return off;
    }
    off = free_links(h, off)[0];
  }
  return 0;
}

void block_free(Handle* h, uint64_t off) {
  Block* b = block_at(h, off);
  uint64_t data_end = hdr(h)->data_off + hdr(h)->data_size;
  // coalesce with next
  uint64_t next_off = off + b->size;
  if (next_off < data_end) {
    Block* nb = block_at(h, next_off);
    if (nb->free) {
      freelist_remove(h, next_off);
      b->size += nb->size;
    }
  }
  // coalesce with prev
  if (b->prev_size) {
    uint64_t prev_off = off - b->prev_size;
    Block* pb = block_at(h, prev_off);
    if (pb->free) {
      freelist_remove(h, prev_off);
      pb->size += b->size;
      off = prev_off;
      b = pb;
    }
  }
  b->free = 1;
  uint64_t after = off + b->size;
  if (after < data_end) block_at(h, after)->prev_size = b->size;
  freelist_push(h, off);
}

// ----------------------------------------------------------------- table

Entry* find_entry(Handle* h, const uint8_t* id) {
  Header* H = hdr(h);
  Entry* t = table(h);
  uint64_t slot = hash_id(id) % H->table_slots;
  for (uint64_t i = 0; i < H->table_slots; i++) {
    Entry* e = &t[(slot + i) % H->table_slots];
    if (e->state == kStateFree) return nullptr;
    if (e->state != kStateTombstone && memcmp(e->id, id, kIdLen) == 0) return e;
  }
  return nullptr;
}

Entry* insert_entry(Handle* h, const uint8_t* id) {
  Header* H = hdr(h);
  Entry* t = table(h);
  uint64_t slot = hash_id(id) % H->table_slots;
  for (uint64_t i = 0; i < H->table_slots; i++) {
    Entry* e = &t[(slot + i) % H->table_slots];
    if (e->state == kStateFree || e->state == kStateTombstone) {
      memcpy(e->id, id, kIdLen);
      return e;
    }
    if (memcmp(e->id, id, kIdLen) == 0) return nullptr;  // exists
  }
  return nullptr;  // table full
}

}  // namespace

extern "C" {

// returns handle pointer or 0. capacity = data region bytes.
void* rtpu_store_create(const char* name, uint64_t capacity) {
  uint64_t slots = capacity / (64 * 1024);
  if (slots < 4096) slots = 4096;
  uint64_t table_bytes = slots * sizeof(Entry);
  uint64_t data_off = align_up(sizeof(Header) + table_bytes);
  uint64_t total = data_off + align_up(capacity);

  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0666);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  Handle* h = new Handle{base, total, fd, true, {0}};
  strncpy(h->name, name, sizeof(h->name) - 1);

  Header* H = hdr(h);
  memset(H, 0, sizeof(Header));
  H->total_size = total;
  H->table_off = sizeof(Header);
  H->table_slots = slots;
  H->data_off = data_off;
  H->data_size = align_up(capacity);
  H->used = 0;
  H->lru_clock = 1;
  memset(at(h, H->table_off), 0, table_bytes);

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&H->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  // one big free block spanning the data region
  Block* b = block_at(h, H->data_off);
  b->size = H->data_size;
  b->prev_size = 0;
  b->free = 1;
  free_links(h, H->data_off)[0] = 0;
  free_links(h, H->data_off)[1] = 0;
  H->free_head = H->data_off;

  __sync_synchronize();
  H->magic = kMagic;
  return h;
}

void* rtpu_store_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0666);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Header* H = reinterpret_cast<Header*>(base);
  if (H->magic != kMagic || H->total_size != (uint64_t)st.st_size) {
    munmap(base, (size_t)st.st_size);
    close(fd);
    return nullptr;
  }
  Handle* h = new Handle{base, (uint64_t)st.st_size, fd, false, {0}};
  strncpy(h->name, name, sizeof(h->name) - 1);
  return h;
}

void rtpu_store_close(void* hp, int unlink_segment) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  if (!h) return;
  munmap(h->base, h->total);
  close(h->fd);
  if (unlink_segment) shm_unlink(h->name);
  delete h;
}

// 0 ok (offset_out = payload offset from segment start), -1 no space,
// -2 already exists, -3 table full
int rtpu_store_alloc(void* hp, const uint8_t* id, uint64_t size,
                     uint64_t* offset_out) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  if (lock(h) != 0) return -4;
  if (find_entry(h, id)) {
    unlock(h);
    return -2;
  }
  uint64_t boff = block_alloc(h, size);
  if (!boff) {
    unlock(h);
    return -1;
  }
  Entry* e = insert_entry(h, id);
  if (!e) {
    block_free(h, boff);
    unlock(h);
    return -3;
  }
  e->offset = boff + sizeof(Block);
  e->size = size;
  e->state = kStateCreated;
  e->refcount = 0;
  e->lru = hdr(h)->lru_clock++;
  hdr(h)->used += size;
  hdr(h)->num_objects++;
  *offset_out = e->offset;
  unlock(h);
  return 0;
}

int rtpu_store_seal(void* hp, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  if (lock(h) != 0) return -4;
  Entry* e = find_entry(h, id);
  if (!e) {
    unlock(h);
    return -1;
  }
  e->state = kStateSealed;
  unlock(h);
  return 0;
}

// 0 ok; -1 missing; -3 not sealed. pin!=0 increments refcount.
int rtpu_store_get(void* hp, const uint8_t* id, uint64_t* off_out,
                   uint64_t* size_out, int pin) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  if (lock(h) != 0) return -4;
  Entry* e = find_entry(h, id);
  if (!e) {
    unlock(h);
    return -1;
  }
  if (e->state != kStateSealed) {
    unlock(h);
    return -3;
  }
  e->lru = hdr(h)->lru_clock++;
  if (pin) e->refcount++;
  *off_out = e->offset;
  *size_out = e->size;
  unlock(h);
  return 0;
}

int rtpu_store_release(void* hp, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  if (lock(h) != 0) return -4;
  Entry* e = find_entry(h, id);
  if (e && e->refcount > 0) e->refcount--;
  unlock(h);
  return e ? 0 : -1;
}

// force=1 deletes even when pinned (owner shutdown / dead-reader cleanup)
int rtpu_store_delete(void* hp, const uint8_t* id, int force) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  if (lock(h) != 0) return -4;
  Entry* e = find_entry(h, id);
  if (!e) {
    unlock(h);
    return -1;
  }
  if (e->refcount > 0 && !force) {
    unlock(h);
    return -5;
  }
  block_free(h, e->offset - sizeof(Block));
  hdr(h)->used -= e->size;
  hdr(h)->num_objects--;
  e->state = kStateTombstone;
  unlock(h);
  return 0;
}

// Collect LRU sealed refcount-0 objects until their sizes sum to >= needed.
// out_ids must hold max_out * kIdLen bytes. Returns count (may free fewer
// bytes than needed if not enough candidates).
int rtpu_store_evict_candidates(void* hp, uint64_t needed, uint8_t* out_ids,
                                int max_out) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  if (lock(h) != 0) return -4;
  Header* H = hdr(h);
  Entry* t = table(h);
  int n = 0;
  uint64_t freed = 0;
  while (freed < needed && n < max_out) {
    Entry* best = nullptr;
    for (uint64_t i = 0; i < H->table_slots; i++) {
      Entry* e = &t[i];
      if (e->state != kStateSealed || e->refcount != 0) continue;
      bool taken = false;
      for (int j = 0; j < n; j++) {
        if (memcmp(out_ids + j * kIdLen, e->id, kIdLen) == 0) {
          taken = true;
          break;
        }
      }
      if (taken) continue;
      if (!best || e->lru < best->lru) best = e;
    }
    if (!best) break;
    memcpy(out_ids + n * kIdLen, best->id, kIdLen);
    freed += best->size;
    n++;
  }
  unlock(h);
  return n;
}

void rtpu_store_stats(void* hp, uint64_t* used, uint64_t* capacity,
                      uint64_t* count) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  Header* H = hdr(h);
  if (used) *used = H->used;
  if (capacity) *capacity = H->data_size;
  if (count) *count = H->num_objects;
}

uint64_t rtpu_store_data_offset(void* hp) {
  return hdr(reinterpret_cast<Handle*>(hp))->data_off;
}

}  // extern "C"
