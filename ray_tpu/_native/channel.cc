// Mutable shared-memory channels for compiled graphs — the native
// counterpart of the reference's mutable plasma objects + semaphores
// (src/ray/core_worker/experimental_mutable_object_manager.{h,cc},
// python/ray/experimental/channel/shared_memory_channel.py): values in
// shm, one writer, N readers, blocking handoff via a process-shared
// mutex + condvar. Steady-state hop latency is a condvar wake, not an RPC.
//
// The slot store is an N-SLOT RING (num_slots >= 1): the writer appends
// value seq W into slot (W-1) % num_slots and blocks only when the slot
// it is about to overwrite still has unacked readers — i.e. when the ring
// is full across ALL reader cursors. Readers consume strictly in sequence
// (each reader sees every value exactly once); per-reader cursors live
// with the reader (local handles keep last_seq; remote readers carry it
// through the dag_chan_read RPC). num_slots = 1 degenerates to the
// original single-slot handoff. The ring is what lets CompiledDAG keep
// max_inflight iterations pipelined instead of serializing every stage
// on the slowest consumer.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// v2: telemetry counters appended to the header shift the slot array, so
// a v1 segment must fail attach (not be misread) — hence a new magic.
constexpr uint64_t kChanMagic = 0x52545055'4348414full;  // "RTPUCHAO"

struct ChanHeader {
  uint64_t magic;
  uint64_t capacity;      // per-slot payload capacity
  uint64_t total_size;
  pthread_mutex_t mutex;
  pthread_cond_t cond;
  uint64_t seq;           // seq of the NEWEST value written (0 = none yet)
  uint32_t num_readers;
  uint32_t closed;
  uint32_t num_slots;
  uint32_t _pad;
  // -- telemetry (mutated under the mutex; snapshot reads are lock-free) --
  uint64_t writer_stall_ns;  // writers blocked: ring full across cursors
  uint64_t reader_stall_ns;  // readers blocked: next value not written yet
  uint64_t writes;           // completed writes
  uint64_t reads;            // completed reads (summed over all readers)
};

// per-slot metadata, laid out as an array right after the header
struct SlotMeta {
  uint64_t seq;           // value id held by this slot (0 = never written)
  uint64_t len;           // payload length of that value
  uint64_t acks;          // readers that consumed that value
};

struct ChanHandle {
  void* base;
  uint64_t total;
  int fd;
  char name[128];
};

inline ChanHeader* chdr(ChanHandle* h) {
  return reinterpret_cast<ChanHeader*>(h->base);
}
inline SlotMeta* slots(ChanHandle* h) {
  return reinterpret_cast<SlotMeta*>(
      reinterpret_cast<uint8_t*>(h->base) + sizeof(ChanHeader));
}
inline uint8_t* payload(ChanHandle* h, uint32_t slot) {
  ChanHeader* H = chdr(h);
  return reinterpret_cast<uint8_t*>(h->base) + sizeof(ChanHeader) +
         sizeof(SlotMeta) * H->num_slots + (uint64_t)slot * H->capacity;
}
inline uint32_t slot_of(ChanHeader* H, uint64_t seq) {
  return (uint32_t)((seq - 1) % H->num_slots);
}

int chan_lock(ChanHandle* h) {
  int rc = pthread_mutex_lock(&chdr(h)->mutex);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&chdr(h)->mutex);
    return 0;
  }
  return rc;
}

// wait on the condvar with optional timeout (ms; <0 = forever).
// returns 0 or ETIMEDOUT.
int chan_wait(ChanHandle* h, int64_t timeout_ms) {
  if (timeout_ms < 0) {
    return pthread_cond_wait(&chdr(h)->cond, &chdr(h)->mutex);
  }
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += timeout_ms / 1000;
  ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) {
    ts.tv_sec += 1;
    ts.tv_nsec -= 1000000000L;
  }
  return pthread_cond_timedwait(&chdr(h)->cond, &chdr(h)->mutex, &ts);
}

inline uint64_t mono_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
}

}  // namespace

extern "C" {

void* rtpu_chan_create(const char* name, uint64_t capacity,
                       uint32_t num_readers, uint32_t num_slots) {
  if (num_slots == 0) num_slots = 1;
  uint64_t total = sizeof(ChanHeader) + sizeof(SlotMeta) * num_slots +
                   capacity * num_slots;
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0666);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  ChanHandle* h = new ChanHandle{base, total, fd, {0}};
  strncpy(h->name, name, sizeof(h->name) - 1);
  ChanHeader* H = chdr(h);
  memset(H, 0, sizeof(ChanHeader) + sizeof(SlotMeta) * num_slots);
  H->capacity = capacity;
  H->total_size = total;
  H->num_readers = num_readers ? num_readers : 1;
  H->num_slots = num_slots;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&H->mutex, &ma);
  pthread_mutexattr_destroy(&ma);

  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&H->cond, &ca);
  pthread_condattr_destroy(&ca);

  __sync_synchronize();
  H->magic = kChanMagic;
  return h;
}

void* rtpu_chan_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0666);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  ChanHandle* h = new ChanHandle{base, (uint64_t)st.st_size, fd, {0}};
  strncpy(h->name, name, sizeof(h->name) - 1);
  if (chdr(h)->magic != kChanMagic) {
    munmap(base, (size_t)st.st_size);
    close(fd);
    delete h;
    return nullptr;
  }
  return h;
}

// Mark the channel closed and wake every blocked reader/writer WITHOUT
// unmapping — safe to call while other threads of this process are
// blocked inside read/write on the same handle (close() would unmap the
// segment under them). Used to fence a channel whose peer process died:
// the creator can no longer set the flag, so any attached handle does.
void rtpu_chan_shutdown(void* hp) {
  ChanHandle* h = reinterpret_cast<ChanHandle*>(hp);
  if (!h) return;
  if (chan_lock(h) == 0) {
    chdr(h)->closed = 1;
    pthread_cond_broadcast(&chdr(h)->cond);
    pthread_mutex_unlock(&chdr(h)->mutex);
  }
}

void rtpu_chan_close(void* hp, int unlink_segment) {
  ChanHandle* h = reinterpret_cast<ChanHandle*>(hp);
  if (!h) return;
  if (chan_lock(h) == 0) {
    chdr(h)->closed = 1;
    pthread_cond_broadcast(&chdr(h)->cond);
    pthread_mutex_unlock(&chdr(h)->mutex);
  }
  munmap(h->base, h->total);
  close(h->fd);
  if (unlink_segment) shm_unlink(h->name);
  delete h;
}

// Appends value seq+1 into its ring slot. Blocks while that slot still
// holds a value not yet acked by every reader (ring full across reader
// cursors). 0 ok; -2 closed; -3 timeout; -4 payload too large.
int rtpu_chan_write(void* hp, const uint8_t* data, uint64_t len,
                    int64_t timeout_ms) {
  ChanHandle* h = reinterpret_cast<ChanHandle*>(hp);
  ChanHeader* H = chdr(h);
  if (len > H->capacity) return -4;
  if (chan_lock(h) != 0) return -1;
  SlotMeta* S = slots(h);
  uint32_t slot;
  uint64_t stall0 = 0;   // set on first block: attributes ring-full stalls
  for (;;) {
    if (H->closed) {
      pthread_mutex_unlock(&H->mutex);
      return -2;
    }
    slot = slot_of(H, H->seq + 1);
    if (S[slot].seq == 0 || S[slot].acks >= H->num_readers) break;
    if (stall0 == 0) stall0 = mono_ns();
    if (chan_wait(h, timeout_ms) == ETIMEDOUT) {
      H->writer_stall_ns += mono_ns() - stall0;
      pthread_mutex_unlock(&H->mutex);
      return -3;
    }
  }
  if (stall0 != 0) H->writer_stall_ns += mono_ns() - stall0;
  memcpy(payload(h, slot), data, len);
  S[slot].len = len;
  S[slot].acks = 0;
  S[slot].seq = ++H->seq;
  H->writes++;
  pthread_cond_broadcast(&H->cond);
  pthread_mutex_unlock(&H->mutex);
  return 0;
}

// Reads the next value after last_seq (strictly in sequence; a reader
// that attached after values were already overwritten fast-forwards to
// the oldest value still in the ring). Blocks until it is written.
// 0 ok; -2 closed (and nothing newer); -3 timeout; -4 out buffer too
// small. On success *seq_out/*len_out describe the value. After close,
// values still in the ring DRAIN before -2 is reported — in-flight ring
// entries are never silently dropped.
int rtpu_chan_read(void* hp, uint64_t last_seq, uint8_t* out,
                   uint64_t out_cap, uint64_t* seq_out, uint64_t* len_out,
                   int64_t timeout_ms) {
  ChanHandle* h = reinterpret_cast<ChanHandle*>(hp);
  ChanHeader* H = chdr(h);
  if (chan_lock(h) != 0) return -1;
  SlotMeta* S = slots(h);
  uint64_t wanted;
  uint64_t stall0 = 0;   // set on first block: attributes starved-reader time
  for (;;) {
    // oldest value still resident: seq - num_slots + 1 (ring wrapped)
    wanted = last_seq + 1;
    if (H->seq >= H->num_slots && wanted < H->seq - H->num_slots + 1)
      wanted = H->seq - H->num_slots + 1;
    if (wanted <= H->seq) break;   // written and still in the ring
    if (H->closed) {               // closed with nothing newer
      if (stall0 != 0) H->reader_stall_ns += mono_ns() - stall0;
      pthread_mutex_unlock(&H->mutex);
      return -2;
    }
    if (stall0 == 0) stall0 = mono_ns();
    if (chan_wait(h, timeout_ms) == ETIMEDOUT) {
      H->reader_stall_ns += mono_ns() - stall0;
      pthread_mutex_unlock(&H->mutex);
      return -3;
    }
  }
  if (stall0 != 0) H->reader_stall_ns += mono_ns() - stall0;
  uint32_t slot = slot_of(H, wanted);
  if (S[slot].len > out_cap) {
    pthread_mutex_unlock(&H->mutex);
    return -4;
  }
  memcpy(out, payload(h, slot), S[slot].len);
  *seq_out = wanted;
  *len_out = S[slot].len;
  S[slot].acks++;
  H->reads++;
  if (S[slot].acks >= H->num_readers) pthread_cond_broadcast(&H->cond);
  pthread_mutex_unlock(&H->mutex);
  return 0;
}

// ---------------------------------------------------------------- zero-copy
// Split write: reserve hands the writer a pointer INTO the next ring slot
// so it can serialize in place (no staging buffer + memcpy pair); commit
// publishes it. Safe under the single-writer contract: between reserve and
// commit the slot is invisible to readers — it is only reservable once
// every reader acked its previous value (acks >= num_readers), so every
// reader cursor is already past it, and seq is not bumped until commit.
// Abandoning a reservation (serialize failed) needs no cleanup: the next
// reserve returns the same slot.
int rtpu_chan_reserve(void* hp, uint64_t len, int64_t timeout_ms,
                      uint8_t** ptr_out) {
  ChanHandle* h = reinterpret_cast<ChanHandle*>(hp);
  ChanHeader* H = chdr(h);
  if (len > H->capacity) return -4;
  if (chan_lock(h) != 0) return -1;
  SlotMeta* S = slots(h);
  uint32_t slot;
  uint64_t stall0 = 0;
  for (;;) {
    if (H->closed) {
      pthread_mutex_unlock(&H->mutex);
      return -2;
    }
    slot = slot_of(H, H->seq + 1);
    if (S[slot].seq == 0 || S[slot].acks >= H->num_readers) break;
    if (stall0 == 0) stall0 = mono_ns();
    if (chan_wait(h, timeout_ms) == ETIMEDOUT) {
      H->writer_stall_ns += mono_ns() - stall0;
      pthread_mutex_unlock(&H->mutex);
      return -3;
    }
  }
  if (stall0 != 0) H->writer_stall_ns += mono_ns() - stall0;
  *ptr_out = payload(h, slot);
  pthread_mutex_unlock(&H->mutex);
  return 0;
}

int rtpu_chan_commit(void* hp, uint64_t len) {
  ChanHandle* h = reinterpret_cast<ChanHandle*>(hp);
  ChanHeader* H = chdr(h);
  if (len > H->capacity) return -4;
  if (chan_lock(h) != 0) return -1;
  if (H->closed) {
    pthread_mutex_unlock(&H->mutex);
    return -2;
  }
  // single writer: the reserved slot is still slot_of(seq + 1)
  SlotMeta* S = slots(h);
  uint32_t slot = slot_of(H, H->seq + 1);
  S[slot].len = len;
  S[slot].acks = 0;
  S[slot].seq = ++H->seq;
  H->writes++;
  pthread_cond_broadcast(&H->cond);
  pthread_mutex_unlock(&H->mutex);
  return 0;
}

// Split read: same wait/fast-forward/drain-after-close discipline as
// rtpu_chan_read, but hands back a pointer into the slot WITHOUT copying
// and WITHOUT acking — the slot stays pinned (the writer cannot reclaim
// it) until rtpu_chan_ack(seq). The caller must ack exactly once per
// viewed value or the ring wedges when it wraps around to that slot.
int rtpu_chan_read_view(void* hp, uint64_t last_seq, uint64_t* seq_out,
                        uint64_t* len_out, uint8_t** ptr_out,
                        int64_t timeout_ms) {
  ChanHandle* h = reinterpret_cast<ChanHandle*>(hp);
  ChanHeader* H = chdr(h);
  if (chan_lock(h) != 0) return -1;
  SlotMeta* S = slots(h);
  uint64_t wanted;
  uint64_t stall0 = 0;
  for (;;) {
    wanted = last_seq + 1;
    if (H->seq >= H->num_slots && wanted < H->seq - H->num_slots + 1)
      wanted = H->seq - H->num_slots + 1;
    if (wanted <= H->seq) break;
    if (H->closed) {
      if (stall0 != 0) H->reader_stall_ns += mono_ns() - stall0;
      pthread_mutex_unlock(&H->mutex);
      return -2;
    }
    if (stall0 == 0) stall0 = mono_ns();
    if (chan_wait(h, timeout_ms) == ETIMEDOUT) {
      H->reader_stall_ns += mono_ns() - stall0;
      pthread_mutex_unlock(&H->mutex);
      return -3;
    }
  }
  if (stall0 != 0) H->reader_stall_ns += mono_ns() - stall0;
  uint32_t slot = slot_of(H, wanted);
  *seq_out = wanted;
  *len_out = S[slot].len;
  *ptr_out = payload(h, slot);
  pthread_mutex_unlock(&H->mutex);
  return 0;
}

// Release a viewed value: counts the reader's ack and wakes a writer
// blocked on that slot. 0 ok; -5 if the slot no longer holds `seq`
// (double-ack after the ring already wrapped — a caller bug).
int rtpu_chan_ack(void* hp, uint64_t seq) {
  ChanHandle* h = reinterpret_cast<ChanHandle*>(hp);
  ChanHeader* H = chdr(h);
  if (chan_lock(h) != 0) return -1;
  SlotMeta* S = slots(h);
  uint32_t slot = slot_of(H, seq);
  if (S[slot].seq != seq) {
    pthread_mutex_unlock(&H->mutex);
    return -5;
  }
  S[slot].acks++;
  H->reads++;
  if (S[slot].acks >= H->num_readers) pthread_cond_broadcast(&H->cond);
  pthread_mutex_unlock(&H->mutex);
  return 0;
}

uint64_t rtpu_chan_capacity(void* hp) {
  return chdr(reinterpret_cast<ChanHandle*>(hp))->capacity;
}

// header introspection: attach-side handles restore the true reader
// count and ring depth from shm instead of guessing (a re-serialized
// attached handle must keep capacity checks honest)
uint32_t rtpu_chan_num_readers(void* hp) {
  return chdr(reinterpret_cast<ChanHandle*>(hp))->num_readers;
}

uint32_t rtpu_chan_num_slots(void* hp) {
  return chdr(reinterpret_cast<ChanHandle*>(hp))->num_slots;
}

// Telemetry snapshot WITHOUT taking the channel mutex: a monitoring
// thread must never contend with (or be blocked behind) a stalled hot
// path. All fields are 64-bit counters mutated under the mutex; reading
// them unlocked can observe a value mid-update across fields (e.g. seq
// bumped before writes), which is fine for monitoring — each field is
// individually torn-free on 64-bit loads. Occupancy is derived by
// scanning the slot array: a slot holds a live value when it was ever
// written and not every reader has acked it yet.
// out[8]: seq, occupancy, num_slots, writer_stall_ns, reader_stall_ns,
//         writes, reads, closed
void rtpu_chan_stats(void* hp, uint64_t* out) {
  ChanHandle* h = reinterpret_cast<ChanHandle*>(hp);
  ChanHeader* H = chdr(h);
  SlotMeta* S = slots(h);
  uint64_t occ = 0;
  for (uint32_t i = 0; i < H->num_slots; ++i) {
    if (S[i].seq != 0 && S[i].acks < H->num_readers) ++occ;
  }
  out[0] = H->seq;
  out[1] = occ;
  out[2] = H->num_slots;
  out[3] = H->writer_stall_ns;
  out[4] = H->reader_stall_ns;
  out[5] = H->writes;
  out[6] = H->reads;
  out[7] = H->closed;
}

}  // extern "C"
