// Mutable shared-memory channels for compiled graphs — the native
// counterpart of the reference's mutable plasma objects + semaphores
// (src/ray/core_worker/experimental_mutable_object_manager.{h,cc},
// python/ray/experimental/channel/shared_memory_channel.py): a single-slot
// value in shm, one writer, N readers, blocking handoff via a process-shared
// mutex + condvar. Steady-state hop latency is a condvar wake, not an RPC.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kChanMagic = 0x52545055'4348414eull;  // "RTPUCHAN"

struct ChanHeader {
  uint64_t magic;
  uint64_t capacity;      // payload capacity
  uint64_t total_size;
  pthread_mutex_t mutex;
  pthread_cond_t cond;
  uint64_t seq;           // id of the value currently in the slot (0 = none)
  uint64_t acks;          // readers that consumed the current value
  uint32_t num_readers;
  uint32_t closed;
  uint64_t len;           // payload length of current value
};

struct ChanHandle {
  void* base;
  uint64_t total;
  int fd;
  char name[128];
};

inline ChanHeader* chdr(ChanHandle* h) {
  return reinterpret_cast<ChanHeader*>(h->base);
}
inline uint8_t* payload(ChanHandle* h) {
  return reinterpret_cast<uint8_t*>(h->base) + sizeof(ChanHeader);
}

int chan_lock(ChanHandle* h) {
  int rc = pthread_mutex_lock(&chdr(h)->mutex);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&chdr(h)->mutex);
    return 0;
  }
  return rc;
}

// wait on the condvar with optional timeout (ms; <0 = forever).
// returns 0 or ETIMEDOUT.
int chan_wait(ChanHandle* h, int64_t timeout_ms) {
  if (timeout_ms < 0) {
    return pthread_cond_wait(&chdr(h)->cond, &chdr(h)->mutex);
  }
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += timeout_ms / 1000;
  ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) {
    ts.tv_sec += 1;
    ts.tv_nsec -= 1000000000L;
  }
  return pthread_cond_timedwait(&chdr(h)->cond, &chdr(h)->mutex, &ts);
}

}  // namespace

extern "C" {

void* rtpu_chan_create(const char* name, uint64_t capacity,
                       uint32_t num_readers) {
  uint64_t total = sizeof(ChanHeader) + capacity;
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0666);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  ChanHandle* h = new ChanHandle{base, total, fd, {0}};
  strncpy(h->name, name, sizeof(h->name) - 1);
  ChanHeader* H = chdr(h);
  memset(H, 0, sizeof(ChanHeader));
  H->capacity = capacity;
  H->total_size = total;
  H->num_readers = num_readers ? num_readers : 1;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&H->mutex, &ma);
  pthread_mutexattr_destroy(&ma);

  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&H->cond, &ca);
  pthread_condattr_destroy(&ca);

  __sync_synchronize();
  H->magic = kChanMagic;
  return h;
}

void* rtpu_chan_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0666);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  ChanHandle* h = new ChanHandle{base, (uint64_t)st.st_size, fd, {0}};
  strncpy(h->name, name, sizeof(h->name) - 1);
  if (chdr(h)->magic != kChanMagic) {
    munmap(base, (size_t)st.st_size);
    close(fd);
    delete h;
    return nullptr;
  }
  return h;
}

void rtpu_chan_close(void* hp, int unlink_segment) {
  ChanHandle* h = reinterpret_cast<ChanHandle*>(hp);
  if (!h) return;
  if (chan_lock(h) == 0) {
    chdr(h)->closed = 1;
    pthread_cond_broadcast(&chdr(h)->cond);
    pthread_mutex_unlock(&chdr(h)->mutex);
  }
  munmap(h->base, h->total);
  close(h->fd);
  if (unlink_segment) shm_unlink(h->name);
  delete h;
}

// Blocks until the slot is free (all readers acked the previous value).
// 0 ok; -2 closed; -3 timeout; -4 payload too large.
int rtpu_chan_write(void* hp, const uint8_t* data, uint64_t len,
                    int64_t timeout_ms) {
  ChanHandle* h = reinterpret_cast<ChanHandle*>(hp);
  ChanHeader* H = chdr(h);
  if (len > H->capacity) return -4;
  if (chan_lock(h) != 0) return -1;
  while (!H->closed && H->seq != 0 && H->acks < H->num_readers) {
    if (chan_wait(h, timeout_ms) == ETIMEDOUT) {
      pthread_mutex_unlock(&H->mutex);
      return -3;
    }
  }
  if (H->closed) {
    pthread_mutex_unlock(&H->mutex);
    return -2;
  }
  memcpy(payload(h), data, len);
  H->len = len;
  H->seq++;
  H->acks = 0;
  pthread_cond_broadcast(&H->cond);
  pthread_mutex_unlock(&H->mutex);
  return 0;
}

// Blocks until a value newer than last_seq arrives; copies it into out.
// 0 ok; -2 closed (and nothing newer); -3 timeout; -4 out buffer too small.
// On success *seq_out/*len_out describe the value.
int rtpu_chan_read(void* hp, uint64_t last_seq, uint8_t* out,
                   uint64_t out_cap, uint64_t* seq_out, uint64_t* len_out,
                   int64_t timeout_ms) {
  ChanHandle* h = reinterpret_cast<ChanHandle*>(hp);
  ChanHeader* H = chdr(h);
  if (chan_lock(h) != 0) return -1;
  while (!H->closed && (H->seq == 0 || H->seq == last_seq)) {
    if (chan_wait(h, timeout_ms) == ETIMEDOUT) {
      pthread_mutex_unlock(&H->mutex);
      return -3;
    }
  }
  if (H->seq == 0 || H->seq == last_seq) {  // closed with nothing newer
    pthread_mutex_unlock(&H->mutex);
    return -2;
  }
  if (H->len > out_cap) {
    pthread_mutex_unlock(&H->mutex);
    return -4;
  }
  memcpy(out, payload(h), H->len);
  *seq_out = H->seq;
  *len_out = H->len;
  H->acks++;
  if (H->acks >= H->num_readers) pthread_cond_broadcast(&H->cond);
  pthread_mutex_unlock(&H->mutex);
  return 0;
}

uint64_t rtpu_chan_capacity(void* hp) {
  return chdr(reinterpret_cast<ChanHandle*>(hp))->capacity;
}

}  // extern "C"
