"""Trainers: DataParallelTrainer + JaxTrainer.

Parity with `python/ray/train/v2/api/data_parallel_trainer.py:59` (fit() spawns
a controller actor and waits) and `train/v2/jax/jax_trainer.py:19` +
`config.py:39 _JaxBackend` (per-worker jax.distributed env). The TPU-native
difference: on a single host the worker owns all local chips and the data
plane is one pjit program (ray_tpu.train.spmd); multi-host slices get
coordinator env vars for `jax.distributed.initialize`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.controller import TrainControllerActor, TrainControllerLogic


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    path: Optional[str]
    error: Optional[str]
    restarts: int = 0
    # elastic lifecycle counters: graceful grow-back restarts and
    # epoch-fence restarts (neither consumes the failure budget)
    resizes: int = 0
    fenced_restarts: int = 0
    final_world_size: Optional[int] = None

    @property
    def best_checkpoints(self) -> List[Checkpoint]:
        return [self.checkpoint] if self.checkpoint else []


class TrainingFailedError(RuntimeError):
    pass


class JaxBackend:
    """Assigns each worker the env for `jax.distributed.initialize`
    (reference train/v2/jax/config.py:24-36: coordinator_address,
    num_processes, process_id). Only engages for multi-worker groups; a
    single worker drives all its chips through one PJRT client."""

    def __init__(self, enable_distributed: Optional[bool] = None):
        self.enable_distributed = enable_distributed

    def worker_envs(self, group) -> List[Dict[str, str]]:
        n = len(group.workers)
        enabled = (self.enable_distributed if self.enable_distributed is not None
                   else n > 1)
        if not enabled:
            return [{} for _ in range(n)]
        # Coordinator = rank 0's reachable address with a port probed free
        # on rank 0's own host (a loopback/controller-probed pair would
        # make non-rank-0 hosts of a multi-host gang connect to themselves).
        host, port = ray_tpu.get(
            group.workers[0].rendezvous_info.remote(), timeout=120)
        coordinator = f"{host}:{port}"
        return [{
            "RAY_TPU_JAX_COORDINATOR": coordinator,
            "RAY_TPU_JAX_NUM_PROCESSES": str(n),
            "RAY_TPU_JAX_PROCESS_ID": str(rank),
        } for rank in range(n)]


def maybe_init_jax_distributed() -> None:
    """Call inside a train loop to join the slice-wide PJRT mesh if the
    backend provisioned one."""
    import os

    coord = os.environ.get("RAY_TPU_JAX_COORDINATOR")
    if not coord:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(os.environ["RAY_TPU_JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["RAY_TPU_JAX_PROCESS_ID"]))


class DataParallelTrainer:
    """Runs `train_loop_per_worker` on a gang of workers."""

    backend = None

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 datasets: Optional[dict] = None):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint
        self.datasets = datasets or {}

    def fit(self, _in_process: bool = False) -> Result:
        resume = (self.resume_from_checkpoint.path
                  if self.resume_from_checkpoint else None)
        if _in_process or not ray_tpu.is_initialized():
            # local/debug mode: controller logic inline (reference
            # local_testing_mode analog); still uses real worker actors
            ray_tpu.init()
            logic = TrainControllerLogic(
                self.train_loop_per_worker, self.train_loop_config,
                self.scaling_config, self.run_config, backend=self.backend,
                resume_from=resume, datasets=self.datasets)
            out = logic.run()
        else:
            controller = TrainControllerActor.options(
                name=f"train-controller-{self.run_config.name or 'run'}"
                     f"-{id(self) & 0xffff:x}").remote()
            out = ray_tpu.get(controller.run.remote(
                self.train_loop_per_worker, self.train_loop_config,
                self.scaling_config, self.run_config, self.backend, resume,
                self.datasets),
                timeout=None)
            ray_tpu.kill(controller)
        result = Result(
            metrics=out["metrics"],
            checkpoint=(Checkpoint(out["checkpoint_path"])
                        if out["checkpoint_path"] else None),
            path=out["storage_path"],
            error=out["error"],
            restarts=out["restarts"],
            resizes=out.get("resizes", 0),
            fenced_restarts=out.get("fenced_restarts", 0),
            final_world_size=out.get("final_world_size"),
        )
        if out["state"] == "ERRORED":
            raise TrainingFailedError(out["error"])
        return result


class JaxTrainer(DataParallelTrainer):
    """SPMD JAX training over TPU workers (reference jax_trainer.py:19).

    With `scaling_config.use_tpu` and a `topology`, reserves a slice and
    gang-places one worker per host; each worker joins the PJRT mesh via
    `maybe_init_jax_distributed()` and runs the same pjit program.
    """

    def __init__(self, *args, jax_backend: Optional[JaxBackend] = None, **kw):
        super().__init__(*args, **kw)
        self.backend = jax_backend or JaxBackend()
