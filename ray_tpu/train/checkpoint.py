"""Checkpoint handle + top-K retention manager.

Parity with `python/ray/train/_checkpoint.py` (directory-handle Checkpoint
over fsspec storage) and
`train/v2/_internal/execution/checkpoint/checkpoint_manager.py` (top-K by
metric per CheckpointConfig) + `v2/_internal/execution/storage.py`
StorageContext (local→remote upload). `storage_path` may be a local/NFS
path or any fsspec URI (`gs://bucket/run1`, `memory://...` in tests): the
manager uploads worker-local checkpoint dirs and `as_directory()`
materializes remote checkpoints back to a local temp dir on demand.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

from ray_tpu.train.config import CheckpointConfig
from ray_tpu.utils import fs as _fs


class Checkpoint:
    """A handle to a directory of checkpoint files — local or remote
    (reference Checkpoint)."""

    def __init__(self, path: str):
        self.path = _fs.abspath(path)
        self._local_cache: Optional[str] = None

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def as_directory(self) -> str:
        """A local directory with the checkpoint contents; remote
        checkpoints download once per handle."""
        if not _fs.is_uri(self.path):
            return self.path
        if self._local_cache is None or not os.path.isdir(self._local_cache):
            self._local_cache = _fs.get_dir(
                self.path, tempfile.mkdtemp(prefix="ckpt_dl_"))
        return self._local_cache

    def to_directory(self, path: Optional[str] = None) -> str:
        dest = path or tempfile.mkdtemp(prefix="ckpt_")
        return _fs.get_dir(self.path, dest)

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


# ---------------------------------------------------------------------------
# World-size-agnostic sharded checkpoints.
#
# Layout of a sharded checkpoint directory:
#   manifest_p<process>.json   one per saving process
#   shards_p<process>.npz      that process's chunks, keyed "<leaf>::<i>"
#
# The manifest records each parameter's GLOBAL shape/dtype plus, per chunk,
# the global index window it covers — so a checkpoint saved at world size W
# restores at any other world size: the reader gathers chunks into full
# arrays (gather-on-restore) and the caller reshards them onto whatever
# mesh the surviving capacity supports (train/spmd.py restore_state_sharded).
# This is the portable-resharding half of the array-redistribution direction
# in PAPERS.md, specialized to checkpoint round-trips.
# ---------------------------------------------------------------------------

SHARDED_FORMAT = "ray_tpu.sharded_ckpt.v1"


def _leaf_key(key_path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in key_path)


def _chunk_windows(arr) -> List[tuple]:
    """[(index_window, numpy_chunk)] covering `arr`'s addressable data.

    `index_window` is [[start, stop], ...] per dim in GLOBAL coordinates.
    Replicated shards (several devices holding the same window) are
    deduplicated; a plain numpy/unsharded array is one full-cover chunk.
    """
    import numpy as np

    shards = getattr(arr, "addressable_shards", None)
    shape = tuple(getattr(arr, "shape", np.shape(arr)))
    if not shards:
        return [([[0, s] for s in shape], np.asarray(arr))]
    seen = {}
    for shard in shards:
        window = []
        for dim, sl in enumerate(shard.index):
            start = 0 if sl.start is None else int(sl.start)
            stop = shape[dim] if sl.stop is None else int(sl.stop)
            window.append([start, stop])
        key = tuple((a, b) for a, b in window)
        if key not in seen:
            seen[key] = (window, np.asarray(shard.data))
    return list(seen.values())


def save_sharded(tree: Any, path: str, *, step: int = 0,
                 world_size: int = 1, process_index: int = 0,
                 extra: Optional[Dict[str, Any]] = None) -> str:
    """Per-parameter save of a (possibly mesh-sharded) pytree.

    Every process of a multi-host job calls this with its own
    `process_index`; each writes only the chunks it can address, so no
    host ever materializes another host's parameters. Single-process
    callers (CI's virtual-device meshes) write the full set.
    """
    import jax
    import numpy as np

    _fs.makedirs(path)
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    params: Dict[str, Any] = {}
    chunks: List[dict] = []
    blobs: Dict[str, Any] = {}
    for kp, leaf in leaves:
        key = _leaf_key(kp)
        arr_windows = _chunk_windows(leaf)
        np_dtype = np.asarray(arr_windows[0][1]).dtype
        params[key] = {"shape": list(np.shape(leaf)),
                       "dtype": np_dtype.name}
        for i, (window, data) in enumerate(arr_windows):
            blob_key = f"{key}::{i}"
            blobs[blob_key] = data
            chunks.append({"leaf": key, "blob": blob_key, "index": window})
    manifest = {"format": SHARDED_FORMAT, "step": int(step),
                "world_size": int(world_size),
                "process_index": int(process_index),
                "params": params, "chunks": chunks, **(extra or {})}
    import io

    buf = io.BytesIO()
    np.savez(buf, **blobs)
    with _fs.open(_fs.join(path, f"shards_p{process_index:05d}.npz"),
                  "wb") as f:
        f.write(buf.getvalue())
    with _fs.open(_fs.join(path, f"manifest_p{process_index:05d}.json"),
                  "w") as f:
        json.dump(manifest, f)
    return path


def is_sharded_checkpoint(path: str) -> bool:
    return _fs.exists(_fs.join(path, "manifest_p00000.json"))


def _read_process_manifests(path: str) -> List[Dict[str, Any]]:
    manifests = []
    i = 0
    while True:
        mp = _fs.join(path, f"manifest_p{i:05d}.json")
        if not _fs.exists(mp):
            break
        with _fs.open(mp, "r") as f:
            manifests.append(json.load(f))
        i += 1
    if not manifests:
        raise FileNotFoundError(f"no sharded-checkpoint manifest in {path}")
    return manifests


def read_sharded_manifest(path: str) -> Dict[str, Any]:
    """The merged view across all saving processes (their global sections
    are identical; chunk lists concatenate)."""
    manifests = _read_process_manifests(path)
    merged = dict(manifests[0])
    merged["chunks"] = [c for m in manifests for c in m["chunks"]]
    merged["num_save_processes"] = len(manifests)
    return merged


def load_sharded(path: str) -> tuple:
    """Gather-on-restore: returns ({leaf_key: np.ndarray}, manifest) with
    every parameter assembled to its GLOBAL shape, regardless of the
    world size / mesh it was saved under."""
    import numpy as np

    import io

    manifests = _read_process_manifests(path)
    manifest = dict(manifests[0])
    manifest["chunks"] = [c for m in manifests for c in m["chunks"]]
    manifest["num_save_processes"] = len(manifests)
    out: Dict[str, Any] = {}
    windows: Dict[str, set] = {}   # leaf -> distinct index windows written
    for p, proc_manifest in enumerate(manifests):
        with _fs.open(_fs.join(path, f"shards_p{p:05d}.npz"), "rb") as f:
            blob = io.BytesIO(f.read())
        with np.load(blob) as z:
            # ONLY this process's chunk list: blob keys ("<leaf>::<i>")
            # repeat across processes, so matching the merged list against
            # z.files would write one process's data into every process's
            # windows
            for chunk in proc_manifest["chunks"]:
                if chunk["blob"] not in z.files:
                    raise ValueError(
                        f"shards_p{p:05d}.npz is missing {chunk['blob']} "
                        f"declared by its manifest")
                key = chunk["leaf"]
                spec = manifest["params"][key]
                if key not in out:
                    out[key] = np.empty(tuple(spec["shape"]),
                                        dtype=_np_dtype(spec["dtype"]))
                    windows[key] = set()
                window = tuple(slice(a, b) for a, b in chunk["index"])
                data = z[chunk["blob"]]
                if out[key][window].shape != data.shape:
                    raise ValueError(
                        f"chunk {chunk['blob']}: window {chunk['index']} "
                        f"does not match data shape {data.shape}")
                # replicated windows may arrive from several processes;
                # last write wins (bitwise-identical by contract)
                out[key][window] = data
                windows[key].add(tuple((a, b) for a, b in chunk["index"]))
    for key, spec in manifest["params"].items():
        if key not in out or not _windows_cover(windows[key],
                                                tuple(spec["shape"])):
            raise ValueError(
                f"sharded checkpoint {path} is missing data for {key!r} "
                f"(windows {sorted(windows.get(key, ()))} do not cover "
                f"shape {spec['shape']})")
    return out, manifest


class _LazyNpz:
    """Row-range reads from an UNCOMPRESSED npz (what `save_sharded`
    writes: `np.savez` stores members, it does not deflate them): the
    npy header of a member is parsed once, after which any leading-dim
    row range seek-reads straight out of the zip — no blob ever
    materializes whole. (A compressed member would still read correctly:
    `ZipExtFile.seek` decompresses forward, trading speed, not memory.)
    """

    def __init__(self, path: str):
        self._path = path
        self._zf = None
        self._meta: Dict[str, tuple] = {}  # member -> (shape, dtype, off)

    def _zip(self):
        import zipfile

        if self._zf is None:
            self._zf = zipfile.ZipFile(_fs.open(self._path, "rb"))
        return self._zf

    def _header(self, name: str) -> tuple:
        import numpy as np

        if name not in self._meta:
            with self._zip().open(name + ".npy") as f:
                version = np.lib.format.read_magic(f)
                if version == (1, 0):
                    shape, fortran, dtype = (
                        np.lib.format.read_array_header_1_0(f))
                else:
                    shape, fortran, dtype = (
                        np.lib.format.read_array_header_2_0(f))
                if fortran:
                    raise ValueError(
                        f"{name}: fortran-order member has no row-major "
                        f"row ranges; save_sharded never writes these")
                self._meta[name] = (tuple(shape), dtype, f.tell())
        return self._meta[name]

    def read_rows(self, name: str, r0: int, r1: int):
        """Rows [r0, r1) of member `name`'s leading dim (the full scalar
        for 0-d members)."""
        import numpy as np

        shape, dtype, off = self._header(name)
        if not shape:
            with self._zip().open(name + ".npy") as f:
                f.seek(off)
                return np.frombuffer(f.read(dtype.itemsize),
                                     dtype).reshape(())
        row = int(np.prod(shape[1:], dtype=np.int64)) * dtype.itemsize
        with self._zip().open(name + ".npy") as f:
            f.seek(off + r0 * row)
            buf = f.read((r1 - r0) * row)
        return np.frombuffer(buf, dtype).reshape((r1 - r0,) + shape[1:])


def open_sharded(path: str) -> tuple:
    """Lazy view of a sharded checkpoint: ({leaf_key: WindowedReader},
    merged manifest) with NO array data loaded. Each reader's
    `.read(window)` seek-reads only the intersecting rows of the
    intersecting chunk blobs, so the streaming restore path
    (`collective.reshard_streaming`, `restore_state_sharded` with
    `stream_chunk_bytes=`) holds chunk-scale host memory where
    `load_sharded` gathers O(model size). Coverage is validated up
    front, exactly like `load_sharded`."""
    from ray_tpu.util.collective.reshard import WindowedReader

    manifests = _read_process_manifests(path)
    manifest = dict(manifests[0])
    manifest["chunks"] = [c for m in manifests for c in m["chunks"]]
    manifest["num_save_processes"] = len(manifests)
    npzs = [_LazyNpz(_fs.join(path, f"shards_p{p:05d}.npz"))
            for p in range(len(manifests))]

    def _loader(key, r0, r1):
        proc, blob = key
        return npzs[proc].read_rows(blob, r0, r1)

    per_leaf: Dict[str, list] = {}
    windows: Dict[str, set] = {}
    for p, pm in enumerate(manifests):
        for chunk in pm["chunks"]:
            win = tuple((int(a), int(b)) for a, b in chunk["index"])
            per_leaf.setdefault(chunk["leaf"], []).append(
                (win, (p, chunk["blob"])))
            windows.setdefault(chunk["leaf"], set()).add(win)
    readers: Dict[str, Any] = {}
    for key, spec in manifest["params"].items():
        shape = tuple(spec["shape"])
        if key not in per_leaf or not _windows_cover(windows[key], shape):
            raise ValueError(
                f"sharded checkpoint {path} is missing data for {key!r} "
                f"(windows {sorted(windows.get(key, ()))} do not cover "
                f"shape {shape})")
        readers[key] = WindowedReader(shape, _np_dtype(spec["dtype"]),
                                      per_leaf[key], _loader)
    return readers, manifest


def _windows_cover(windows: set, shape: tuple) -> bool:
    """Whether axis-aligned index windows jointly cover `shape`, without
    materializing a per-element mask (restore-time memory matters: the
    gathered params already cost O(model size)). Full-cover and
    disjoint-tile layouts — everything real shardings produce — resolve
    by volume bookkeeping; genuinely overlapping partial windows fall
    back to a coordinate-grid check over the distinct boundaries."""
    import math

    total = math.prod(shape) if shape else 1
    if not shape:
        return bool(windows)
    full = tuple((0, s) for s in shape)
    if full in windows:
        return True

    def volume(w):
        return math.prod(b - a for a, b in w)

    def overlaps(w1, w2):
        return all(a1 < b2 and a2 < b1
                   for (a1, b1), (a2, b2) in zip(w1, w2))

    wins = sorted(windows)
    disjoint = all(not overlaps(wins[i], wins[j])
                   for i in range(len(wins)) for j in range(i + 1, len(wins)))
    if disjoint:
        return sum(volume(w) for w in wins) >= total
    # overlapping partial windows: exact cover via the boundary grid —
    # every grid cell (product of distinct per-axis intervals) must fall
    # inside some window. Grid size is O(prod windows-per-axis), tiny
    # next to element counts.
    axes_cuts = []
    for dim, size in enumerate(shape):
        cuts = {0, size}
        for w in wins:
            cuts.update(w[dim])
        axes_cuts.append(sorted(cuts))
    from itertools import product as _product

    for cell in _product(*([(lo, hi) for lo, hi in zip(cs, cs[1:])]
                           for cs in axes_cuts)):
        if not any(all(a <= lo and hi <= b
                       for (lo, hi), (a, b) in zip(cell, w))
                   for w in wins):
            return False
    return True


def _np_dtype(name: str):
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


class CheckpointManager:
    """Persists reported checkpoints under storage_path, keeps top-K."""

    def __init__(self, storage_path: str, config: Optional[CheckpointConfig] = None):
        self.storage_path = storage_path
        self.config = config or CheckpointConfig()
        self.tracked: List[Dict[str, Any]] = []  # {path, metrics, index}
        self._index = 0
        _fs.makedirs(storage_path)

    def register(self, checkpoint: Checkpoint,
                 metrics: Optional[Dict[str, Any]] = None) -> Checkpoint:
        """Copy/upload a worker-local checkpoint into durable storage;
        evict per top-K policy. Returns the durable handle."""
        self._index += 1
        dest = _fs.join(self.storage_path, f"checkpoint_{self._index:06d}")
        if _fs.abspath(checkpoint.path) != dest:
            _fs.put_dir(checkpoint.as_directory(), dest)
        entry = {"path": dest, "metrics": metrics or {}, "index": self._index,
                 "time": time.time()}
        self.tracked.append(entry)
        self._write_manifest()
        self._evict()
        return Checkpoint(dest)

    def _score(self, entry) -> float:
        attr = self.config.checkpoint_score_attribute
        if attr is None:
            return entry["index"]  # recency
        v = entry["metrics"].get(attr)
        if v is None:
            return float("-inf")
        return float(v) if self.config.checkpoint_score_order == "max" else -float(v)

    def _evict(self) -> None:
        k = self.config.num_to_keep
        if k is None or len(self.tracked) <= k:
            return
        self.tracked.sort(key=self._score, reverse=True)
        for entry in self.tracked[k:]:
            _fs.rmtree(entry["path"], ignore_errors=True)
        self.tracked = self.tracked[:k]
        self._write_manifest()

    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self.tracked:
            return None
        return Checkpoint(max(self.tracked, key=self._score)["path"])

    def latest_checkpoint(self) -> Optional[Checkpoint]:
        if not self.tracked:
            return None
        return Checkpoint(max(self.tracked, key=lambda e: e["index"])["path"])

    def _write_manifest(self) -> None:
        manifest = _fs.join(self.storage_path, "checkpoints.json")
        with _fs.open(manifest, "w") as f:
            json.dump([{k: v for k, v in e.items()} for e in self.tracked], f)

    @classmethod
    def restore(cls, storage_path: str,
                config: Optional[CheckpointConfig] = None) -> "CheckpointManager":
        mgr = cls(storage_path, config)
        manifest = _fs.join(storage_path, "checkpoints.json")
        if _fs.exists(manifest):
            with _fs.open(manifest, "r") as f:
                mgr.tracked = [e for e in json.load(f)
                               if _fs.isdir(e["path"])]
            mgr._index = max((e["index"] for e in mgr.tracked), default=0)
        return mgr
