"""Checkpoint handle + top-K retention manager.

Parity with `python/ray/train/_checkpoint.py` (directory-handle Checkpoint
over fsspec storage) and
`train/v2/_internal/execution/checkpoint/checkpoint_manager.py` (top-K by
metric per CheckpointConfig) + `v2/_internal/execution/storage.py`
StorageContext (local→remote upload). `storage_path` may be a local/NFS
path or any fsspec URI (`gs://bucket/run1`, `memory://...` in tests): the
manager uploads worker-local checkpoint dirs and `as_directory()`
materializes remote checkpoints back to a local temp dir on demand.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

from ray_tpu.train.config import CheckpointConfig
from ray_tpu.utils import fs as _fs


class Checkpoint:
    """A handle to a directory of checkpoint files — local or remote
    (reference Checkpoint)."""

    def __init__(self, path: str):
        self.path = _fs.abspath(path)
        self._local_cache: Optional[str] = None

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def as_directory(self) -> str:
        """A local directory with the checkpoint contents; remote
        checkpoints download once per handle."""
        if not _fs.is_uri(self.path):
            return self.path
        if self._local_cache is None or not os.path.isdir(self._local_cache):
            self._local_cache = _fs.get_dir(
                self.path, tempfile.mkdtemp(prefix="ckpt_dl_"))
        return self._local_cache

    def to_directory(self, path: Optional[str] = None) -> str:
        dest = path or tempfile.mkdtemp(prefix="ckpt_")
        return _fs.get_dir(self.path, dest)

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


class CheckpointManager:
    """Persists reported checkpoints under storage_path, keeps top-K."""

    def __init__(self, storage_path: str, config: Optional[CheckpointConfig] = None):
        self.storage_path = storage_path
        self.config = config or CheckpointConfig()
        self.tracked: List[Dict[str, Any]] = []  # {path, metrics, index}
        self._index = 0
        _fs.makedirs(storage_path)

    def register(self, checkpoint: Checkpoint,
                 metrics: Optional[Dict[str, Any]] = None) -> Checkpoint:
        """Copy/upload a worker-local checkpoint into durable storage;
        evict per top-K policy. Returns the durable handle."""
        self._index += 1
        dest = _fs.join(self.storage_path, f"checkpoint_{self._index:06d}")
        if _fs.abspath(checkpoint.path) != dest:
            _fs.put_dir(checkpoint.as_directory(), dest)
        entry = {"path": dest, "metrics": metrics or {}, "index": self._index,
                 "time": time.time()}
        self.tracked.append(entry)
        self._write_manifest()
        self._evict()
        return Checkpoint(dest)

    def _score(self, entry) -> float:
        attr = self.config.checkpoint_score_attribute
        if attr is None:
            return entry["index"]  # recency
        v = entry["metrics"].get(attr)
        if v is None:
            return float("-inf")
        return float(v) if self.config.checkpoint_score_order == "max" else -float(v)

    def _evict(self) -> None:
        k = self.config.num_to_keep
        if k is None or len(self.tracked) <= k:
            return
        self.tracked.sort(key=self._score, reverse=True)
        for entry in self.tracked[k:]:
            _fs.rmtree(entry["path"], ignore_errors=True)
        self.tracked = self.tracked[:k]
        self._write_manifest()

    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self.tracked:
            return None
        return Checkpoint(max(self.tracked, key=self._score)["path"])

    def latest_checkpoint(self) -> Optional[Checkpoint]:
        if not self.tracked:
            return None
        return Checkpoint(max(self.tracked, key=lambda e: e["index"])["path"])

    def _write_manifest(self) -> None:
        manifest = _fs.join(self.storage_path, "checkpoints.json")
        with _fs.open(manifest, "w") as f:
            json.dump([{k: v for k, v in e.items()} for e in self.tracked], f)

    @classmethod
    def restore(cls, storage_path: str,
                config: Optional[CheckpointConfig] = None) -> "CheckpointManager":
        mgr = cls(storage_path, config)
        manifest = _fs.join(storage_path, "checkpoints.json")
        if _fs.exists(manifest):
            with _fs.open(manifest, "r") as f:
                mgr.tracked = [e for e in json.load(f)
                               if _fs.isdir(e["path"])]
            mgr._index = max((e["index"] for e in mgr.tracked), default=0)
        return mgr
