"""Checkpoint handle + top-K retention manager.

Parity with `python/ray/train/_checkpoint.py` (directory-handle Checkpoint)
and `train/v2/_internal/execution/checkpoint/checkpoint_manager.py` (top-K by
metric per CheckpointConfig). Storage is a local/NFS path; TPU jobs write
orbax/msgpack files into the directory — the framework only moves bytes.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional

from ray_tpu.train.config import CheckpointConfig


class Checkpoint:
    """A handle to a directory of checkpoint files (reference Checkpoint)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def as_directory(self) -> str:
        return self.path

    def to_directory(self, path: Optional[str] = None) -> str:
        dest = path or tempfile.mkdtemp(prefix="ckpt_")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


class CheckpointManager:
    """Persists reported checkpoints under storage_path, keeps top-K."""

    def __init__(self, storage_path: str, config: Optional[CheckpointConfig] = None):
        self.storage_path = storage_path
        self.config = config or CheckpointConfig()
        self.tracked: List[Dict[str, Any]] = []  # {path, metrics, index}
        self._index = 0
        os.makedirs(storage_path, exist_ok=True)

    def register(self, checkpoint: Checkpoint,
                 metrics: Optional[Dict[str, Any]] = None) -> Checkpoint:
        """Copy a worker-local checkpoint into durable storage; evict per
        top-K policy. Returns the durable handle."""
        self._index += 1
        dest = os.path.join(self.storage_path, f"checkpoint_{self._index:06d}")
        if os.path.abspath(checkpoint.path) != dest:
            shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
        entry = {"path": dest, "metrics": metrics or {}, "index": self._index,
                 "time": time.time()}
        self.tracked.append(entry)
        self._write_manifest()
        self._evict()
        return Checkpoint(dest)

    def _score(self, entry) -> float:
        attr = self.config.checkpoint_score_attribute
        if attr is None:
            return entry["index"]  # recency
        v = entry["metrics"].get(attr)
        if v is None:
            return float("-inf")
        return float(v) if self.config.checkpoint_score_order == "max" else -float(v)

    def _evict(self) -> None:
        k = self.config.num_to_keep
        if k is None or len(self.tracked) <= k:
            return
        self.tracked.sort(key=self._score, reverse=True)
        for entry in self.tracked[k:]:
            shutil.rmtree(entry["path"], ignore_errors=True)
        self.tracked = self.tracked[:k]
        self._write_manifest()

    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self.tracked:
            return None
        return Checkpoint(max(self.tracked, key=self._score)["path"])

    def latest_checkpoint(self) -> Optional[Checkpoint]:
        if not self.tracked:
            return None
        return Checkpoint(max(self.tracked, key=lambda e: e["index"])["path"])

    def _write_manifest(self) -> None:
        manifest = os.path.join(self.storage_path, "checkpoints.json")
        with open(manifest, "w") as f:
            json.dump([{k: v for k, v in e.items()} for e in self.tracked], f)

    @classmethod
    def restore(cls, storage_path: str,
                config: Optional[CheckpointConfig] = None) -> "CheckpointManager":
        mgr = cls(storage_path, config)
        manifest = os.path.join(storage_path, "checkpoints.json")
        if os.path.exists(manifest):
            with open(manifest) as f:
                mgr.tracked = [e for e in json.load(f)
                               if os.path.isdir(e["path"])]
            mgr._index = max((e["index"] for e in mgr.tracked), default=0)
        return mgr
