"""Streaming dataset ingest for trainers (reference
`ray.train.get_dataset_shard` + streaming_split semantics, made
elastic-safe).

A `DatasetShard` is one worker's view of a dataset passed to a trainer
via `datasets={...}`. The contract is GLOBAL-BATCH deterministic:

- global batch i is the same rows at every world size (the dataset's
  deterministic order re-batched at `batch_size`);
- rank r of a world-w gang receives the row window
  [r * per, (r + 1) * per) of each global batch (per = batch_size // w),
  so the union across ranks is exactly the global batch — the usual
  data-parallel sharding of a fixed global batch shape (static XLA
  shapes survive a resize).

Elastic resize semantics (the continuous-ingest drill): the controller
rebuilds every rank's shard with the new (rank, world) on each
generation; a train fn that checkpoints its step and resumes with
`start_batch=<resumed step>` consumes exactly one global batch per step
— across a mid-stream shrink or regrow, no batch is duplicated and none
is dropped, because batch identity is the global index, not the worker.

The underlying stream re-executes the pipeline from the source on each
(re)start and skips already-consumed batches; sources must therefore be
re-executable (read thunks / lineage-recoverable refs) — which is also
what the pipeline's own fault tolerance requires.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional


class DatasetShard:
    """One worker's elastic-safe view of a trainer dataset."""

    def __init__(self, dataset, rank: int, world_size: int):
        self._dataset = dataset
        self.rank = int(rank)
        self.world_size = max(int(world_size), 1)

    def iter_batches(self, *, batch_size: int, start_batch: int = 0,
                     batch_format: str = "numpy") -> Iterator[Any]:
        """Yield this rank's slice of every global batch from
        `start_batch` on. `batch_size` is the GLOBAL batch size and must
        divide evenly across the gang (static per-rank shapes)."""
        for _, batch in self.iter_global_batches(
                batch_size=batch_size, start_batch=start_batch,
                batch_format=batch_format):
            yield batch

    def iter_global_batches(self, *, batch_size: int, start_batch: int = 0,
                            batch_format: str = "numpy") -> Iterator[tuple]:
        """(global_index, rank slice) pairs — for train loops that key
        their step bookkeeping off the batch identity.

        Trailing partial global batches are DROPPED by construction: the
        fixed [rank*per, (rank+1)*per) windows of a short batch would
        hand ranks unequal (even empty) slices — exactly the ragged
        shapes an SPMD step cannot take — so there is no drop_last
        knob to get that wrong with."""
        if batch_size % self.world_size:
            raise ValueError(
                f"global batch_size {batch_size} must divide across "
                f"world_size {self.world_size}")
        per = batch_size // self.world_size
        lo, hi = self.rank * per, (self.rank + 1) * per
        for gi, batch in enumerate(self._dataset.iter_batches(
                batch_size=batch_size, batch_format=batch_format,
                drop_last=True)):
            if gi < start_batch:
                continue
            yield gi, self._slice(batch, lo, hi)

    @staticmethod
    def _slice(batch: Any, lo: int, hi: int) -> Any:
        if isinstance(batch, dict):
            return {k: v[lo:hi] for k, v in batch.items()}
        return batch[lo:hi]

    def __repr__(self):
        return (f"DatasetShard(rank={self.rank}/"
                f"{self.world_size}, {self._dataset!r})")


def build_shards(datasets: Optional[Dict[str, Any]], rank: int,
                 world_size: int) -> Dict[str, DatasetShard]:
    """Per-rank shard map for one worker-group generation (rebuilt on
    every elastic restart so rank/world stay current)."""
    return {name: DatasetShard(ds, rank, world_size)
            for name, ds in (datasets or {}).items()}
