"""TrainWorker actor + WorkerGroup.

Parity with `python/ray/train/v2/_internal/execution/worker_group/
worker_group.py:103` (actor group creation w/ PGs, poll_status) and
`worker.py`/`thread_runner.py` (train fn runs on a thread inside the actor).
TPU twist: workers of a multi-host job are gang-placed one-per-host on a
reserved slice via the slice-name label selector (SURVEY §3.4).
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train import session as session_lib
from ray_tpu.train.checkpoint import Checkpoint


@ray_tpu.remote
class TrainWorker:
    """Hosts the user train function on a thread; polled by the controller."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._ctx: Optional[session_lib.TrainContext] = None
        self._error: Optional[str] = None
        self._done = False

    def setup_and_start(self, train_fn, train_config, rank, world_size,
                        local_rank, node_rank, resume_checkpoint_path,
                        backend_env: Optional[Dict[str, str]] = None,
                        generation: int = 0, run_name: Optional[str] = None,
                        dataset_shards: Optional[dict] = None):
        import os

        from ray_tpu.util import tracing

        if backend_env:
            os.environ.update(backend_env)
        resume = (Checkpoint(resume_checkpoint_path)
                  if resume_checkpoint_path else None)
        self._generation = generation
        self._ctx = session_lib.TrainContext(
            rank=rank, world_size=world_size, local_rank=local_rank,
            node_rank=node_rank, resume_checkpoint=resume,
            generation=generation, run_name=run_name,
            dataset_shards=dataset_shards)
        # this actor call's execute span carries the driver's trace when
        # the driver traces: capture it NOW (the train thread outlives the
        # call) so per-step spans join the run's trace
        carrier = tracing.inject_context()

        def _run():
            session_lib._set_context(self._ctx)
            try:
                with tracing.adopt_context(carrier):
                    if train_config is None:
                        train_fn()
                    else:
                        train_fn(train_config)
            except StopIteration:
                pass
            except BaseException:
                self._error = traceback.format_exc()
            finally:
                session_lib._set_context(None)
                try:
                    # the controller kills this actor shortly after it
                    # polls done — flush synchronously BEFORE raising
                    # _done so the final steps' spans/telemetry provably
                    # beat the kill (the periodic pusher's next tick, or
                    # a post-done flush, would race it)
                    from ray_tpu.util import metrics as _m

                    _m.flush(wait=True)
                except Exception:
                    pass
                self._done = True

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name=f"train-rank{rank}")
        self._thread.start()
        return True

    def poll(self):
        """Drain new reports; reference worker_group.poll_status :488.
        Reports carry the group generation so a fenced group's late
        reports are distinguishable from the live gang's."""
        with self._ctx.lock:
            reports = self._ctx.reports
            self._ctx.reports = []
        return {"reports": reports, "done": self._done, "error": self._error,
                "generation": getattr(self, "_generation", 0)}

    def request_stop(self):
        if self._ctx is not None:
            self._ctx.stop_requested = True
        return True

    def node_id(self):
        return ray_tpu.get_runtime_context().node_id.hex()

    def node_ip(self):
        """IP other gang members can reach this worker's host on (used by
        backends that rendezvous on rank 0, e.g. torch MASTER_ADDR)."""
        import os
        import socket

        # Route toward the head when it is remote; head-spawned workers
        # have no RAY_TPU_HEAD_HOST (loopback), so fall back to the primary
        # outbound interface (UDP connect sends no packets).
        from ray_tpu.core import config as _config

        for target in (_config.get("head_host"), "8.8.8.8"):
            if not target or target.startswith("127."):
                continue
            try:
                with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                    s.connect((target, 1))
                    return s.getsockname()[0]
            except OSError:
                continue
        return "127.0.0.1"

    def rendezvous_info(self):
        """(reachable_ip, free_port) probed on THIS host — rendezvous ports
        must be chosen where they will actually be bound (rank 0's node),
        not on the controller."""
        import socket

        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
        return self.node_ip(), port

    def shutdown_worker(self):
        return True


class WorkerGroup:
    """Creates and tracks the gang of TrainWorker actors.

    Each group carries a monotonically increasing `generation` (set by
    the controller) — the train-level half of the fencing story: the
    cluster epoch fences a group against control-plane restarts; the
    generation scopes collective-group rendezvous names and tags every
    polled status, so a zombie member of a killed gang can neither
    rendezvous with its successor nor have its reports mistaken for the
    live gang's (checkpoints only enter run storage via the controller
    draining the group it currently polls).
    """

    def __init__(self, scaling_config, label_selector: Optional[dict] = None,
                 placement_group=None, generation: int = 0,
                 run_name: Optional[str] = None):
        self.scaling = scaling_config
        self.run_name = run_name
        self.label_selector = label_selector
        self.placement_group = placement_group
        self.generation = generation
        self.workers: List[Any] = []
        self.actor_ids: List[str] = []     # hex ids, index == rank
        self.node_ids: List[str] = []      # hex node of each worker

    def start(self, train_fn: Callable, train_config: Any,
              resume_checkpoint: Optional[Checkpoint] = None,
              backend=None, datasets: Optional[dict] = None) -> None:
        n = self.scaling.num_workers
        res = self.scaling.worker_resources()
        opts: Dict[str, Any] = {"resources": res, "num_cpus": res.get("CPU", 0)}
        if self.label_selector:
            opts["label_selector"] = self.label_selector
        if self.placement_group is not None:
            opts["placement_group"] = self.placement_group
        if self.scaling.placement_strategy in ("SPREAD", "STRICT_SPREAD"):
            opts["scheduling_strategy"] = "spread"
        self.workers = [TrainWorker.options(**opts).remote() for _ in range(n)]
        self.actor_ids = [w._actor_id.hex() for w in self.workers]
        backend_envs = (backend.worker_envs(self) if backend is not None
                        else [{} for _ in range(n)])
        from ray_tpu.train.ingest import build_shards

        starts = []
        for rank, w in enumerate(self.workers):
            starts.append(w.setup_and_start.remote(
                train_fn, train_config, rank, n, 0, rank,
                resume_checkpoint.path if resume_checkpoint else None,
                backend_envs[rank], self.generation, self.run_name,
                # per-generation shard map: rebuilt with the CURRENT
                # (rank, world) so an elastic resize re-splits the
                # stream without duplicating or dropping global batches
                build_shards(datasets, rank, n)))
        ray_tpu.get(starts, timeout=120)
        # node placement, recorded for the controller's death watch
        # (a node_state DEAD event for any of these hosts fails the
        # group immediately, without waiting for a poll RPC to time out)
        self.node_ids = ray_tpu.get(
            [w.node_id.remote() for w in self.workers], timeout=60)

    def poll(self) -> List[dict]:
        return ray_tpu.get([w.poll.remote() for w in self.workers], timeout=60)

    def request_stop_all(self) -> None:
        """Ask every worker to stop at its next report — the graceful
        (checkpoint-boundary) half of an elastic resize. Best-effort:
        a worker that died since the last poll is already stopping."""
        refs = []
        for w in self.workers:
            try:
                refs.append(w.request_stop.remote())
            except Exception:
                pass
        try:
            ray_tpu.get(refs, timeout=30)
        except Exception:
            pass

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
